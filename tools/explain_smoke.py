#!/usr/bin/env python3
"""Explainability smoke (tools/verify.sh): schedule a mixed
feasible/infeasible batch through the LIVE kernel scheduler and prove the
decision ledger's four surfaces agree.

Asserts, from the exported surfaces only:

1. every feasible pod binds via the kernel path and its ledger record
   (served over HTTP at /explainz) names the node it actually landed on;
2. the seeded-unschedulable pod gets a reference-style breakdown
   ("0/N nodes are available: ...") that is IDENTICAL across the
   Unschedulable condition, the FailedScheduling event, and /explainz;
3. scheduler_unschedulable_reasons_total{predicate} is live on /metrics.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return json.loads(resp.read())


def main() -> int:
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.scheduler.factory import ConfigFactory
    from kubernetes_tpu.utils.debugserver import DebugServer

    server = APIServer().start()
    factory = sched = debug = None
    try:
        client = RESTClient.for_server(server, user_agent="explain-smoke")
        for i in range(3):
            client.create("nodes", api.Node(
                metadata=api.ObjectMeta(
                    name=f"n{i}",
                    labels={api.LABEL_HOSTNAME: f"n{i}", "disk": "ssd"}),
                status=api.NodeStatus(
                    allocatable={"cpu": "4", "memory": "8Gi", "pods": "110"},
                    conditions=[api.NodeCondition(type="Ready",
                                                  status="True")])))

        def pod(name, cpu="100m", selector=None):
            return api.Pod(
                metadata=api.ObjectMeta(name=name, namespace="default"),
                spec=api.PodSpec(
                    node_selector=selector,
                    containers=[api.Container(
                        name="c", image="pause",
                        resources=api.ResourceRequirements(
                            requests={"cpu": cpu, "memory": "100Mi"}))]))

        for i in range(4):
            client.create("pods", pod(f"fits-{i}"))
        client.create("pods", pod("nofit", selector={"disk": "nvme"}))

        factory = ConfigFactory(client)
        factory.run(timeout=60)
        sched = factory.create_batch_from_provider(batch_size=32).run()
        debug = DebugServer(port=0, healthz=sched.healthy).start()

        # wait: 4 binds + an Unschedulable condition on the seeded pod
        deadline = time.monotonic() + 60
        bound, cond = [], None
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            bound = [p for p in pods if p.spec and p.spec.node_name]
            nofit = next(p for p in pods if p.metadata.name == "nofit")
            cond = next((c for c in ((nofit.status.conditions or [])
                                     if nofit.status else [])
                         if c.type == api.POD_SCHEDULED
                         and c.status == api.CONDITION_FALSE), None)
            if len(bound) >= 4 and cond is not None:
                break
            time.sleep(0.05)
        if len(bound) < 4 or cond is None:
            print(f"explain_smoke: bound={len(bound)}/4 cond={cond}",
                  file=sys.stderr)
            return 1
        if sched.kernel_failures:
            print(f"explain_smoke: kernel fell back ({sched.health}: "
                  f"{sched.disabled_reason})", file=sys.stderr)
            return 1

        want = cond.message or ""
        if not want.startswith("0/3 nodes are available:") \
                or "MatchNodeSelector" not in want:
            print(f"explain_smoke: condition message not a breakdown: "
                  f"{want!r}", file=sys.stderr)
            return 1

        # surface 2: the FailedScheduling event carries the same breakdown
        # (the recorder posts async — poll, don't sample)
        deadline = time.monotonic() + 30
        failed = []
        while time.monotonic() < deadline:
            evs, _ = client.list(
                "events", "default",
                field_selector="involvedObject.kind=Pod,"
                               "involvedObject.name=nofit")
            failed = [e for e in evs if e.reason == "FailedScheduling"]
            if any(e.message == want for e in failed):
                break
            time.sleep(0.05)
        if not any(e.message == want for e in failed):
            print(f"explain_smoke: FailedScheduling event mismatch: "
                  f"{[e.message for e in failed]!r} != {want!r}",
                  file=sys.stderr)
            return 1

        # surface 3: /explainz over live HTTP
        z = _get_json(debug.port, "/explainz?pod=default/nofit")
        dec = z.get("decision") or {}
        if dec.get("reason") != want:
            print(f"explain_smoke: /explainz reason mismatch: "
                  f"{dec.get('reason')!r} != {want!r}", file=sys.stderr)
            return 1
        for p in bound:
            z = _get_json(debug.port,
                          f"/explainz?pod=default/{p.metadata.name}")
            node = (z.get("decision") or {}).get("node")
            if node != p.spec.node_name:
                print(f"explain_smoke: ledger says {p.metadata.name} -> "
                      f"{node}, bound to {p.spec.node_name}", file=sys.stderr)
                return 1

        # surface 4: the reasons counter is scraped off /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{debug.port}/metrics", timeout=5) as resp:
            metrics = resp.read().decode()
        if ('scheduler_unschedulable_reasons_total{'
                'predicate="MatchNodeSelector"}') not in metrics:
            print("explain_smoke: reasons counter missing from /metrics",
                  file=sys.stderr)
            return 1

        print(f"explain_smoke: OK — 4 bound with ledger records, "
              f"breakdown agrees across condition/event/explainz: {want!r}")
        return 0
    finally:
        if debug is not None:
            debug.stop()
        if sched is not None:
            sched.stop()
        if factory is not None:
            factory.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
