#!/usr/bin/env python3
"""Scheduling-objectives smoke (tools/verify.sh): run the LIVE kernel
scheduler under the gang_preempt objective and prove the objective
subsystem end to end:

1. a gang of pods binds all-or-nothing onto ONE topology domain (zone);
2. a high-priority pod with zero feasible nodes forces a preemption: the
   victim is evicted through the apiserver and gets a reference-style
   Preempted Event, and the preemptor eventually binds;
3. the preemptor's FailedScheduling event, its Unschedulable condition,
   and its /explainz decision all carry the SAME nomination sentence
   (nominated node + victims) — the four-surface agreement contract;
4. scheduler_preemptions_total / scheduler_gang_placements_total are live
   on /metrics.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return json.loads(resp.read())


def main() -> int:
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.scheduler.factory import ConfigFactory
    from kubernetes_tpu.scheduler.objectives.config import (
        GANG_LABEL, PRIORITY_ANNOTATION,
    )
    from kubernetes_tpu.utils.debugserver import DebugServer

    server = APIServer().start()
    factory = sched = debug = None
    try:
        client = RESTClient.for_server(server, user_agent="objectives-smoke")
        for i in range(4):
            client.create("nodes", api.Node(
                metadata=api.ObjectMeta(
                    name=f"n{i}",
                    labels={api.LABEL_HOSTNAME: f"n{i}",
                            api.LABEL_ZONE: f"z{i % 2}"}),
                status=api.NodeStatus(
                    allocatable={"cpu": "1", "memory": "4Gi", "pods": "8"},
                    conditions=[api.NodeCondition(type="Ready",
                                                  status="True")])))

        def pod(name, cpu, labels=None, ann=None):
            return api.Pod(
                metadata=api.ObjectMeta(name=name, namespace="default",
                                        labels=labels, annotations=ann),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": cpu, "memory": "64Mi"}))]))

        # a 2-pod gang + low-priority fillers that exhaust every node's cpu
        for i in range(2):
            client.create("pods", pod(f"gang-{i}", "300m",
                                      labels={GANG_LABEL: "train"}))
        for i in range(4):
            client.create("pods", pod(f"low-{i}", "600m",
                                      ann={PRIORITY_ANNOTATION: "1"}))

        factory = ConfigFactory(client)
        factory.run(timeout=60)
        sched = factory.create_batch_from_provider(
            batch_size=32, objective="gang_preempt").run()
        debug = DebugServer(port=0, healthz=sched.healthy).start()

        # phase 1: gang co-placed on one zone, fillers bound
        deadline = time.monotonic() + 60
        gang_nodes = {}
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            gang_nodes = {p.metadata.name: p.spec.node_name for p in pods
                          if p.spec and p.spec.node_name
                          and p.metadata.name.startswith("gang-")}
            bound = sum(1 for p in pods if p.spec and p.spec.node_name)
            if len(gang_nodes) == 2 and bound >= 5:
                break
            time.sleep(0.05)
        if len(gang_nodes) != 2:
            print(f"objectives_smoke: gang not placed: {gang_nodes}",
                  file=sys.stderr)
            return 1
        nodes_by_name, _ = client.list("nodes", "")
        zone_of = {n.metadata.name: (n.metadata.labels or {}).get(
            api.LABEL_ZONE) for n in nodes_by_name}
        zones = {zone_of[nd] for nd in gang_nodes.values()}
        if len(zones) != 1:
            print(f"objectives_smoke: gang split across zones: "
                  f"{gang_nodes} -> {zones}", file=sys.stderr)
            return 1
        if sched.kernel_failures:
            print(f"objectives_smoke: kernel fell back ({sched.health}: "
                  f"{sched.disabled_reason})", file=sys.stderr)
            return 1

        # phase 2: a high-priority near-whole-node pod forces preemption
        client.create("pods", pod("hi", "800m",
                                  ann={PRIORITY_ANNOTATION: "10"}))
        deadline = time.monotonic() + 60
        nominated = None
        while time.monotonic() < deadline:
            evs, _ = client.list(
                "events", "default",
                field_selector="involvedObject.kind=Pod,"
                               "involvedObject.name=hi")
            for e in evs:
                if e.reason == "FailedScheduling" \
                        and "nominated node" in (e.message or ""):
                    nominated = e.message
                    break
            if nominated:
                break
            time.sleep(0.05)
        if not nominated:
            print("objectives_smoke: no nominated FailedScheduling event",
                  file=sys.stderr)
            return 1

        # the ledger must carry the nomination decision with the SAME
        # sentence (the preemptor re-binds within ~a backoff period and its
        # latest-per-pod record moves on, so search the decision tail, not
        # just the latest record)
        z = _get_json(debug.port, "/explainz?n=256")
        nomination = None
        for dec in z.get("decisions") or []:
            if dec.get("pod") == "default/hi" and dec.get("preemption"):
                nomination = dec
        if nomination is None:
            print(f"objectives_smoke: no preemption decision for "
                  f"default/hi in /explainz tail", file=sys.stderr)
            return 1
        if nomination.get("reason") != nominated:
            print(f"objectives_smoke: /explainz reason mismatch:\n"
                  f"  explainz: {nomination.get('reason')!r}\n"
                  f"  event:    {nominated!r}", file=sys.stderr)
            return 1
        if not (nomination.get("preemption") or {}).get("victims"):
            print(f"objectives_smoke: /explainz decision carries no "
                  f"victims: {nomination!r}", file=sys.stderr)
            return 1

        # victim evicted + Preempted event; preemptor eventually binds
        deadline = time.monotonic() + 60
        preempted_ev, hi_bound = [], None
        while time.monotonic() < deadline:
            evs, _ = client.list("events", "default")
            preempted_ev = [e for e in evs if e.reason == "Preempted"]
            pods, _ = client.list("pods", "default")
            hi = next((p for p in pods if p.metadata.name == "hi"), None)
            hi_bound = hi.spec.node_name if hi and hi.spec else None
            if preempted_ev and hi_bound:
                break
            time.sleep(0.05)
        if not preempted_ev:
            print("objectives_smoke: no Preempted event on any victim",
                  file=sys.stderr)
            return 1
        if not hi_bound:
            print("objectives_smoke: preemptor never bound after eviction",
                  file=sys.stderr)
            return 1

        # the Unschedulable condition carried the same nomination while the
        # preemptor waited (it may have flipped to scheduled since — check
        # the recorded FailedScheduling matches what the condition said via
        # the event dedup identity: message equality was asserted above)

        # phase 3: objective counters live on /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{debug.port}/metrics", timeout=5) as resp:
            metrics = resp.read().decode()
        for needle in ('scheduler_preemptions_total{reason="evicted"}',
                       'scheduler_gang_placements_total{outcome="placed"}'):
            if needle not in metrics:
                print(f"objectives_smoke: {needle} missing from /metrics",
                      file=sys.stderr)
                return 1

        print(f"objectives_smoke: OK — gang co-placed in zone "
              f"{zones.pop()!r}, preemption evicted "
              f"{len(preempted_ev)} victim(s), hi bound to {hi_bound}; "
              f"event == /explainz: {nominated!r}")
        return 0
    finally:
        if debug is not None:
            debug.stop()
        if sched is not None:
            sched.stop()
        if factory is not None:
            factory.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
