#!/usr/bin/env bash
# kube-verify: the repo's pre-merge battery (the hack/verify-* analogue).
#
#   1. static analysis  — python -m kubernetes_tpu.analysis over the package
#                         (zero non-baselined findings or it fails)
#   2. tier-1 tests     — the full 'not slow' suite, which tests/conftest.py
#                         runs under the runtime race detectors (lock-order
#                         tracker + checked informer store); any recorded
#                         inversion or cache mutation fails the test that
#                         triggered it
#
# Usage: tools/verify.sh [--static-only|--tests-only]

set -euo pipefail
cd "$(dirname "$0")/.."

run_static=1
run_tests=1
case "${1:-}" in
  --static-only) run_tests=0 ;;
  --tests-only)  run_static=0 ;;
  "") ;;
  *) echo "usage: tools/verify.sh [--static-only|--tests-only]" >&2; exit 2 ;;
esac

if [ "$run_static" = 1 ]; then
  echo "== kube-verify static analysis =="
  python -m kubernetes_tpu.analysis kubernetes_tpu/
fi

if [ "$run_tests" = 1 ]; then
  echo "== tier-1 tests (race detectors on) =="
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

echo "verify: OK"
