#!/usr/bin/env bash
# kube-verify: the repo's pre-merge battery (the hack/verify-* analogue).
#
#   1. static analysis  — python -m kubernetes_tpu.analysis over the package
#                         (zero non-baselined findings or it fails)
#   2. tier-1 tests     — the full 'not slow' suite, which tests/conftest.py
#                         runs under the runtime race detectors (lock-order
#                         tracker + checked informer store); any recorded
#                         inversion or cache mutation fails the test that
#                         triggered it
#   2b. wave-parity smoke — tools/wave_smoke.py solves the full-carry
#                         smoke batch (and a gang_preempt batch) with the
#                         serial scan AND the wave-commit solver and exits
#                         1 unless every output (assignments, victims,
#                         gang verdicts, explain extras) is bit-identical
#
#   3. soak smoke       — a ~10 s kubemark churn soak through
#                         `bench.py --mode soak` (micro-batched arrivals
#                         via SOAK_MICROBATCH_MS, scraped SLIs, SLO
#                         verdicts, wedge detection), schema-checked by
#                         tools/check_soak.py — the steady-state bench path
#                         is exercised on every verify, not just on bench
#                         rounds
#   4. trace smoke      — tools/trace_smoke.py schedules one pod through a
#                         live apiserver and asserts the client span and
#                         the apiserver audit record share one trace id
#                         (the cross-process propagation contract)
#   5. wedge smoke      — a soak with a seeded kernel-stage hang MUST exit
#                         nonzero, report wedged:true, and ship a
#                         flight-recorder bundle; check_soak.py
#                         --expect-wedged schema-checks both
#
#   5b. leader-kill smoke — a ~15 s chaos soak against the replicated
#                         control plane (3-store quorum, 2 apiservers
#                         behind the discovery proxy): the storage leader
#                         and the primary apiserver are killed mid-churn;
#                         the run must finish with zero lost acked
#                         bindings, a recorded failover, member
#                         convergence, and a flight-recorder bundle —
#                         schema-checked by check_soak.py
#
#   6. explain smoke    — tools/explain_smoke.py schedules a mixed
#                         feasible/infeasible batch through the live kernel
#                         scheduler and asserts the per-predicate breakdown
#                         agrees across the Unschedulable condition, the
#                         FailedScheduling event, /explainz, and /metrics
#
#   7. objectives smoke — tools/objectives_smoke.py runs the live scheduler
#                         under gang_preempt: a gang co-places on one zone,
#                         a high-priority pod forces a preemption (victim
#                         evicted + Preempted Event), and the nomination
#                         sentence agrees across the FailedScheduling
#                         event, /explainz, and the objective counters on
#                         /metrics
#
# Usage: tools/verify.sh [--static-only|--tests-only|--soak-only|--trace-only|--explain-only|--objectives-only]

set -euo pipefail
cd "$(dirname "$0")/.."

run_static=1
run_tests=1
run_soak=1
run_trace=1
run_explain=1
run_objectives=1
case "${1:-}" in
  --static-only)  run_tests=0; run_soak=0; run_trace=0; run_explain=0; run_objectives=0 ;;
  --tests-only)   run_static=0; run_soak=0; run_trace=0; run_explain=0; run_objectives=0 ;;
  --soak-only)    run_static=0; run_tests=0; run_trace=0; run_explain=0; run_objectives=0 ;;
  --trace-only)   run_static=0; run_tests=0; run_soak=0; run_explain=0; run_objectives=0 ;;
  --explain-only) run_static=0; run_tests=0; run_soak=0; run_trace=0; run_objectives=0 ;;
  --objectives-only) run_static=0; run_tests=0; run_soak=0; run_trace=0; run_explain=0 ;;
  "") ;;
  *) echo "usage: tools/verify.sh [--static-only|--tests-only|--soak-only|--trace-only|--explain-only|--objectives-only]" >&2; exit 2 ;;
esac

if [ "$run_static" = 1 ]; then
  echo "== kube-verify static analysis =="
  python -m kubernetes_tpu.analysis kubernetes_tpu/
fi

if [ "$run_tests" = 1 ]; then
  echo "== tier-1 tests (race detectors on) =="
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

if [ "$run_soak" = 1 ]; then
  echo "== wave-parity smoke (serial vs wave commit, exact equality) =="
  JAX_PLATFORMS=cpu timeout -k 10 300 python tools/wave_smoke.py

  echo "== soak smoke (churn + micro-batch + scraped SLIs + schema check) =="
  soak_out="$(mktemp /tmp/soak-smoke.XXXXXX.json)"
  JAX_PLATFORMS=cpu SOAK_NODES=8 SOAK_RATE=40 SOAK_DURATION=4 \
    SOAK_SCRAPE_PERIOD=1 SOAK_BATCH=32 SOAK_MICROBATCH_MS=25 \
    timeout -k 10 300 python bench.py --mode soak > "$soak_out"
  python tools/check_soak.py "$soak_out"
  rm -f "$soak_out"

  echo "== wedge smoke (seeded hang -> wedged:true + flight-recorder bundle) =="
  wedge_out="$(mktemp /tmp/soak-wedge.XXXXXX.json)"
  if JAX_PLATFORMS=cpu SOAK_NODES=4 SOAK_RATE=20 SOAK_DURATION=3 \
      SOAK_SCRAPE_PERIOD=1 SOAK_BATCH=16 BENCH_SOAK_HANG_STAGE=solve \
      timeout -k 10 300 python bench.py --mode soak > "$wedge_out"; then
    echo "verify: seeded-hang soak exited 0 — the wedge was laundered" >&2
    exit 1
  fi
  python tools/check_soak.py --expect-wedged "$wedge_out"
  rm -f "$wedge_out"

  echo "== leader-kill smoke (3-store quorum + apiserver failover, zero lost binds) =="
  lk_out="$(mktemp /tmp/soak-leaderkill.XXXXXX.json)"
  JAX_PLATFORMS=cpu SOAK_NODES=8 SOAK_RATE=40 SOAK_DURATION=6 \
    SOAK_SCRAPE_PERIOD=1 SOAK_BATCH=32 \
    timeout -k 10 300 python bench.py --mode soak --scenario leader_kill \
    > "$lk_out"
  python tools/check_soak.py "$lk_out"
  rm -f "$lk_out"
fi

if [ "$run_trace" = 1 ]; then
  echo "== trace propagation smoke (client span <-> apiserver audit) =="
  JAX_PLATFORMS=cpu timeout -k 10 120 python tools/trace_smoke.py
fi

if [ "$run_explain" = 1 ]; then
  echo "== explain smoke (decision ledger: condition == event == /explainz) =="
  JAX_PLATFORMS=cpu timeout -k 10 180 python tools/explain_smoke.py
fi

if [ "$run_objectives" = 1 ]; then
  echo "== objectives smoke (gang placement + live preemption + surface agreement) =="
  JAX_PLATFORMS=cpu timeout -k 10 240 python tools/objectives_smoke.py
fi

echo "verify: OK"
