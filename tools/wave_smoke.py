#!/usr/bin/env python3
"""Wave-parity smoke (tools/verify.sh): the wave-commit solver must equal
the serial per-pod scan EXACTLY at the smoke shape — assignments, objective
outputs, and explain extras, bit for bit — or this exits 1.

Covers the default full-carry-surface batch (ports, disks, volumes,
inter-pod terms, sym/te tables) with explain on, plus a gang_preempt batch,
and asserts the wave count actually shrank the serial dimension.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from kubernetes_tpu.utils.platform import force_cpu
    force_cpu(device_count=1)

    import jax
    import numpy as np

    from kubernetes_tpu.ops.fixtures import feature_batch
    from kubernetes_tpu.ops.kernel import Weights, _schedule_jit, features_of

    def solve(ct, obj, explain, wave):
        import jax.numpy as jnp
        arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
        out = _schedule_jit(arrays, ct.n_zones, Weights(), features_of(ct),
                            explain, obj, wave)
        return jax.tree_util.tree_map(np.asarray, out)

    failures = []

    def compare(name, serial, wavey):
        ls = jax.tree_util.tree_flatten_with_path(serial)[0]
        lw = jax.tree_util.tree_flatten_with_path(wavey)[0]
        if len(ls) != len(lw):
            failures.append(f"{name}: output tree structure differs")
            return
        for (pa, va), (_pb, vb) in zip(ls, lw):
            if not np.array_equal(np.asarray(va), np.asarray(vb)):
                failures.append(
                    f"{name}: {jax.tree_util.keystr(pa)} differs")

    # 1) full-carry default batch, explain on
    ct = feature_batch(n_nodes=48, n_pods=32, with_existing=True)
    serial = solve(ct, None, True, 0)
    wavey, waves = solve(ct, None, True, 16)
    compare("default/explain", serial, wavey)
    if int(waves) >= ct.n_real_pods:
        failures.append(
            f"default/explain: wave_count {int(waves)} did not shrink the "
            f"serial dimension ({ct.n_real_pods} pods)")
    print(f"wave_smoke: default/explain waves={int(waves)} "
          f"pods={ct.n_real_pods}")

    # 2) gang_preempt batch (atomic interaction groups through the wave)
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.ops.tensorize import Tensorizer
    from kubernetes_tpu.scheduler.batch import make_plugin_args
    from kubernetes_tpu.scheduler.objectives.config import (
        GANG_LABEL, PRIORITY_ANNOTATION, gang_order, get_objective,
    )

    def mk_pod(name, cpu, labels=None, ann=None, node=""):
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace="default",
                                    labels=labels, annotations=ann),
            spec=api.PodSpec(node_name=node, containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(
                    requests={"cpu": cpu, "memory": "256Mi"}))]))

    nodes = [api.Node(
        metadata=api.ObjectMeta(
            name=f"n{i:02d}",
            labels={api.LABEL_HOSTNAME: f"n{i:02d}",
                    api.LABEL_ZONE: f"z{i % 4}"}),
        status=api.NodeStatus(
            allocatable={"cpu": "4", "memory": "16Gi", "pods": "16"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))
        for i in range(16)]
    existing = [mk_pod(f"e{i:02d}", "1500m", node=f"n{i % 16:02d}",
                       ann={PRIORITY_ANNOTATION: str(i % 2)})
                for i in range(32)]
    pending = []
    for i in range(24):
        labels, ann = {}, None
        if i % 3 == 0:
            labels[GANG_LABEL] = f"g{i // 9}"
        elif i % 5 == 1:
            ann = {PRIORITY_ANNOTATION: "7"}
        pending.append(mk_pod(f"p{i:02d}", "900m", labels=labels, ann=ann))
    obj = get_objective("gang_preempt")
    pending, _ = gang_order(pending)
    ct2 = Tensorizer(plugin_args=make_plugin_args(nodes),
                     objective=obj).build(nodes, existing, pending)
    serial2 = solve(ct2, obj, True, 0)
    wavey2, waves2 = solve(ct2, obj, True, 8)
    compare("gang_preempt/explain", serial2, wavey2)
    print(f"wave_smoke: gang_preempt/explain waves={int(waves2)} "
          f"pods={ct2.n_real_pods}")

    if failures:
        for f in failures:
            print(f"wave_smoke: FAIL {f}", file=sys.stderr)
        return 1
    print("wave_smoke: OK (wave == serial bit-for-bit)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
