#!/usr/bin/env python3
"""Schema check for `bench.py --mode soak` output (tools/verify.sh step 3).

Validates the report shape the soak smoke just emitted — stdlib only, no
jsonschema dependency. Exit 0 on a conforming report, 1 with one line per
violation otherwise. A `--expect-wedged` run inverts the wedge assertion
(used to prove the seeded-hang path stays honest) AND requires a
flight-recorder bundle: a wedged soak must ship its black box, and the
bundle itself is schema-checked (spans incl. a timed-out stage, audit
records, SLO verdicts in the trigger). `--bundle <path>` checks a bundle
file standalone.
"""

import json
import os
import sys


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def check(doc: dict, expect_wedged: bool) -> list:
    errs = []

    def need(obj, key, pred, where, desc):
        if not isinstance(obj, dict) or key not in obj:
            errs.append(f"{where}.{key}: missing")
        elif not pred(obj[key]):
            errs.append(f"{where}.{key}: {desc}, got {obj[key]!r}")

    need(doc, "metric",
         lambda v: isinstance(v, str) and "pods_scheduled_per_sec" in v,
         "$", "metric string naming pods_scheduled_per_sec")
    need(doc, "value", _is_num, "$", "number")
    need(doc, "unit", lambda v: v == "pods/s", "$", "'pods/s'")
    need(doc, "vs_baseline", _is_num, "$", "number")
    need(doc, "wedged", lambda v: isinstance(v, bool), "$", "bool")
    need(doc, "detail", lambda v: isinstance(v, dict), "$", "object")
    detail = doc.get("detail") or {}
    need(detail, "mode", lambda v: v == "soak", "detail", "'soak'")
    need(detail, "rounds", lambda v: isinstance(v, list) and v,
         "detail", "non-empty list")
    need(detail, "slos", lambda v: isinstance(v, list), "detail", "list")
    need(detail, "wedged", lambda v: isinstance(v, bool), "detail", "bool")
    need(detail, "config", lambda v: isinstance(v, dict), "detail", "object")

    def _reasons_ok(v) -> bool:
        return isinstance(v, dict) and all(
            isinstance(k, str) and _is_num(n) for k, n in v.items())

    gang_mode = (detail.get("config") or {}).get("scenario") == "gang_churn"
    for i, rnd in enumerate(detail.get("rounds") or []):
        where = f"detail.rounds[{i}]"
        need(rnd, "created", _is_num, where, "number")
        need(rnd, "bound_in_round", _is_num, where, "number")
        need(rnd, "slos", lambda v: isinstance(v, dict), where, "object")
        need(rnd, "unschedulable_reasons", _reasons_ok, where,
             "predicate -> count object (may be empty)")
        for key in ("pods_per_sec", "e2e_p50_seconds", "e2e_p99_seconds"):
            need(rnd, key, lambda v: v is None or _is_num(v), where,
                 "number or null (null = no samples, never fake zero)")
        if gang_mode:
            for key in ("preemptions", "gangs_placed", "gangs_rejected"):
                need(rnd, key, _is_num, where,
                     "number scraped off the objective counters")

    for i, slo in enumerate(detail.get("slos") or []):
        where = f"detail.slos[{i}]"
        need(slo, "name", lambda v: isinstance(v, str) and v, where, "name")
        need(slo, "verdict", lambda v: v in ("ok", "burning", "no_data"),
             where, "ok|burning|no_data")
        need(slo, "windows", lambda v: isinstance(v, list) and v, where,
             "non-empty list")

    if (detail.get("config") or {}).get("scenario") == "leader_kill" \
            and not expect_wedged:
        # the chaos scenario's verdict blocks: the kill must actually have
        # fired, the storage leader must have failed over, every acked bind
        # must survive, and the failover window's black box must exist
        fo = detail.get("failover")
        if not isinstance(fo, dict):
            errs.append("detail.failover: missing (leader_kill must report "
                        "its chaos verdict)")
        else:
            where = "detail.failover"
            need(fo, "chaos_fired", lambda v: v is True, where,
                 "true (a leader_kill soak that never killed proved "
                 "nothing)")
            need(fo, "failover_seconds", _is_num, where,
                 "number (the leader must actually have failed over)")
            need(fo, "leader_transitions",
                 lambda v: _is_num(v) and v >= 1, where, ">= 1")
            need(fo, "lost_bindings", lambda v: v == 0, where,
                 "0 (an acked bind that vanished is the loss this "
                 "scenario exists to catch)")
            need(fo, "acked_binds_tracked",
                 lambda v: _is_num(v) and v > 0, where,
                 "positive (no tracked binds = the ledger never saw the "
                 "churn)")
            need(fo, "members_converged", lambda v: v is True, where,
                 "true (replicas must agree after rejoin)")
        bundle = (doc.get("flight_recorder_bundle")
                  or detail.get("flight_recorder_bundle"))
        if not bundle:
            errs.append("$.flight_recorder_bundle: missing (the failover "
                        "window must ship its black box)")
        elif not os.path.exists(bundle):
            errs.append(f"$.flight_recorder_bundle: {bundle} does not exist")
        else:
            errs.extend(check_bundle(bundle))

    if expect_wedged:
        if not doc.get("wedged"):
            errs.append("$.wedged: expected true (seeded hang must be "
                        "reported, not laundered into a success)")
        bundle = (doc.get("flight_recorder_bundle")
                  or detail.get("flight_recorder_bundle"))
        if not bundle:
            errs.append("$.flight_recorder_bundle: missing (a wedged soak "
                        "must ship its black box)")
        elif not os.path.exists(bundle):
            errs.append(f"$.flight_recorder_bundle: {bundle} does not exist")
        else:
            errs.extend(check_bundle(bundle, expect_timeout_span=True))
    else:
        if doc.get("wedged"):
            errs.append("$.wedged: true — the soak smoke wedged")
        steady = detail.get("steady_state") or {}
        need(steady, "pods_per_sec", _is_num, "detail.steady_state",
             "number (a clean soak must measure throughput)")
        need(steady, "pods_bound",
             lambda v: _is_num(v) and v > 0, "detail.steady_state",
             "positive (a clean soak must bind pods)")
        # the micro-batch block: solve cadence + device-residency proof
        mb = detail.get("microbatch")
        if not isinstance(mb, dict):
            errs.append("detail.microbatch: missing (the soak must report "
                        "its solve cadence)")
        else:
            where = "detail.microbatch"
            need(mb, "window_ms", _is_num, where, "number")
            need(mb, "rounds", lambda v: _is_num(v) and v > 0, where,
                 "positive (a clean soak must run kernel rounds)")
            need(mb, "rounds_per_second",
                 lambda v: v is None or _is_num(v), where,
                 "number or null")
            need(mb, "avg_pods_per_round",
                 lambda v: v is None or _is_num(v), where,
                 "number or null")
            need(mb, "device_resident", lambda v: v is True, where,
                 "true (the incremental device-resident path must be on)")
            need(mb, "incremental_builds",
                 lambda v: _is_num(v) and v > 0, where,
                 "positive (solves must go through the incremental "
                 "mirror, not per-round full re-tensorize)")
        need(detail, "unschedulable_reasons", _reasons_ok, "detail",
             "predicate -> count object scraped off the reasons counter")
        if gang_mode:
            # gang_churn's objective verdict blocks (scraped, rebased):
            # gangs must actually place — a gang_churn soak that never
            # co-placed a gang proved nothing
            need(detail, "preemptions", _reasons_ok, "detail",
                 "reason -> count object scraped off preemptions_total")
            need(detail, "gangs_placed", lambda v: _is_num(v) and v > 0,
                 "detail", "positive (a clean gang soak must place gangs)")
            need(detail, "gangs_rejected", _is_num, "detail", "number")
    return errs


def check_bundle(path: str, expect_timeout_span: bool = False) -> list:
    """Schema-check one flight-recorder bundle; returns violation lines."""
    errs = []
    where = f"bundle({os.path.basename(path)})"
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{where}: unreadable: {e}"]
    if doc.get("kind") != "ktpu-flight-recorder-bundle":
        errs.append(f"{where}.kind: not a flight-recorder bundle")
    if not doc.get("reason"):
        errs.append(f"{where}.reason: missing")
    for key in ("spans", "audit", "events", "notes", "decisions"):
        if not isinstance(doc.get(key), list):
            errs.append(f"{where}.{key}: missing list")
    if not isinstance(doc.get("metrics"), dict) or \
            "counters" not in (doc.get("metrics") or {}):
        errs.append(f"{where}.metrics.counters: missing")
    if not doc.get("spans"):
        errs.append(f"{where}.spans: empty (a bundle with no spans explains "
                    "nothing)")
    if not doc.get("audit"):
        errs.append(f"{where}.audit: empty (the triggering requests must be "
                    "in the bundle)")
    if expect_timeout_span:
        timed_out = [s for s in doc.get("spans") or []
                     if isinstance(s, dict)
                     and (s.get("attrs") or {}).get("timeout")]
        if not timed_out:
            errs.append(f"{where}.spans: no timed-out stage span (the wedge "
                        "cause must be in the bundle)")
        trigger = doc.get("trigger") or {}
        if not trigger.get("slos"):
            errs.append(f"{where}.trigger.slos: missing SLO verdicts")
    return errs


def main(argv) -> int:
    expect_wedged = "--expect-wedged" in argv
    bundle_mode = "--bundle" in argv
    paths = [a for a in argv if not a.startswith("-")]
    if len(paths) != 1:
        print("usage: check_soak.py [--expect-wedged] <report.json> | "
              "check_soak.py --bundle <bundle.json>", file=sys.stderr)
        return 2
    if bundle_mode:
        errs = check_bundle(paths[0])
        for e in errs:
            print(f"check_soak: {e}", file=sys.stderr)
        if not errs:
            print(f"check_soak: bundle OK ({paths[0]})")
        return 1 if errs else 0
    try:
        with open(paths[0], encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_soak: cannot read {paths[0]}: {e}", file=sys.stderr)
        return 1
    errs = check(doc, expect_wedged)
    for e in errs:
        print(f"check_soak: {e}", file=sys.stderr)
    if not errs:
        print(f"check_soak: OK ({paths[0]})")
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
