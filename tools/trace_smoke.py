#!/usr/bin/env python3
"""Trace-propagation smoke (tools/verify.sh): schedule one pod through a
LIVE apiserver and prove the cross-process trace actually crossed.

Asserts, from the exported surfaces only (span ring + audit log):

1. the pod was bound by the real scheduler loop (informer -> FIFO ->
   schedule -> bind POST);
2. the scheduler's pod span and the apiserver's audit record for the bind
   POST share one trace id;
3. the client-side rest span is the audit record's remote parent.

Exit 0 on success, 1 with a diagnostic on any failure.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.observability.audit import AUDIT
    from kubernetes_tpu.scheduler.factory import ConfigFactory
    from kubernetes_tpu.utils import trace

    server = APIServer().start()
    factory = sched = None
    try:
        client = RESTClient.for_server(server, user_agent="trace-smoke")
        client.create("nodes", api.Node(
            metadata=api.ObjectMeta(name="smoke-node",
                                    labels={api.LABEL_HOSTNAME: "smoke-node"}),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "8Gi", "pods": "110"},
                conditions=[api.NodeCondition(type="Ready", status="True")])))
        client.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="smoke-pod", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(
                    requests={"cpu": "100m", "memory": "100Mi"}))])))
        factory = ConfigFactory(client)
        factory.run(timeout=60)
        sched = factory.create_from_provider()
        sched.run()
        deadline = time.monotonic() + 60
        bound = None
        while time.monotonic() < deadline:
            p = client.get("pods", "smoke-pod", "default")
            if p.spec and p.spec.node_name:
                bound = p
                break
            time.sleep(0.05)
        if bound is None:
            print("trace_smoke: pod never bound", file=sys.stderr)
            return 1

        # the finished pod span carries the trace the bind traveled on
        deadline = time.monotonic() + 10
        roots = []
        while time.monotonic() < deadline and not roots:
            roots = trace.recent_spans(name="schedule_pod")
            time.sleep(0.02)
        if not roots:
            print("trace_smoke: no finished schedule_pod span", file=sys.stderr)
            return 1
        trace_id = roots[-1].trace_id

        deadline = time.monotonic() + 10
        binds = []
        while time.monotonic() < deadline and not binds:
            binds = [r for r in AUDIT.tail(trace_id=trace_id)
                     if r.path.endswith("/bindings") and r.verb == "POST"]
            time.sleep(0.02)
        if not binds:
            on_trace = AUDIT.tail(trace_id=trace_id)
            print(f"trace_smoke: no bind audit record on trace {trace_id} "
                  f"(records on trace: {[r.path for r in on_trace]})",
                  file=sys.stderr)
            return 1
        rec = binds[-1]
        if rec.status != 201:
            print(f"trace_smoke: bind audited with status {rec.status}",
                  file=sys.stderr)
            return 1
        rest_spans = [s for s in trace.spans_for_trace(trace_id)
                      if s.name == "rest:POST"
                      and s.attrs.get("path", "").endswith("/bindings")]
        if not rest_spans:
            print("trace_smoke: no client rest span on the bind trace",
                  file=sys.stderr)
            return 1
        if rec.parent_id not in {s.span_id for s in rest_spans}:
            print(f"trace_smoke: audit parent {rec.parent_id} is not the "
                  "client's bind request span", file=sys.stderr)
            return 1
        print(f"trace_smoke: OK — trace {trace_id}: scheduler span -> "
              f"rest:POST {rest_spans[-1].span_id} -> apiserver audit "
              f"(status {rec.status}, {rec.latency_seconds}s)")
        return 0
    finally:
        if sched is not None:
            sched.stop()
        if factory is not None:
            factory.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
