"""Controllers + workqueue + leader election against a live in-proc cluster."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.controllers.endpoints_controller import EndpointsController
from kubernetes_tpu.controllers.node_controller import NodeController
from kubernetes_tpu.controllers.replication_controller import ReplicationManager
from kubernetes_tpu.utils.workqueue import (
    DelayingQueue, RateLimitingQueue, WorkQueue, parallelize,
)


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=2000, burst=2000)


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.03)
    raise AssertionError("condition not met")


class TestWorkQueue:
    def test_dedup(self):
        q = WorkQueue()
        q.add("a")
        q.add("a")
        q.add("b")
        assert len(q) == 2

    def test_dirty_requeue_while_processing(self):
        q = WorkQueue()
        q.add("a")
        item = q.get()
        q.add("a")          # while processing: marked dirty, not queued
        assert len(q) == 0
        q.done(item)        # now requeued
        assert len(q) == 1

    def test_delaying(self):
        q = DelayingQueue()
        q.add_after("x", 0.1)
        assert q.get(timeout=0.02) is None
        assert q.get(timeout=1.0) == "x"

    def test_rate_limited_backoff_grows(self):
        q = RateLimitingQueue(base_delay=0.01, max_delay=1.0)
        t0 = time.monotonic()
        q.add_rate_limited("x")
        assert q.get(timeout=2.0) == "x"
        q.done("x")
        q.add_rate_limited("x")  # second failure: 2x delay
        assert q.get(timeout=2.0) == "x"
        q.forget("x")

    def test_parallelize(self):
        out = []
        import threading
        lock = threading.Lock()

        def piece(i):
            with lock:
                out.append(i)

        parallelize(4, 20, piece)
        assert sorted(out) == list(range(20))


def mk_rc(name, replicas, labels):
    return api.ReplicationController(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.ReplicationControllerSpec(
            replicas=replicas, selector=dict(labels),
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels=dict(labels)),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m"}))]))))


class TestReplicationController:
    def test_scales_up_and_down(self, client):
        rm = ReplicationManager(client)
        rm.start()
        try:
            client.create("replicationcontrollers", mk_rc("web", 3, {"app": "web"}))
            _wait(lambda: len(client.list("pods", "default")[0]) == 3)
            pods, _ = client.list("pods", "default")
            assert all(p.metadata.name.startswith("web-") for p in pods)
            assert all((p.metadata.labels or {}).get("app") == "web" for p in pods)
            # scale down
            rc = client.get("replicationcontrollers", "web", "default")
            rc.spec.replicas = 1
            client.update("replicationcontrollers", rc)
            _wait(lambda: len(client.list("pods", "default")[0]) == 1)
            # status reflects observed count
            _wait(lambda: client.get("replicationcontrollers", "web",
                                     "default").status.replicas == 1)
        finally:
            rm.stop()

    def test_replaces_deleted_pod(self, client):
        rm = ReplicationManager(client)
        rm.start()
        try:
            client.create("replicationcontrollers", mk_rc("r", 2, {"app": "r"}))
            _wait(lambda: len(client.list("pods", "default")[0]) == 2)
            victim = client.list("pods", "default")[0][0]
            client.delete("pods", victim.metadata.name, "default")
            _wait(lambda: len(client.list("pods", "default")[0]) == 2)
        finally:
            rm.stop()


class TestEndpointsController:
    def test_builds_endpoints_from_ready_pods(self, client):
        ec = EndpointsController(client)
        ec.start()
        try:
            client.create("services", api.Service(
                metadata=api.ObjectMeta(name="svc", namespace="default"),
                spec=api.ServiceSpec(selector={"app": "web"},
                                     ports=[api.ServicePort(port=80, target_port=8080)])))
            pod = api.Pod(
                metadata=api.ObjectMeta(name="w1", namespace="default",
                                        labels={"app": "web"}),
                spec=api.PodSpec(node_name="n1", containers=[
                    api.Container(name="c", image="i")]),
                status=api.PodStatus(
                    phase="Running", pod_ip="10.1.0.5",
                    conditions=[api.PodCondition(type="Ready", status="True")]))
            # create via registry (status is server-managed on normal create)
            client.create("pods", api.Pod(
                metadata=pod.metadata, spec=pod.spec))
            got = client.get("pods", "w1", "default")
            got.status = pod.status
            client.update_status("pods", got)
            _wait(lambda: client.get("endpoints", "svc", "default").subsets)
            ep = client.get("endpoints", "svc", "default")
            assert ep.subsets[0].addresses[0].ip == "10.1.0.5"
            assert ep.subsets[0].ports[0].port == 8080
            # pod goes unready -> moves to notReadyAddresses
            got = client.get("pods", "w1", "default")
            got.status.conditions = [api.PodCondition(type="Ready", status="False")]
            client.update_status("pods", got)
            _wait(lambda: (client.get("endpoints", "svc", "default")
                           .subsets[0].not_ready_addresses))
        finally:
            ec.stop()


class TestNodeController:
    def test_marks_stale_node_unknown_and_evicts(self, client):
        now = [1000.0]
        nc = NodeController(client, monitor_period=999, grace_period=40,
                            pod_eviction_timeout=60, eviction_qps=1000,
                            clock=lambda: now[0])
        client.create("nodes", api.Node(
            metadata=api.ObjectMeta(name="n1"),
            status=api.NodeStatus(
                capacity={"cpu": "4", "pods": "10"},
                conditions=[api.NodeCondition(
                    type="Ready", status="True",
                    last_heartbeat_time="t0")])))
        client.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="p1", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(name="c", image="i")])))
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name="p1", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n1")), "default")
        nc.node_informer.run()
        nc.pod_informer.run()
        nc.node_informer.wait_for_sync()
        nc.pod_informer.wait_for_sync()
        try:
            nc.monitor_once()           # baseline heartbeat observed
            now[0] += 50                # > grace period, no new heartbeat
            nc.monitor_once()
            _wait(lambda: any(
                c.type == "Ready" and c.status == "Unknown"
                for c in client.get("nodes", "n1").status.conditions))
            now[0] += 70                # > eviction timeout
            nc.monitor_once()
            _wait(lambda: not client.list(
                "pods", "default",
                field_selector=None)[0])
        finally:
            nc.node_informer.stop()
            nc.pod_informer.stop()

    def test_fresh_heartbeat_resets(self, client):
        now = [0.0]
        nc = NodeController(client, clock=lambda: now[0])
        client.create("nodes", api.Node(
            metadata=api.ObjectMeta(name="n1"),
            status=api.NodeStatus(conditions=[api.NodeCondition(
                type="Ready", status="True", last_heartbeat_time="h1")])))
        nc.node_informer.run()
        nc.node_informer.wait_for_sync()
        nc.pod_informer.run()
        nc.pod_informer.wait_for_sync()
        try:
            nc.monitor_once()
            now[0] += 50
            n = client.get("nodes", "n1")
            n.status.conditions[0].last_heartbeat_time = "h2"
            client.update_status("nodes", n)
            _wait(lambda: nc.node_informer.store.get("n1")
                  .status.conditions[0].last_heartbeat_time == "h2")
            nc.monitor_once()
            assert "n1" not in nc._not_ready_since
        finally:
            nc.node_informer.stop()
            nc.pod_informer.stop()


class TestNamespaceController:
    def test_cascade_delete(self, client):
        from kubernetes_tpu.controllers.namespace_controller import NamespaceController
        nc = NamespaceController(client).start()
        try:
            client.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="doomed")))
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name="p", namespace="doomed"),
                spec=api.PodSpec(containers=[api.Container(name="c", image="i")])))
            ns = client.get("namespaces", "doomed")
            ns.status = api.NamespaceStatus(phase="Terminating")
            # regression: /api/v1/namespaces/{name}/status must route to the
            # namespaces status subresource, not parse as ns+resource
            client.update_status("namespaces", ns)
            _wait(lambda: _gone(client, "namespaces", "doomed"))
            assert not client.list("pods", "doomed")[0]
        finally:
            nc.stop()


def _gone(client, resource, name):
    try:
        client.get(resource, name)
        return False
    except Exception:
        return True


class TestLeaderElection:
    def test_single_leader_and_failover(self, client):
        started = []

        def make(identity):
            return LeaderElector(
                client,
                LeaderElectionConfig(lock_name="lock", identity=identity,
                                     lease_duration=0.6, renew_deadline=0.4,
                                     retry_period=0.1),
                on_started_leading=lambda i=identity: started.append(i))

        a, b = make("a"), make("b")
        a.run()
        _wait(lambda: a.is_leader)
        b.run()
        time.sleep(0.4)
        assert not b.is_leader          # lease held by a
        assert started == ["a"]
        a.stop()                        # stops renewing
        _wait(lambda: b.is_leader, timeout=5)
        assert started == ["a", "b"]
        b.stop()


class TestReviewRegressions:
    """Regression coverage for cache-lag over-creation, swallowed conflicts,
    dead-node eviction, and leader re-acquisition."""

    def test_leader_reacquires_after_losing_lease(self, client):
        import json as _json

        from kubernetes_tpu.client.leaderelection import LEADER_ANNOTATION

        started, stopped = [], []
        el = LeaderElector(
            client,
            LeaderElectionConfig(lock_name="relock", identity="a",
                                 lease_duration=0.6, renew_deadline=0.4,
                                 retry_period=0.05),
            on_started_leading=lambda: started.append("a"),
            on_stopped_leading=lambda: stopped.append("a"))
        el.run()
        _wait(lambda: el.is_leader)
        # another process steals the lease (fresh record, different holder)
        ep = client.get("endpoints", "relock", "kube-system")
        ep.metadata.annotations[LEADER_ANNOTATION] = _json.dumps({
            "holderIdentity": "thief",
            "leaseDurationSeconds": 1,
            "acquireTime": time.time(), "renewTime": time.time()})
        client.update("endpoints", ep, "kube-system")
        _wait(lambda: stopped == ["a"], timeout=5)
        # the thief never renews; el must re-enter acquire and lead again
        _wait(lambda: started == ["a", "a"] and el.is_leader, timeout=5)
        el.stop()

    def test_rc_expectations_prevent_double_create(self, client):
        # informer stores populated manually and never updated -> simulates
        # worst-case cache lag; without expectations the second sync would
        # create another full replica set
        rm = ReplicationManager(client)
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name="web", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"app": "web"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "web"}),
                    spec=api.PodSpec(containers=[
                        api.Container(name="c", image="pause")]))))
        created = client.create("replicationcontrollers", rc, "default")
        rm.rc_informer.store.add("default/web", created)
        rm.sync("default/web")
        rm.sync("default/web")  # cache still shows 0 pods
        pods = [p for p in client.list("pods", "default")[0]
                if (p.metadata.labels or {}).get("app") == "web"]
        assert len(pods) == 3

    def test_endpoints_conflict_raises_for_requeue(self, client, monkeypatch):
        from kubernetes_tpu.client.rest import ApiError

        ec = EndpointsController(client)
        svc = api.Service(
            metadata=api.ObjectMeta(name="s1", namespace="default"),
            spec=api.ServiceSpec(selector={"app": "x"},
                                 ports=[api.ServicePort(port=80)]))
        client.create("services", svc, "default")
        pod = api.Pod(
            metadata=api.ObjectMeta(name="px", namespace="default",
                                    labels={"app": "x"}),
            spec=api.PodSpec(containers=[api.Container(name="c", image="i")]),
            status=api.PodStatus(pod_ip="10.0.0.9", phase=api.POD_RUNNING))
        client.create("pods", pod, "default")
        ec.svc_informer.store.add("default/s1", client.get("services", "s1", "default"))
        ec.pod_informer.store.add("default/px", client.get("pods", "px", "default"))
        ec.sync("default/s1")  # creates endpoints

        # next write conflicts -> sync must raise so the worker requeues
        calls = {}

        def conflicting_update(*a, **kw):
            calls["hit"] = True
            raise ApiError(409, "Conflict", "simulated concurrent write")

        monkeypatch.setattr(client, "update", conflicting_update)
        # a second ready pod changes the desired subsets so sync reaches update
        pod2 = api.Pod(
            metadata=api.ObjectMeta(name="py", namespace="default",
                                    labels={"app": "x"}),
            spec=api.PodSpec(containers=[api.Container(name="c", image="i")]),
            status=api.PodStatus(pod_ip="10.0.0.10", phase=api.POD_RUNNING))
        ec.pod_informer.store.add("default/py", pod2)
        with pytest.raises(ApiError):
            ec.sync("default/s1")
        assert calls.get("hit")

    def test_node_delete_evicts_bound_pods(self, client):
        nc = NodeController(client, monitor_period=0.1, eviction_qps=1000.0)
        node = api.Node(
            metadata=api.ObjectMeta(name="doomed"),
            status=api.NodeStatus(conditions=[api.NodeCondition(
                type=api.NODE_READY, status=api.CONDITION_TRUE)]))
        client.create("nodes", node)
        pod = api.Pod(
            metadata=api.ObjectMeta(name="orphan", namespace="default"),
            spec=api.PodSpec(node_name="doomed",
                             containers=[api.Container(name="c", image="i")]))
        client.create("pods", pod, "default")
        nc.start()
        try:
            _wait(lambda: nc.node_informer.store.get("doomed") is not None)
            client.delete("nodes", "doomed")
            _wait(lambda: _pod_gone(client, "orphan"), timeout=10)
        finally:
            nc.stop()


def _pod_gone(client, name):
    try:
        client.get("pods", name, "default")
        return False
    except Exception:
        return True
