"""Label/field selector tests (reference pkg/labels/selector_test.go patterns)."""

import pytest

from kubernetes_tpu.api import labels
from kubernetes_tpu.api.fields import parse_field_selector
from kubernetes_tpu.api.labels import (
    Requirement, Selector, SelectorError, parse_selector, selector_from_label_selector,
    selector_from_map,
)


class TestParse:
    @pytest.mark.parametrize("s,lbls,want", [
        ("a=b", {"a": "b"}, True),
        ("a=b", {"a": "c"}, False),
        ("a==b", {"a": "b"}, True),
        ("a!=b", {"a": "c"}, True),
        ("a!=b", {"a": "b"}, False),
        ("a!=b", {}, True),              # absent key satisfies !=
        ("a in (b,c)", {"a": "c"}, True),
        ("a in (b,c)", {"a": "d"}, False),
        ("a notin (b)", {"a": "c"}, True),
        ("a notin (b)", {"a": "b"}, False),
        ("a notin (b)", {}, True),
        ("a", {"a": "anything"}, True),
        ("a", {}, False),
        ("!a", {}, True),
        ("!a", {"a": "x"}, False),
        ("a=b,c in (d, e),!f", {"a": "b", "c": "e"}, True),
        ("a=b,c in (d, e),!f", {"a": "b", "c": "e", "f": "1"}, False),
        ("", {"anything": "goes"}, True),
        (None, {}, True),
    ])
    def test_matches(self, s, lbls, want):
        assert parse_selector(s).matches(lbls) is want

    @pytest.mark.parametrize("bad", ["a in b", "=x", "a in (b"])
    def test_invalid(self, bad):
        with pytest.raises(SelectorError):
            parse_selector(bad)


def test_selector_from_map():
    sel = selector_from_map({"app": "web", "tier": "fe"})
    assert sel.matches({"app": "web", "tier": "fe", "extra": "ok"})
    assert not sel.matches({"app": "web"})
    # nil selector matches nothing (how nil RC/service selectors behave)
    assert not selector_from_map(None).matches({})
    # empty selector matches everything
    assert selector_from_map({}).matches({"x": "y"})


def test_structured_label_selector():
    ls = {"matchLabels": {"app": "db"},
          "matchExpressions": [
              {"key": "tier", "operator": "In", "values": ["be", "mid"]},
              {"key": "canary", "operator": "DoesNotExist"}]}
    sel = selector_from_label_selector(ls)
    assert sel.matches({"app": "db", "tier": "be"})
    assert not sel.matches({"app": "db", "tier": "fe"})
    assert not sel.matches({"app": "db", "tier": "be", "canary": "y"})
    assert not selector_from_label_selector(None).matches({})


def test_gt_lt():
    gt = Selector((Requirement("cores", labels.GT, ("4",)),))
    assert gt.matches({"cores": "8"})
    assert not gt.matches({"cores": "2"})
    assert not gt.matches({})
    assert not gt.matches({"cores": "notanumber"})
    lt = Selector((Requirement("cores", labels.LT, ("4",)),))
    assert lt.matches({"cores": "2"})


def test_selector_str_roundtrip():
    cases = ["a=b", "a in (b,c)", "!a", "a,b notin (c)", "cores>4", "cores<4"]
    samples = [{}, {"a": "b"}, {"a": "c"}, {"b": "c"}, {"cores": "8"},
               {"cores": "2"}, {"a": "b", "b": "x", "cores": "4"}]
    for s in cases:
        sel = parse_selector(s)
        reparsed = parse_selector(str(sel))
        for lbls in samples:
            assert reparsed.matches(lbls) == sel.matches(lbls), (s, lbls)


class TestFieldSelector:
    def test_basic(self):
        fs = parse_field_selector("spec.nodeName=")
        assert fs.matches({"spec.nodeName": ""})
        assert not fs.matches({"spec.nodeName": "node1"})

    def test_neq(self):
        fs = parse_field_selector("status.phase!=Failed,status.phase!=Succeeded")
        assert fs.matches({"status.phase": "Running"})
        assert not fs.matches({"status.phase": "Failed"})

    def test_empty_matches_all(self):
        assert parse_field_selector("").matches({"anything": "x"})
        assert parse_field_selector(None).matches({})
