"""Service-LB + route controllers over the cloud provider seam.

Parity target: reference pkg/controller/service/servicecontroller.go and
pkg/controller/route/routecontroller.go behind pkg/cloudprovider
(round-4 verdict missing #6). Driven through the live apiserver and
informers against the FakeCloud.
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.cloudprovider import FakeCloud
from kubernetes_tpu.controllers.route_controller import RouteController
from kubernetes_tpu.controllers.service_controller import ServiceController


def wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def mk_node(name, ready=True):
    return api.Node(
        metadata=api.ObjectMeta(name=name),
        status=api.NodeStatus(conditions=[api.NodeCondition(
            type=api.NODE_READY,
            status=api.CONDITION_TRUE if ready else api.CONDITION_FALSE)]))


def mk_lb_service(name, port=80):
    return api.Service(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.ServiceSpec(type="LoadBalancer",
                             selector={"app": name},
                             ports=[api.ServicePort(port=port)]))


@pytest.fixture()
def stack():
    server = APIServer().start()
    client = RESTClient.for_server(server)
    cloud = FakeCloud()
    try:
        yield server, client, cloud
    finally:
        server.stop()


class TestServiceController:
    def test_lb_lifecycle(self, stack):
        server, client, cloud = stack
        client.create("nodes", mk_node("n1"))
        client.create("nodes", mk_node("n2"))
        client.create("nodes", mk_node("down", ready=False))
        ctrl = ServiceController(client, cloud)
        ctrl.start()
        try:
            client.create("services", mk_lb_service("web"))
            # the LB appears, fronts only READY nodes, and the ingress IP
            # lands in service status
            svc = wait_for(
                lambda: (lambda s: s if s.status and s.status.load_balancer
                         and s.status.load_balancer.ingress else None)(
                    client.get("services", "web", "default")),
                msg="ingress IP in status")
            ip = svc.status.load_balancer.ingress[0].ip
            lb = cloud.get_load_balancer("lb-default-web")
            assert lb["ip"] == ip
            assert lb["nodes"] == ["n1", "n2"]
            assert lb["ports"] == [80]

            # deletion tears the cloud LB down
            client.delete("services", "web", "default")
            wait_for(lambda: cloud.get_load_balancer("lb-default-web")
                     is None, msg="LB deleted")
        finally:
            ctrl.stop()

    def test_node_readiness_retargets_lbs(self, stack):
        server, client, cloud = stack
        client.create("nodes", mk_node("a"))
        ctrl = ServiceController(client, cloud)
        ctrl.start()
        try:
            client.create("services", mk_lb_service("api"))
            wait_for(lambda: cloud.get_load_balancer("lb-default-api"),
                     msg="LB created")
            client.create("nodes", mk_node("b"))
            wait_for(lambda: cloud.get_load_balancer(
                "lb-default-api")["nodes"] == ["a", "b"],
                msg="new node behind the LB")
        finally:
            ctrl.stop()

    def test_non_lb_services_ignored(self, stack):
        server, client, cloud = stack
        ctrl = ServiceController(client, cloud)
        ctrl.start()
        try:
            client.create("services", api.Service(
                metadata=api.ObjectMeta(name="plain", namespace="default"),
                spec=api.ServiceSpec(ports=[api.ServicePort(port=80)])))
            time.sleep(0.5)
            assert cloud.get_load_balancer("lb-default-plain") is None
        finally:
            ctrl.stop()


class TestRouteController:
    def test_cidr_allocation_and_routes(self, stack):
        server, client, cloud = stack
        for i in range(3):
            client.create("nodes", mk_node(f"r{i}"))
        ctrl = RouteController(client, cloud)
        ctrl.start()
        try:
            wait_for(lambda: len(cloud.list_routes()) == 3,
                     msg="routes for all nodes")
            cidrs = set()
            for i in range(3):
                node = client.get("nodes", f"r{i}")
                assert node.spec.pod_cidr, f"r{i} got no podCIDR"
                cidrs.add(node.spec.pod_cidr)
            assert len(cidrs) == 3  # unique allocations
            assert cloud.list_routes() == {
                f"r{i}": client.get("nodes", f"r{i}").spec.pod_cidr
                for i in range(3)}

            # node departure removes its route
            client.delete("nodes", "r1")
            wait_for(lambda: "r1" not in cloud.list_routes(),
                     msg="route removed")
        finally:
            ctrl.stop()

    def test_existing_cidr_respected(self, stack):
        server, client, cloud = stack
        n = mk_node("pre")
        n.spec = api.NodeSpec(pod_cidr="10.244.7.0/24")
        client.create("nodes", n)
        ctrl = RouteController(client, cloud)
        ctrl.start()
        try:
            wait_for(lambda: cloud.list_routes().get("pre")
                     == "10.244.7.0/24", msg="pre-set CIDR routed")
            assert client.get("nodes", "pre").spec.pod_cidr == "10.244.7.0/24"
        finally:
            ctrl.stop()
