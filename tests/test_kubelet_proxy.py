"""Kubelet (hollow), proxier, and the full-stack e2e: apiserver + scheduler +
RC controller + endpoints controller + hollow kubelet + proxy — a pod goes
RC -> scheduled -> running -> endpoints -> NAT rules end to end (the
reference's density-style smoke at miniature scale)."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.controllers.endpoints_controller import EndpointsController
from kubernetes_tpu.controllers.replication_controller import ReplicationManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.proxy import FakeIptables, Proxier
from kubernetes_tpu.scheduler.factory import ConfigFactory


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=2000, burst=2000)


def _wait(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.03)
    raise AssertionError("condition not met")


def mk_pod(name, node="", cpu="100m"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PodSpec(node_name=node, containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(requests={"cpu": cpu}))]))


class TestKubelet:
    def test_registers_node_with_capacity(self, client):
        kl = Kubelet(client, "node-a")
        kl.start()
        try:
            node = client.get("nodes", "node-a")
            assert node.status.capacity["cpu"] == "4"
            conds = {c.type: c.status for c in node.status.conditions}
            assert conds["Ready"] == "True"
        finally:
            kl.stop()

    def test_runs_assigned_pod_and_reports_status(self, client):
        kl = Kubelet(client, "node-a")
        kl.start()
        try:
            client.create("pods", mk_pod("p1"))
            client.bind(api.Binding(
                metadata=api.ObjectMeta(name="p1", namespace="default"),
                target=api.ObjectReference(kind="Node", name="node-a")), "default")
            _wait(lambda: client.get("pods", "p1", "default").status.phase == "Running")
            pod = client.get("pods", "p1", "default")
            assert pod.status.pod_ip
            assert pod.status.container_statuses[0].state.running
            conds = {c.type: c.status for c in pod.status.conditions}
            assert conds["Ready"] == "True"
        finally:
            kl.stop()

    def test_admission_rejects_overcommit(self, client):
        """The kubelet re-runs GeneralPredicates locally (the second
        enforcer) — direct-bound pods that don't fit are Failed."""
        kl = Kubelet(client, "node-a")
        kl.start()
        try:
            client.create("pods", mk_pod("fat", cpu="64"))
            client.bind(api.Binding(
                metadata=api.ObjectMeta(name="fat", namespace="default"),
                target=api.ObjectReference(kind="Node", name="node-a")), "default")
            _wait(lambda: client.get("pods", "fat", "default").status.phase == "Failed")
            pod = client.get("pods", "fat", "default")
            assert pod.status.reason == "OutOfResources"
        finally:
            kl.stop()

    def test_deletion_kills_runtime_pod(self, client):
        rt = FakeRuntime()
        kl = Kubelet(client, "node-a", runtime=rt)
        kl.start()
        try:
            client.create("pods", mk_pod("p1"))
            client.bind(api.Binding(
                metadata=api.ObjectMeta(name="p1", namespace="default"),
                target=api.ObjectReference(kind="Node", name="node-a")), "default")
            _wait(lambda: "default/p1" in rt.running())
            client.delete("pods", "p1", "default")
            _wait(lambda: "default/p1" not in rt.running())
        finally:
            kl.stop()


class TestProxier:
    def test_compiles_nat_rules(self, client):
        ipt = FakeIptables()
        px = Proxier(client, ipt)
        client.create("services", api.Service(
            metadata=api.ObjectMeta(name="web", namespace="default"),
            spec=api.ServiceSpec(cluster_ip="10.96.0.10", selector={"app": "web"},
                                 ports=[api.ServicePort(name="http", port=80)])))
        client.create("endpoints", api.Endpoints(
            metadata=api.ObjectMeta(name="web", namespace="default"),
            subsets=[api.EndpointSubset(
                addresses=[api.EndpointAddress(ip="10.1.0.5"),
                           api.EndpointAddress(ip="10.1.0.6")],
                ports=[api.EndpointPort(name="http", port=8080)])]))
        px.start()
        try:
            rules = ipt.current
            assert "-d 10.96.0.10/32" in rules and "--dport 80" in rules
            assert "10.1.0.5:8080" in rules and "10.1.0.6:8080" in rules
            assert "--probability 0.50000" in rules  # 2-way balance
            # endpoint removal resyncs
            client.update("endpoints", api.Endpoints(
                metadata=api.ObjectMeta(
                    name="web", namespace="default",
                    resource_version=client.get("endpoints", "web", "default"
                                                ).metadata.resource_version),
                subsets=[api.EndpointSubset(
                    addresses=[api.EndpointAddress(ip="10.1.0.5")],
                    ports=[api.EndpointPort(name="http", port=8080)])]))
            _wait(lambda: "10.1.0.6:8080" not in ipt.current)
            assert "10.1.0.5:8080" in ipt.current
        finally:
            px.stop()


class TestFullStack:
    def test_rc_to_nat_rules_end_to_end(self, client):
        """RC -> scheduler -> hollow kubelet -> endpoints -> proxy."""
        components = []
        try:
            for name in ("node-1", "node-2"):
                kl = Kubelet(client, name)
                kl.start()
                components.append(kl)
            factory = ConfigFactory(client)
            factory.run()
            sched = factory.create_from_provider().run()
            components.extend([sched, factory])
            rm = ReplicationManager(client)
            rm.start()
            components.append(rm)
            ec = EndpointsController(client)
            ec.start()
            components.append(ec)
            ipt = FakeIptables()
            px = Proxier(client, ipt)
            px.start()
            components.append(px)

            client.create("services", api.Service(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ServiceSpec(cluster_ip="10.96.0.1",
                                     selector={"app": "web"},
                                     ports=[api.ServicePort(name="http", port=80,
                                                            target_port=8080)])))
            client.create("replicationcontrollers", api.ReplicationController(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ReplicationControllerSpec(
                    replicas=3, selector={"app": "web"},
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"app": "web"}),
                        spec=api.PodSpec(containers=[api.Container(
                            name="c", image="pause",
                            resources=api.ResourceRequirements(
                                requests={"cpu": "100m"}))])))))

            # 3 pods running across both nodes
            def all_running():
                pods, _ = client.list("pods", "default")
                return (len(pods) == 3
                        and all(p.status and p.status.phase == "Running"
                                for p in pods)
                        and all(p.spec.node_name for p in pods))

            _wait(all_running, timeout=30)
            pods, _ = client.list("pods", "default")
            assert {p.spec.node_name for p in pods} == {"node-1", "node-2"}

            # endpoints have 3 ready addresses; proxy compiled DNAT for each
            _wait(lambda: len(client.get("endpoints", "web", "default")
                              .subsets[0].addresses or []) == 3, timeout=30)
            ips = [a.ip for a in client.get("endpoints", "web", "default")
                   .subsets[0].addresses]
            _wait(lambda: all(f"{ip}:8080" in ipt.current for ip in ips))
        finally:
            for c in reversed(components):
                c.stop()
