"""The TPU batch scheduler wired into the live control plane.

VERDICT round-1 #1: pods created through the API server must be bound by the
kernel path (not the sequential oracle), with bindings identical to the
oracle run of the same sequence. Mirrors the reference's integration pattern
(test/integration/scheduler_test.go) with the batch algorithm behind the
same ConfigFactory seam (plugin/pkg/scheduler/factory/factory.go:248-342).
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.scheduler.batch import (
    ListServiceLister, make_plugin_args, oracle_batch,
)
from kubernetes_tpu.scheduler.factory import ConfigFactory


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=5000, burst=5000)


def mk_pod(name, cpu="100m", mem="256Mi", ns="default", labels=None,
           selector=None, tolerations=None):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(
            node_selector=selector,
            tolerations=tolerations,
            containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(
                    requests={"cpu": cpu, "memory": mem}))]))


def mk_node(name, cpu="4", mem="16Gi", pods="110", labels=None, taints=None,
            ready=True):
    labels = dict(labels or {})
    labels.setdefault(api.LABEL_HOSTNAME, name)
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[api.NodeCondition(
                type="Ready", status="True" if ready else "False")]))


def wait_scheduled(client, n, ns="default", timeout=60.0):
    deadline = time.monotonic() + timeout
    done = []
    while time.monotonic() < deadline:
        pods, _ = client.list("pods", ns)
        done = [p for p in pods if p.spec.node_name]
        if len(done) >= n:
            return done
        time.sleep(0.05)
    raise AssertionError(f"only {len(done)}/{n} pods scheduled in {timeout}s")


def build_cluster(client, n_nodes=6, n_pods=40):
    """Nodes with zones/taints/labels + pods with selectors/tolerations so
    the full kernel surface runs, created BEFORE the scheduler starts so the
    FIFO drains them in one deterministic batch."""
    nodes = []
    for i in range(n_nodes):
        labels = {api.LABEL_ZONE: f"z{i % 2}"}
        if i % 3 == 0:
            labels["disk"] = "ssd"
        taints = ([api.Taint(key="ded", value="x", effect="NoSchedule")]
                  if i == n_nodes - 1 else None)
        n = mk_node(f"n-{i:02d}", labels=labels, taints=taints)
        nodes.append(n)
        client.create("nodes", n)
    svc = api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(port=80)]))
    client.create("services", svc)
    pods = []
    for i in range(n_pods):
        kw = {}
        if i % 5 == 0:
            kw["selector"] = {"disk": "ssd"}
        if i % 7 == 0:
            kw["tolerations"] = [api.Toleration(key="ded", operator="Exists")]
        p = mk_pod(f"pod-{i:03d}", labels={"app": "web" if i % 2 else "db"},
                   **kw)
        pods.append(p)
        client.create("pods", p)
    return nodes, pods, [svc]


class TestBatchSchedulerE2E:
    def test_kernel_path_binds_pods(self, client, caplog):
        import logging
        nodes, pods, services = build_cluster(client)
        factory = ConfigFactory(client)
        factory.run()
        with caplog.at_level(logging.WARNING, logger="scheduler.tpu"):
            sched = factory.create_batch_from_provider(batch_size=128).run()
            try:
                done = wait_scheduled(client, len(pods))
            finally:
                sched.stop()
                factory.stop()
        # the device path, not the fallback, did the placing
        assert sched.kernel_failures == 0, (
            f"health={sched.health} reason={sched.disabled_reason}\n"
            f"{caplog.text}")
        assert sched.kernel_batches >= 1
        assert sched.kernel_pods == len(pods), caplog.text
        # constraints honored end-to-end
        by_name = {n.metadata.name: n for n in nodes}
        for p in done:
            node = by_name[p.spec.node_name]
            if p.spec.node_selector:
                for k, v in p.spec.node_selector.items():
                    assert (node.metadata.labels or {}).get(k) == v
            if node.spec and node.spec.taints:
                assert p.spec.tolerations, \
                    f"{p.metadata.name} on tainted node without toleration"
            conds = {c.type: c.status for c in (p.status.conditions or [])}
            assert conds.get("PodScheduled") == "True"

    def test_bindings_match_oracle(self, client):
        """The live kernel run must produce byte-identical bindings to the
        offline oracle over the same FIFO sequence (SURVEY §7 done-means)."""
        nodes, pods, services = build_cluster(client)
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=128).run()
        try:
            done = wait_scheduled(client, len(pods))
        finally:
            sched.stop()
            factory.stop()
        assert sched.kernel_failures == 0
        live = {p.metadata.name: p.spec.node_name for p in done}

        args = make_plugin_args(nodes,
                                service_lister=ListServiceLister(services))
        want = oracle_batch(nodes, [], pods, args)
        expected = {p.metadata.name: host
                    for p, host in zip(pods, want) if host is not None}
        assert live == expected

    def test_unschedulable_pod_takes_failure_path(self, client):
        client.create("nodes", mk_node("only", cpu="1"))
        client.create("pods", mk_pod("fits", cpu="500m"))
        client.create("pods", mk_pod("huge", cpu="64"))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=16).run()
        try:
            wait_scheduled(client, 1)
            deadline = time.monotonic() + 10
            cond = None
            while time.monotonic() < deadline and cond is None:
                pod = client.get("pods", "huge", "default")
                for c in (pod.status.conditions or []):
                    if c.type == "PodScheduled" and c.status == "False":
                        cond = c
                time.sleep(0.05)
        finally:
            sched.stop()
            factory.stop()
        assert cond is not None and cond.reason == "Unschedulable"
        assert not client.get("pods", "huge", "default").spec.node_name

    def test_device_failure_falls_back_to_oracle(self, client, monkeypatch):
        """A broken device degrades to reference behavior, not a wedged
        queue."""
        client.create("nodes", mk_node("n1"))
        client.create("pods", mk_pod("p1"))
        client.create("pods", mk_pod("p2"))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=16)

        def boom(nodes, existing, pending):
            raise RuntimeError("device exploded")

        monkeypatch.setattr(sched, "_run_kernel", boom)
        sched.run()
        try:
            done = wait_scheduled(client, 2)
        finally:
            sched.stop()
            factory.stop()
        assert sched.kernel_failures >= 1
        assert {p.spec.node_name for p in done} == {"n1"}

    def test_second_batch_sees_first_batch_assumes(self, client):
        """Capacity booked by batch 1 constrains batch 2 (the cross-batch
        analogue of AssumePod, cache.go:101)."""
        client.create("nodes", mk_node("small", cpu="1", pods="4"))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=64).run()
        try:
            # batch 1: two pods fill the node's cpu
            client.create("pods", mk_pod("a1", cpu="500m"))
            client.create("pods", mk_pod("a2", cpu="500m"))
            wait_scheduled(client, 2)
            # batch 2: no cpu left
            client.create("pods", mk_pod("b1", cpu="500m"))
            deadline = time.monotonic() + 10
            cond = None
            while time.monotonic() < deadline and cond is None:
                pod = client.get("pods", "b1", "default")
                for c in (pod.status.conditions or []):
                    if c.type == "PodScheduled" and c.status == "False":
                        cond = c
                time.sleep(0.05)
        finally:
            sched.stop()
            factory.stop()
        assert cond is not None
        assert not client.get("pods", "b1", "default").spec.node_name
