"""Kubelet volume pipeline: real directories behind pod volumes.

Parity target: reference pkg/volume/ + pkg/kubelet/volume_manager.go —
the node-side half of the PV story (round-4 verdict missing #3). The
ProcessRuntime makes it physical: emptyDir shares real files between
containers of a pod, hostPath exposes host files, PVC resolves through
the bound PV, cloud sources leave attach bookkeeping.
"""

import os
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.kubelet.runtime import FakeCadvisor
from kubernetes_tpu.volume import VolumeError, VolumeManager


def wait_for(cond, timeout=20.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def vol_pod(name, volumes, containers, ns="default"):
    return api.Pod(metadata=api.ObjectMeta(name=name, namespace=ns),
                   spec=api.PodSpec(volumes=volumes, containers=containers,
                                    restart_policy="Never"))


class TestVolumeManagerUnit:
    def test_empty_dir_lifecycle(self, tmp_path):
        vm = VolumeManager(str(tmp_path))
        pod = vol_pod(
            "e", [api.Volume(name="scratch",
                             empty_dir=api.EmptyDirVolumeSource())],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="scratch", mount_path="/data")])])
        views = vm.setup_pod(pod)
        path = views["c"]["/data"]
        assert os.path.isdir(path)
        open(os.path.join(path, "f"), "w").write("x")
        vm.teardown_pod("default/e")
        assert not os.path.exists(path)  # emptyDir dies with the pod

    def test_host_path_passthrough_and_survival(self, tmp_path):
        host = tmp_path / "host"
        host.mkdir()
        (host / "seed").write_text("host data")
        vm = VolumeManager(str(tmp_path / "root"))
        pod = vol_pod(
            "h", [api.Volume(name="hp", host_path=api.HostPathVolumeSource(
                path=str(host)))],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="hp", mount_path="/host")])])
        views = vm.setup_pod(pod)
        assert views["c"]["/host"] == str(host)
        vm.teardown_pod("default/h")
        assert (host / "seed").read_text() == "host data"  # survives

    def test_missing_host_path_rejected(self, tmp_path):
        vm = VolumeManager(str(tmp_path))
        pod = vol_pod(
            "m", [api.Volume(name="hp", host_path=api.HostPathVolumeSource(
                path=str(tmp_path / "nope")))],
            [api.Container(name="c", image="i")])
        with pytest.raises(VolumeError):
            vm.setup_pod(pod)

    def test_unknown_mount_rejected(self, tmp_path):
        vm = VolumeManager(str(tmp_path))
        pod = vol_pod(
            "u", None,
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="ghost", mount_path="/x")])])
        with pytest.raises(VolumeError):
            vm.setup_pod(pod)

    def test_cloud_attach_bookkeeping_survives_pod(self, tmp_path):
        vm = VolumeManager(str(tmp_path))
        pod = vol_pod(
            "a", [api.Volume(name="data",
                             aws_elastic_block_store=
                             api.AWSElasticBlockStoreVolumeSource(
                                 volume_id="vol-9"))],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="data", mount_path="/data")])])
        views = vm.setup_pod(pod)
        marker = os.path.join(views["c"]["/data"], ".attached")
        assert open(marker).read().strip() == "ebs:vol-9"
        vm.teardown_pod("default/a")
        assert os.path.exists(marker)  # attach record outlives the pod


class TestVolumesThroughProcessRuntime:
    @pytest.fixture()
    def stack(self, tmp_path):
        server = APIServer().start()
        client = RESTClient.for_server(server)
        rt = ProcessRuntime(root_dir=str(tmp_path / "pods"))
        kl = Kubelet(client, "vnode", runtime=rt, cadvisor=FakeCadvisor(),
                     heartbeat_period=5.0, sync_period=0.2)
        kl.start()
        try:
            yield server, client, rt
        finally:
            kl.stop()
            rt.cleanup()
            server.stop()

    def _schedule(self, client, pod):
        client.create("pods", pod)
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name=pod.metadata.name),
            target=api.ObjectReference(kind="Node", name="vnode")),
            pod.metadata.namespace or "default")

    def test_empty_dir_shared_between_containers(self, stack):
        """The volume IS shared state: the writer's file appears in the
        reader's view — two real processes, one real directory."""
        server, client, rt = stack
        pod = vol_pod(
            "share",
            [api.Volume(name="shared",
                        empty_dir=api.EmptyDirVolumeSource())],
            [api.Container(
                name="writer", image="i",
                command=["/bin/sh", "-c",
                         'echo payload > "$KTPU_MOUNTS/data/msg"; sleep 600'],
                volume_mounts=[api.VolumeMount(name="shared",
                                               mount_path="/data")]),
             api.Container(
                 name="reader", image="i",
                 command=["/bin/sh", "-c", "sleep 600"],
                 volume_mounts=[api.VolumeMount(name="shared",
                                                mount_path="/data")])])
        pod.spec.restart_policy = "Always"
        self._schedule(client, pod)
        wait_for(lambda: "default/share" in rt.running(), msg="pod running")

        def read_back():
            rc, out = rt.exec("default/share", "reader",
                              ["/bin/sh", "-c", 'cat "$KTPU_MOUNTS/data/msg"'])
            return out.strip() if rc == 0 else None
        assert wait_for(read_back, msg="shared payload") == "payload"

    def test_pvc_resolves_through_bound_pv(self, stack, tmp_path):
        """claim -> bound PV (hostPath) -> the pod writes into the PV's
        real path — the full PV story end to end."""
        server, client, rt = stack
        pv_dir = tmp_path / "pv-store"
        pv_dir.mkdir()
        client.create("persistentvolumes", api.PersistentVolume(
            metadata=api.ObjectMeta(name="pv1"),
            spec=api.PersistentVolumeSpec(
                capacity={"storage": "1Gi"},
                host_path=api.HostPathVolumeSource(path=str(pv_dir)))))
        client.create("persistentvolumeclaims", api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="claim1", namespace="default"),
            spec=api.PersistentVolumeClaimSpec(volume_name="pv1")))
        pod = vol_pod(
            "pvc-user",
            [api.Volume(name="store",
                        persistent_volume_claim=
                        api.PersistentVolumeClaimVolumeSource(
                            claim_name="claim1"))],
            [api.Container(
                name="c", image="i",
                command=["/bin/sh", "-c",
                         'echo durable > "$KTPU_MOUNTS/store/out"; sleep 600'],
                volume_mounts=[api.VolumeMount(name="store",
                                               mount_path="/store")])])
        pod.spec.restart_policy = "Always"
        self._schedule(client, pod)
        wait_for(lambda: (pv_dir / "out").exists(), msg="write into PV")
        assert (pv_dir / "out").read_text().strip() == "durable"
        # pod teardown leaves the PV's data (reclaim is the controller's job)
        rt.kill_pod("default/pvc-user")
        assert (pv_dir / "out").exists()

    def test_unbound_pvc_keeps_pod_pending(self, stack):
        server, client, rt = stack
        client.create("persistentvolumeclaims", api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="floating", namespace="default"),
            spec=api.PersistentVolumeClaimSpec()))
        pod = vol_pod(
            "stuck",
            [api.Volume(name="v",
                        persistent_volume_claim=
                        api.PersistentVolumeClaimVolumeSource(
                            claim_name="floating"))],
            [api.Container(name="c", image="i")])
        self._schedule(client, pod)
        time.sleep(1.0)
        assert "default/stuck" not in rt.running()


class TestVolumeValidation:
    def test_unknown_mount_rejected_at_admission(self):
        from kubernetes_tpu.api.validation import ValidationError, validate_pod
        pod = vol_pod(
            "v", [api.Volume(name="data",
                             empty_dir=api.EmptyDirVolumeSource())],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="dtaa", mount_path="/data")])])
        with pytest.raises(ValidationError) as ei:
            validate_pod(pod)
        assert "no volume named" in str(ei.value)

    def test_duplicate_mount_path_rejected(self):
        from kubernetes_tpu.api.validation import ValidationError, validate_pod
        pod = vol_pod(
            "v", [api.Volume(name="a", empty_dir=api.EmptyDirVolumeSource()),
                  api.Volume(name="b", empty_dir=api.EmptyDirVolumeSource())],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="a", mount_path="/data"),
                api.VolumeMount(name="b", mount_path="/data")])])
        with pytest.raises(ValidationError):
            validate_pod(pod)

    def test_colliding_view_entries_rejected_at_setup(self, tmp_path):
        vm = VolumeManager(str(tmp_path))
        pod = vol_pod(
            "v", [api.Volume(name="a", empty_dir=api.EmptyDirVolumeSource()),
                  api.Volume(name="b", empty_dir=api.EmptyDirVolumeSource())],
            [api.Container(name="c", image="i", volume_mounts=[
                api.VolumeMount(name="a", mount_path="/data/logs"),
                api.VolumeMount(name="b", mount_path="/data_logs")])])
        with pytest.raises(VolumeError) as ei:
            vm.setup_pod(pod)
        assert "collide" in str(ei.value)

    def test_partial_setup_rolls_back_owned_paths(self, tmp_path):
        vm = VolumeManager(str(tmp_path / "root"))
        pod = vol_pod(
            "v", [api.Volume(name="good",
                             empty_dir=api.EmptyDirVolumeSource()),
                  api.Volume(name="bad", host_path=api.HostPathVolumeSource(
                      path=str(tmp_path / "missing")))],
            [api.Container(name="c", image="i")])
        with pytest.raises(VolumeError):
            vm.setup_pod(pod)
        assert not os.path.exists(os.path.join(
            str(tmp_path / "root"), "default_v", "volumes", "good"))
        assert vm.mounted("default/v") == {}


class TestFailedMountHeals:
    def test_late_host_path_heals_via_resync(self, tmp_path):
        """Missing hostPath -> FailedSync, pod Pending; the path appearing
        later heals it on the resync tick without any new watch event."""
        server = APIServer().start()
        client = RESTClient.for_server(server)
        rt = ProcessRuntime(root_dir=str(tmp_path / "pods"))
        kl = Kubelet(client, "vnode", runtime=rt, cadvisor=FakeCadvisor(),
                     heartbeat_period=5.0, sync_period=0.2)
        kl.start()
        try:
            host = tmp_path / "appears-later"
            pod = vol_pod(
                "heal", [api.Volume(name="hp",
                                    host_path=api.HostPathVolumeSource(
                                        path=str(host)))],
                [api.Container(name="c", image="i",
                               command=["/bin/sh", "-c", "sleep 600"],
                               volume_mounts=[api.VolumeMount(
                                   name="hp", mount_path="/host")])])
            pod.spec.restart_policy = "Always"
            client.create("pods", pod)
            client.bind(api.Binding(
                metadata=api.ObjectMeta(name="heal"),
                target=api.ObjectReference(kind="Node", name="vnode")),
                "default")
            time.sleep(1.0)
            assert "default/heal" not in rt.running()
            host.mkdir()
            wait_for(lambda: "default/heal" in rt.running(),
                     msg="pod healed after hostPath appeared")
        finally:
            kl.stop()
            rt.cleanup()
            server.stop()
