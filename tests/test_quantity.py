"""Quantity parsing (reference pkg/api/resource/quantity_test.go semantics)."""

import pytest

from kubernetes_tpu.api.quantity import (
    QuantityError, format_cpu, format_memory, parse_cpu, parse_memory, parse_quantity,
)


@pytest.mark.parametrize("s,milli", [
    ("100m", 100),
    ("1", 1000),
    ("2", 2000),
    ("0.5", 500),
    ("1500m", 1500),
    ("2.5", 2500),
    (1, 1000),
    (0.1, 100),
    ("0", 0),
])
def test_parse_cpu(s, milli):
    assert parse_cpu(s) == milli


@pytest.mark.parametrize("s,b", [
    ("500Mi", 500 * 2**20),
    ("1Gi", 2**30),
    ("128974848", 128974848),
    ("1G", 10**9),
    ("100k", 100_000),
    ("1.5Gi", 3 * 2**29),
    ("2e3", 2000),
    ("0", 0),
])
def test_parse_memory(s, b):
    assert parse_memory(s) == b


def test_milli_rounds_up():
    # Quantity.MilliValue rounds up: 1 byte -> 1 milli-unit
    assert parse_cpu("0.0001") == 1


def test_exa_vs_exponent():
    assert parse_quantity("2E") == 2 * 10**18
    assert parse_quantity("2E2") == 200


@pytest.mark.parametrize("bad", ["", "abc", "1.2.3", None, "Mi"])
def test_invalid(bad):
    with pytest.raises((QuantityError, TypeError)):
        parse_quantity(bad)


def test_format_roundtrip():
    assert format_cpu(100) == "100m"
    assert format_cpu(2000) == "2"
    assert format_memory(2**30) == "1Gi"
    assert parse_memory(format_memory(524288000)) == 524288000
