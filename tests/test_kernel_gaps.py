"""Adversarial differential tests for round-1 kernel blind spots.

VERDICT #3: the kernel silently lacked (a) in-batch inter-pod (anti-)affinity
between *pending* pods, (b) soft InterPodAffinityPriority
(interpod_affinity.go:86-216), and (c) the volume trio
(NoDiskConflict/MaxPDVolumeCount/VolumeZone, predicates.go:105-347). These
tests were written to FAIL against the round-1 kernel before the fix; each
constructs a cluster where the missing feature changes the binding.
"""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.batch import (
    ListPodLister, ListServiceLister, make_plugin_args, oracle_batch, tpu_batch,
)


def mk_node(name, cpu="4", mem="32Gi", pods="110", labels=None, taints=None):
    labels = dict(labels or {})
    labels.setdefault(api.LABEL_HOSTNAME, name)
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


def mk_pod(name, ns="default", cpu=None, mem=None, labels=None, node="",
           affinity=None, volumes=None):
    requests = {}
    if cpu:
        requests["cpu"] = cpu
    if mem:
        requests["memory"] = mem
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(
            node_name=node, affinity=affinity, volumes=volumes,
            containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(requests=requests)
                if requests else None)]))


def anti(match_labels, topology_key=""):
    return api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
        required_during_scheduling_ignored_during_execution=[
            api.PodAffinityTerm(
                label_selector=api.LabelSelector(match_labels=match_labels),
                topology_key=topology_key)]))


def aff(match_labels, topology_key=""):
    return api.Affinity(pod_affinity=api.PodAffinity(
        required_during_scheduling_ignored_during_execution=[
            api.PodAffinityTerm(
                label_selector=api.LabelSelector(match_labels=match_labels),
                topology_key=topology_key)]))


def pref(match_labels, topology_key="", weight=100, anti_=False):
    wt = [api.WeightedPodAffinityTerm(
        weight=weight,
        pod_affinity_term=api.PodAffinityTerm(
            label_selector=api.LabelSelector(match_labels=match_labels),
            topology_key=topology_key))]
    if anti_:
        return api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            preferred_during_scheduling_ignored_during_execution=wt))
    return api.Affinity(pod_affinity=api.PodAffinity(
        preferred_during_scheduling_ignored_during_execution=wt))


def gce_vol(pd, ro=False):
    return api.Volume(name=pd, gce_persistent_disk=
                      api.GCEPersistentDiskVolumeSource(pd_name=pd, read_only=ro))


def ebs_vol(vid):
    return api.Volume(name=vid, aws_elastic_block_store=
                      api.AWSElasticBlockStoreVolumeSource(volume_id=vid))


def pvc_vol(claim):
    return api.Volume(name=claim, persistent_volume_claim=
                      api.PersistentVolumeClaimVolumeSource(claim_name=claim))


def two_args(nodes, existing=(), services=(), pvcs=(), pvs=()):
    pvc_map = {f"{p.metadata.namespace}/{p.metadata.name}": p for p in pvcs}
    pv_map = {p.metadata.name: p for p in pvs}

    def mk():
        return make_plugin_args(
            nodes, pod_lister=ListPodLister(list(existing)),
            service_lister=ListServiceLister(services),
            pvc_lookup=lambda ns, name: pvc_map.get(f"{ns}/{name}"),
            pv_lookup=pv_map.get)
    return mk(), mk()


def assert_same(nodes, existing, pending, args_oracle, args_tpu, **kw):
    got_oracle = oracle_batch(nodes, existing, pending, args_oracle, **kw)
    got_tpu = tpu_batch(nodes, existing, pending, args_tpu)
    assert got_tpu == got_oracle, (
        f"kernel disagrees with oracle:\n  oracle: {got_oracle}\n"
        f"  tpu:    {got_tpu}")
    return got_oracle


class TestInBatchAntiAffinity:
    def test_zone_anti_affinity_caps_group(self):
        """3 pods anti-affine on zone, 2 zones: only 2 can place; the third
        is blocked by *in-batch* commits, which the round-1 kernel ignored."""
        nodes = [mk_node(f"n{i}", labels={api.LABEL_ZONE: f"z{i % 2}"})
                 for i in range(4)]
        pending = [mk_pod(f"p{i}", labels={"app": "db"},
                          affinity=anti({"app": "db"}, api.LABEL_ZONE))
                   for i in range(3)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got.count(None) == 1
        placed = [g for g in got if g]
        zones = {g[-1] for g in placed}  # n0/n2 -> z0, n1/n3 -> z1
        assert len(placed) == 2

    def test_hostname_anti_affinity_spreads(self):
        nodes = [mk_node(f"n{i}") for i in range(3)]
        pending = [mk_pod(f"p{i}", labels={"app": "db"},
                          affinity=anti({"app": "db"}, api.LABEL_HOSTNAME))
                   for i in range(4)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        placed = [g for g in got if g]
        assert len(placed) == 3 and len(set(placed)) == 3
        assert got.count(None) == 1

    def test_empty_topology_key_uses_failure_domains(self):
        """topology_key='' means any default failure-domain key
        (non_zero.go:87-109)."""
        nodes = [mk_node(f"n{i}", labels={api.LABEL_ZONE: "z0"})
                 for i in range(3)]
        pending = [mk_pod(f"p{i}", labels={"app": "db"},
                          affinity=anti({"app": "db"}))
                   for i in range(2)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        # same zone everywhere: second pod has nowhere to go
        assert got.count(None) == 1

    def test_symmetry_between_pending_pods(self):
        """Pod A's anti-affinity forbids later pod B that matches A's term
        (predicates.go:883-921 symmetry, applied in-batch)."""
        nodes = [mk_node("n0"), mk_node("n1", cpu="8")]
        pending = [
            mk_pod("a", labels={"app": "api"}, cpu="100m",
                   affinity=anti({"app": "web"}, api.LABEL_HOSTNAME)),
            mk_pod("b", labels={"app": "web"}, cpu="100m"),
        ]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got[0] is not None and got[1] is not None
        assert got[0] != got[1]


class TestInBatchAffinity:
    def test_follower_lands_with_leader(self):
        """B requires app=web on its node; only pending pod A provides it."""
        nodes = [mk_node(f"n{i}") for i in range(3)]
        pending = [
            mk_pod("a", labels={"app": "web"}, cpu="100m"),
            mk_pod("b", labels={"app": "api"}, cpu="100m",
                   affinity=aff({"app": "web"}, api.LABEL_HOSTNAME)),
        ]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got[1] == got[0]

    def test_self_affine_group_stays_in_one_zone(self):
        """First pod of a self-selecting group schedules via the disregard
        rule (predicates.go:818-844); the rest must join its domain."""
        nodes = [mk_node(f"n{i}", labels={api.LABEL_ZONE: f"z{i % 2}"},
                         cpu=("8" if i == 1 else "4"))
                 for i in range(4)]
        pending = [mk_pod(f"p{i}", labels={"app": "web"}, cpu="1",
                          affinity=aff({"app": "web"}, api.LABEL_ZONE))
                   for i in range(3)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert None not in got
        zones = {int(g[1]) % 2 for g in got}
        assert len(zones) == 1

    def test_affinity_to_existing_pod_still_works(self):
        nodes = [mk_node("n0"), mk_node("n1")]
        existing = [mk_pod("e", labels={"app": "web"}, node="n1")]
        pending = [mk_pod("p", labels={"app": "api"},
                          affinity=aff({"app": "web"}, api.LABEL_HOSTNAME))]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n1"]


class TestSoftInterPodAffinity:
    def test_preferred_affinity_to_existing_pod(self):
        """Weighted preference pulls the pod toward the cache's zone even
        when least-requested prefers elsewhere."""
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z0"}),
                 mk_node("n1", labels={api.LABEL_ZONE: "z1"}, cpu="8")]
        existing = [mk_pod("cache", labels={"app": "cache"}, node="n0",
                           cpu="500m")]
        pending = [mk_pod("p", labels={"app": "api"}, cpu="100m",
                          affinity=pref({"app": "cache"}, api.LABEL_ZONE))]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n0"]

    def test_preferred_anti_affinity_pushes_away(self):
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z0"}, cpu="8"),
                 mk_node("n1", labels={api.LABEL_ZONE: "z1"})]
        existing = [mk_pod("noisy", labels={"app": "noisy"}, node="n0",
                           cpu="100m")]
        pending = [mk_pod("p", labels={"app": "api"}, cpu="100m",
                          affinity=pref({"app": "noisy"}, api.LABEL_ZONE,
                                        anti_=True))]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n1"]

    def test_reverse_preference_from_existing_pod(self):
        """Existing pod's preferred affinity about the incoming pod counts
        too (interpod_affinity.go reverse direction)."""
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z0"}),
                 mk_node("n1", labels={api.LABEL_ZONE: "z1"}, cpu="8")]
        existing = [mk_pod("waiting", labels={"app": "waiting"}, node="n0",
                           cpu="500m",
                           affinity=pref({"app": "friend"}, api.LABEL_ZONE))]
        pending = [mk_pod("p", labels={"app": "friend"}, cpu="100m")]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n0"]

    def test_hard_affinity_symmetric_weight(self):
        """Existing pod's *hard* affinity terms matching the incoming pod add
        hardPodAffinityWeight (interpod_affinity.go:120-140)."""
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z0"}),
                 mk_node("n1", labels={api.LABEL_ZONE: "z1"}, cpu="8")]
        existing = [mk_pod("e", labels={"app": "leader"}, node="n0", cpu="500m",
                           affinity=aff({"app": "member"}, api.LABEL_ZONE))]
        pending = [mk_pod("p", labels={"app": "member"}, cpu="100m")]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n0"]

    def test_in_batch_soft_affinity(self):
        """B prefers A's zone; A is also pending (in-batch commit feeds the
        score)."""
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z0"}, cpu="3"),
                 mk_node("n1", labels={api.LABEL_ZONE: "z1"}, cpu="8")]
        pending = [
            mk_pod("a", labels={"app": "cache"}, cpu="2800m"),  # -> n1 (fits)
            mk_pod("b", labels={"app": "api"}, cpu="100m",
                   affinity=pref({"app": "cache"}, api.LABEL_ZONE,
                                 weight=100)),
        ]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got[1][-1] == got[0][-1]


class TestVolumePredicates:
    def test_gce_pd_conflict_with_existing(self):
        nodes = [mk_node("n0", cpu="8"), mk_node("n1")]
        existing = [mk_pod("e", node="n0", cpu="100m",
                           volumes=[gce_vol("data")])]
        pending = [mk_pod("p", cpu="100m", volumes=[gce_vol("data")])]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n1"]

    def test_gce_pd_both_read_only_ok(self):
        nodes = [mk_node("n0", cpu="8"), mk_node("n1")]
        existing = [mk_pod("e", node="n0", cpu="100m",
                           volumes=[gce_vol("data", ro=True)])]
        pending = [mk_pod("p", cpu="100m", volumes=[gce_vol("data", ro=True)])]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n0"]

    def test_in_batch_ebs_conflict(self):
        """Two pending pods share an EBS volume: the second must avoid the
        first's node."""
        nodes = [mk_node("n0"), mk_node("n1")]
        pending = [mk_pod("p0", cpu="100m", volumes=[ebs_vol("vol-1")]),
                   mk_pod("p1", cpu="100m", volumes=[ebs_vol("vol-1")])]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert None not in got
        assert got[0] != got[1]

    def test_max_ebs_volume_count(self):
        """Node at the 39-volume EBS attach limit rejects a pod with a new
        volume but accepts one reusing an attached volume."""
        nodes = [mk_node("full", cpu="64"), mk_node("empty")]
        existing = []
        vid = 0
        for i in range(4):
            count = 10 if i < 3 else 9
            existing.append(mk_pod(
                f"e{i}", node="full", cpu="100m",
                volumes=[ebs_vol(f"vol-{vid + j}") for j in range(count)]))
            vid += count
        assert vid == 39
        # "reuse" needs 8 cores so "empty" (4 cores) can't take it, and on
        # "full" NoDiskConflict forbids sharing an attached EBS volume: the
        # attach-count reuse exemption never helps EBS, so it goes nowhere
        pending = [mk_pod("new", cpu="100m", volumes=[ebs_vol("vol-new")]),
                   mk_pod("reuse", cpu="8", volumes=[ebs_vol("vol-0")])]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got[0] == "empty"
        assert got[1] is None

    def test_max_gce_volume_reuse_read_only(self):
        """Node at the 16-volume GCE attach limit rejects a pod bringing a
        new disk but accepts one re-mounting an attached disk read-only
        (reused volumes don't count against the limit, and both-read-only
        shares pass NoDiskConflict)."""
        nodes = [mk_node("full", cpu="64"), mk_node("empty")]
        existing = [
            mk_pod(f"e{i}", node="full", cpu="100m",
                   volumes=[gce_vol(f"disk-{i * 8 + j}", ro=True)
                            for j in range(8)])
            for i in range(2)]
        # "reuse" needs 8 cores so only "full" can take it: scheduling there
        # proves the attached-disk reuse is exempt from the count
        pending = [mk_pod("new", cpu="100m", volumes=[gce_vol("disk-new")]),
                   mk_pod("reuse", cpu="8",
                          volumes=[gce_vol("disk-0", ro=True)])]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got[0] == "empty"
        assert got[1] == "full"

    def test_volume_zone_conflict(self):
        pvs = [api.PersistentVolume(
            metadata=api.ObjectMeta(
                name="pv-z0", labels={api.LABEL_ZONE: "z0"}),
            spec=api.PersistentVolumeSpec(
                gce_persistent_disk=api.GCEPersistentDiskVolumeSource(
                    pd_name="disk0")))]
        pvcs = [api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="claim0", namespace="default"),
            spec=api.PersistentVolumeClaimSpec(volume_name="pv-z0"))]
        nodes = [mk_node("n0", labels={api.LABEL_ZONE: "z1"}, cpu="8"),
                 mk_node("n1", labels={api.LABEL_ZONE: "z0"})]
        pending = [mk_pod("p", cpu="100m", volumes=[pvc_vol("claim0")])]
        a, b = two_args(nodes, pvcs=pvcs, pvs=pvs)
        got = assert_same(nodes, [], pending, a, b)
        assert got == ["n1"]

    def test_unbound_pvc_unschedulable(self):
        pvcs = [api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name="pending-claim", namespace="default"),
            spec=api.PersistentVolumeClaimSpec())]
        nodes = [mk_node("n0")]
        pending = [mk_pod("p", cpu="100m", volumes=[pvc_vol("pending-claim")])]
        a, b = two_args(nodes, pvcs=pvcs)
        got = assert_same(nodes, [], pending, a, b)
        assert got == [None]


class TestMixedStress:
    def test_random_cluster_with_all_features(self):
        import random
        rng = random.Random(7)
        nodes = [mk_node(f"n{i:02d}",
                         labels={api.LABEL_ZONE: f"z{i % 3}"},
                         cpu=rng.choice(["2", "4", "8"]))
                 for i in range(12)]
        apps = ["web", "db", "cache"]
        pending = []
        for i in range(30):
            app = rng.choice(apps)
            affinity = None
            volumes = None
            roll = rng.random()
            if roll < 0.2:
                affinity = anti({"app": app}, api.LABEL_ZONE)
            elif roll < 0.35:
                affinity = aff({"app": rng.choice(apps)}, api.LABEL_ZONE)
            elif roll < 0.5:
                affinity = pref({"app": rng.choice(apps)}, api.LABEL_ZONE,
                                weight=rng.choice([10, 50]),
                                anti_=rng.random() < 0.5)
            elif roll < 0.6:
                volumes = [ebs_vol(f"vol-{rng.randrange(6)}")]
            pending.append(mk_pod(f"p{i:02d}", labels={"app": app},
                                  cpu=rng.choice(["100m", "500m"]),
                                  affinity=affinity, volumes=volumes))
        a, b = two_args(nodes)
        assert_same(nodes, [], pending, a, b)
