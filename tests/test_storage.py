"""Store contracts: versioning, CAS, watch window semantics (reference
pkg/storage interfaces + watch cache behavior).

Parameterized over every L0 the registry can mount — MemStore,
DurableStore, and the quorum ReplicatedStore — because the contract IS
the acceptance bar for the replication layer: one monotonic
resourceVersion, CAS update/guaranteed_update, bounded watch window with
410. A store that needs its own copy of these tests has already diverged.
"""

import threading

import pytest

from kubernetes_tpu.storage import (
    ADDED, DELETED, MODIFIED, Conflict, DurableStore, KeyExists,
    KeyNotFound, MemStore, ReplicatedStore, TooOldResourceVersion,
)


@pytest.fixture(params=["mem", "durable", "replicated"])
def make_store(request, tmp_path):
    """Factory fixture: make_store(window=..., watcher_queue=...) builds a
    fresh store of the parameterized kind; teardown closes them all."""
    created = []
    seq = [0]

    def factory(**kw):
        seq[0] += 1
        if request.param == "mem":
            s = MemStore(**kw)
        elif request.param == "durable":
            s = DurableStore(str(tmp_path / f"d{seq[0]}"), **kw)
        else:
            s = ReplicatedStore.local(str(tmp_path / f"r{seq[0]}"), **kw)
        created.append(s)
        return s

    yield factory
    for s in created:
        close = getattr(s, "close", None)
        if close is not None:
            close()


def test_create_get_versions(make_store):
    s = make_store()
    rv1 = s.create("/pods/default/a", {"x": 1})
    rv2 = s.create("/pods/default/b", {"x": 2})
    assert rv2 > rv1
    obj, rv = s.get("/pods/default/a")
    assert obj == {"x": 1} and rv == rv1
    with pytest.raises(KeyExists):
        s.create("/pods/default/a", {})
    with pytest.raises(KeyNotFound):
        s.get("/missing")


def test_returned_objects_are_copies(make_store):
    s = make_store()
    s.create("/k", {"nested": {"a": 1}})
    obj, _ = s.get("/k")
    obj["nested"]["a"] = 99
    assert s.get("/k")[0]["nested"]["a"] == 1


def test_list_prefix_and_snapshot_rv(make_store):
    s = make_store()
    s.create("/pods/ns1/a", {"n": "a"})
    s.create("/pods/ns2/b", {"n": "b"})
    s.create("/nodes/n1", {"n": "n1"})
    items, rv = s.list("/pods/")
    assert [o["n"] for o, _ in items] == ["a", "b"]
    assert rv == s.current_rv
    items, _ = s.list("/pods/ns1/")
    assert len(items) == 1


def test_cas_update(make_store):
    s = make_store()
    rv = s.create("/k", {"v": 0})
    s.update("/k", {"v": 1}, expect_rv=rv)
    with pytest.raises(Conflict):
        s.update("/k", {"v": 2}, expect_rv=rv)  # stale
    assert s.get("/k")[0] == {"v": 1}
    s.update("/k", {"v": 3})  # unconditional
    assert s.get("/k")[0] == {"v": 3}


def test_guaranteed_update(make_store):
    s = make_store()
    s.create("/k", {"v": 0})
    obj, rv = s.guaranteed_update("/k", lambda o, _rv: {**o, "v": o["v"] + 1})
    assert obj["v"] == 1
    # fn returning None = no-op
    obj2, rv2 = s.guaranteed_update("/k", lambda o, _rv: None)
    assert obj2["v"] == 1 and rv2 == rv


def test_guaranteed_update_concurrent(make_store):
    s = make_store()
    s.create("/counter", {"v": 0})
    n_threads, n_incr = 8, 25

    def work():
        for _ in range(n_incr):
            s.guaranteed_update("/counter", lambda o, _rv: {**o, "v": o["v"] + 1})

    ts = [threading.Thread(target=work) for _ in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert s.get("/counter")[0]["v"] == n_threads * n_incr


def test_delete_and_event(make_store):
    s = make_store()
    s.create("/k", {"v": 1})
    w = s.watch("/", since_rv=0)
    obj, rv = s.delete("/k")
    assert obj == {"v": 1}
    evs = [w.next(timeout=1) for _ in range(2)]
    assert [e.type for e in evs] == [ADDED, DELETED]
    assert evs[1].obj == {"v": 1}  # deleted events carry final state
    w.stop()


class TestWatch:
    def test_live_stream(self, make_store):
        s = make_store()
        w = s.watch("/pods/")
        s.create("/pods/ns/a", {"n": "a"})
        s.update("/pods/ns/a", {"n": "a2"})
        s.create("/nodes/x", {})  # outside prefix: not delivered
        e1, e2 = w.next(timeout=1), w.next(timeout=1)
        assert (e1.type, e1.obj["n"]) == (ADDED, "a")
        assert (e2.type, e2.obj["n"]) == (MODIFIED, "a2")
        assert w.next(timeout=0.05) is None
        w.stop()

    def test_replay_from_rv(self, make_store):
        s = make_store()
        rv1 = s.create("/pods/ns/a", {"n": "a"})
        s.create("/pods/ns/b", {"n": "b"})
        w = s.watch("/pods/", since_rv=rv1)
        ev = w.next(timeout=1)
        assert ev.obj["n"] == "b" and ev.rv > rv1
        w.stop()

    def test_watch_from_current_rv_sees_nothing_old(self, make_store):
        s = make_store()
        s.create("/pods/ns/a", {})
        w = s.watch("/pods/", since_rv=s.current_rv)
        assert w.next(timeout=0.05) is None
        w.stop()

    def test_too_old_resource_version(self, make_store):
        s = make_store(window=4)
        for i in range(10):
            s.create(f"/pods/ns/p{i}", {"i": i})
        with pytest.raises(TooOldResourceVersion):
            s.watch("/pods/", since_rv=1)
        # within the window is fine
        w = s.watch("/pods/", since_rv=s.current_rv - 2)
        assert w.next(timeout=1) is not None
        w.stop()

    def test_compaction_forces_relist(self, make_store):
        s = make_store()
        rv = s.create("/pods/ns/a", {})
        s.create("/pods/ns/b", {})
        s.compact()
        with pytest.raises(TooOldResourceVersion):
            s.watch("/pods/", since_rv=rv)

    def test_stop_unblocks_iteration(self, make_store):
        s = make_store()
        w = s.watch("/")
        got = []

        def consume():
            for ev in w:
                got.append(ev)

        t = threading.Thread(target=consume)
        t.start()
        s.create("/k", {})
        w.stop()
        t.join(timeout=2)
        assert not t.is_alive()
        assert len(got) == 1
