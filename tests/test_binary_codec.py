"""Binary wire codec + content negotiation (reference pkg/runtime/serializer/
protobuf: magic-prefixed envelope, application/vnd.kubernetes.protobuf)."""

import json
import time

import pytest

from kubernetes_tpu.api import binary_codec, types as api
from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


class TestCodecRoundTrip:
    def test_scalars_and_nesting(self):
        payload = {
            "apiVersion": "v1", "kind": "Pod",
            "int": 42, "neg": -7, "big": 2**40,
            "float": 3.25, "t": True, "f": False, "none": None,
            "str": "héllo", "list": [1, "two", {"three": 3}],
            "nested": {"a": {"b": {"c": []}}},
        }
        data = binary_codec.encode_dict(payload)
        assert data.startswith(binary_codec.MAGIC)
        assert binary_codec.decode_dict(data) == payload

    def test_pod_roundtrip_and_smaller_than_json(self):
        pod = api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="default",
                                    labels={"app": "x", "tier": "web"}),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(
                    requests={"cpu": "100m", "memory": "64Mi"}))]))
        d = scheme.encode(pod)
        data = binary_codec.encode_dict(d)
        assert binary_codec.decode_dict(data) == d
        assert len(data) < len(json.dumps(d).encode())

    def test_corrupt_inputs_raise(self):
        with pytest.raises(binary_codec.BinaryCodecError):
            binary_codec.decode_dict(b"not binary")
        ok = binary_codec.encode_dict({"apiVersion": "v1", "kind": "Pod"})
        with pytest.raises(binary_codec.BinaryCodecError):
            binary_codec.decode_dict(ok[:-2])  # truncated


class TestWireNegotiation:
    def _pod(self, name):
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace="default"),
            spec=api.PodSpec(containers=[api.Container(name="c",
                                                       image="pause")]))

    def test_binary_client_crud(self, server):
        c = RESTClient.for_server(server,
                                  content_type=binary_codec.CONTENT_TYPE)
        created = c.create("pods", self._pod("binpod"), "default")
        assert created.metadata.name == "binpod"
        got = c.get("pods", "binpod", "default")
        assert got.spec.containers[0].image == "pause"
        items, rv = c.list("pods", "default")
        assert [p.metadata.name for p in items] == ["binpod"]
        got.metadata.labels = {"x": "y"}
        updated = c.update("pods", got, "default")
        assert updated.metadata.labels == {"x": "y"}
        c.delete("pods", "binpod", "default")

    def test_binary_and_json_clients_interoperate(self, server):
        cb = RESTClient.for_server(server,
                                   content_type=binary_codec.CONTENT_TYPE)
        cj = RESTClient.for_server(server)
        cb.create("pods", self._pod("shared"), "default")
        assert cj.get("pods", "shared", "default").metadata.name == "shared"

    def test_binary_watch_stream(self, server):
        cb = RESTClient.for_server(server,
                                   content_type=binary_codec.CONTENT_TYPE)
        w = cb.watch("pods", "default")
        got = []
        import threading
        def reader():
            for etype, obj in w:
                got.append((etype, obj.metadata.name))
                if len(got) >= 2:
                    return
        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.2)
        cb.create("pods", self._pod("w1"), "default")
        cb.delete("pods", "w1", "default")
        t.join(timeout=10)
        w.stop()
        assert ("ADDED", "w1") in got
        assert ("DELETED", "w1") in got

    def test_error_status_in_binary(self, server):
        from kubernetes_tpu.client.rest import ApiError
        cb = RESTClient.for_server(server,
                                   content_type=binary_codec.CONTENT_TYPE)
        with pytest.raises(ApiError) as exc:
            cb.get("pods", "absent", "default")
        assert exc.value.code == 404
