"""Kubemark-style scale smoke: N hollow nodes on one shared informer, a
pending-pod wave pushed through the real scheduler, everything Running.
(The reference's scheduler_perf + kubemark pattern at CI-friendly scale;
bench.py covers the 30k/5k tensor path on hardware.)"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.factory import ConfigFactory


@pytest.mark.slow
def test_hollow_cluster_schedules_wave():
    server = APIServer().start()
    client = RESTClient.for_server(server, qps=5000, burst=5000)
    hollow = None
    sched = factory = None
    try:
        hollow = HollowCluster(client, num_nodes=30).start()
        nodes, _ = client.list("nodes")
        assert len(nodes) == 30

        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_from_provider().run()

        n_pods = 120
        t0 = time.monotonic()
        for i in range(n_pods):
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name=f"load-{i:04d}", namespace="default",
                                        labels={"app": "load"}),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m", "memory": "200Mi"}))])))

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            running = [p for p in pods
                       if p.status and p.status.phase == "Running"]
            if len(running) == n_pods:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"only {len(running)}/{n_pods} running within deadline")
        elapsed = time.monotonic() - t0

        # every pod placed on a hollow node, spread across many nodes
        by_node = {}
        for p in pods:
            by_node.setdefault(p.spec.node_name, 0)
            by_node[p.spec.node_name] += 1
        assert all(n.startswith("hollow-") for n in by_node)
        assert len(by_node) >= 25
        assert max(by_node.values()) <= 110
        print(f"\nkubemark smoke: {n_pods} pods on 30 hollow nodes in "
              f"{elapsed:.1f}s ({n_pods / elapsed:.0f} pods/s e2e)")
    finally:
        for c in (sched, factory, hollow):
            if c is not None:
                c.stop()
        server.stop()


@pytest.mark.slow
def test_hollow_cluster_saturation_250_nodes():
    """250 hollow nodes with a 10-pod cap, driven to FULL saturation by the
    batch scheduler: every node ends exactly at its cap and the next pod is
    unschedulable — the kubemark shape actually exercising the pods-per-node
    limit (cluster/kubemark/config-default.sh:26 analogue at CI scale)."""
    from concurrent.futures import ThreadPoolExecutor

    server = APIServer().start()
    client = RESTClient.for_server(server, qps=50000, burst=50000)
    hollow = sched = factory = None
    n_nodes, cap = 250, 10
    n_pods = n_nodes * cap
    try:
        hollow = HollowCluster(client, num_nodes=n_nodes, pods=str(cap)).start()
        with ThreadPoolExecutor(max_workers=16) as pool:
            list(pool.map(lambda i: client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name=f"sat-{i:04d}",
                                        namespace="default"),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "10m", "memory": "16Mi"}))]))),
                range(n_pods)))

        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=1024).run()

        deadline = time.monotonic() + 240
        bound = []
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            bound = [p for p in pods if p.spec.node_name]
            if len(bound) == n_pods:
                break
            time.sleep(0.3)
        assert len(bound) == n_pods, f"{len(bound)}/{n_pods} bound"

        by_node = {}
        for p in bound:
            by_node[p.spec.node_name] = by_node.get(p.spec.node_name, 0) + 1
        assert len(by_node) == n_nodes          # every node used
        assert set(by_node.values()) == {cap}   # all exactly at cap

        # saturated cluster: one more pod must be unschedulable
        client.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="overflow", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(name="c",
                                                       image="pause")])))
        deadline = time.monotonic() + 20
        cond = None
        while time.monotonic() < deadline:
            p = client.get("pods", "overflow", "default")
            if p.spec.node_name:
                raise AssertionError("overflow pod bound past the cap")
            conds = (p.status.conditions or []) if p.status else []
            cond = next((c for c in conds if c.type == api.POD_SCHEDULED), None)
            if cond is not None and cond.status == api.CONDITION_FALSE:
                break
            time.sleep(0.2)
        assert cond is not None and cond.reason == "Unschedulable"
    finally:
        for c in (sched, factory, hollow):
            if c is not None:
                c.stop()
        server.stop()
