"""Kubemark-style scale smoke: N hollow nodes on one shared informer, a
pending-pod wave pushed through the real scheduler, everything Running.
(The reference's scheduler_perf + kubemark pattern at CI-friendly scale;
bench.py covers the 30k/5k tensor path on hardware.)"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler.factory import ConfigFactory


@pytest.mark.slow
def test_hollow_cluster_schedules_wave():
    server = APIServer().start()
    client = RESTClient.for_server(server, qps=5000, burst=5000)
    hollow = None
    sched = factory = None
    try:
        hollow = HollowCluster(client, num_nodes=30).start()
        nodes, _ = client.list("nodes")
        assert len(nodes) == 30

        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_from_provider().run()

        n_pods = 120
        t0 = time.monotonic()
        for i in range(n_pods):
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name=f"load-{i:04d}", namespace="default",
                                        labels={"app": "load"}),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m", "memory": "200Mi"}))])))

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            running = [p for p in pods
                       if p.status and p.status.phase == "Running"]
            if len(running) == n_pods:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"only {len(running)}/{n_pods} running within deadline")
        elapsed = time.monotonic() - t0

        # every pod placed on a hollow node, spread across many nodes
        by_node = {}
        for p in pods:
            by_node.setdefault(p.spec.node_name, 0)
            by_node[p.spec.node_name] += 1
        assert all(n.startswith("hollow-") for n in by_node)
        assert len(by_node) >= 25
        assert max(by_node.values()) <= 110
        print(f"\nkubemark smoke: {n_pods} pods on 30 hollow nodes in "
              f"{elapsed:.1f}s ({n_pods / elapsed:.0f} pods/s e2e)")
    finally:
        for c in (sched, factory, hollow):
            if c is not None:
                c.stop()
        server.stop()
