"""Kubelet depth: probes, restart policy via PLEG, QoS memory eviction
(round-3 verdict #8 — reference pkg/kubelet/{prober,pleg,eviction},
pkg/probe, pkg/kubelet/qos)."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.pleg import CONTAINER_DIED, PLEG
from kubernetes_tpu.kubelet.qos import (
    BEST_EFFORT, BURSTABLE, GUARANTEED, qos_class,
)
from kubernetes_tpu.kubelet.runtime import FakeCadvisor, FakeRuntime


def mk_pod(name, node="n-0", cpu=None, limits=None, liveness=None,
           readiness=None, restart_policy=""):
    resources = None
    if cpu or limits:
        resources = api.ResourceRequirements(
            requests={"cpu": cpu} if cpu else None,
            limits=limits)
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PodSpec(
            node_name=node, restart_policy=restart_policy,
            containers=[api.Container(
                name="c", image="pause", resources=resources,
                liveness_probe=liveness, readiness_probe=readiness)]))


def exec_probe(period=1, failure_threshold=2, initial_delay=0):
    return api.Probe(exec=api.ExecAction(command=["check"]),
                     period_seconds=period,
                     failure_threshold=failure_threshold,
                     initial_delay_seconds=initial_delay)


class TestQoS:
    def test_classes(self):
        assert qos_class(mk_pod("a")) == BEST_EFFORT
        assert qos_class(mk_pod("b", cpu="100m")) == BURSTABLE
        g = api.Pod(spec=api.PodSpec(containers=[api.Container(
            name="c", resources=api.ResourceRequirements(
                requests={"cpu": "1", "memory": "1Gi"},
                limits={"cpu": "1", "memory": "1Gi"}))]),
            metadata=api.ObjectMeta(name="g"))
        assert qos_class(g) == GUARANTEED

    def test_extended_resource_only_pod_agrees_with_scheduler(self):
        """A TPU/GPU-only pod must classify identically for eviction ranking
        (here) and CheckNodeMemoryPressure (scheduler) — divergence caused an
        evict/reschedule loop."""
        from kubernetes_tpu.scheduler.predicates import is_best_effort
        p = api.Pod(
            metadata=api.ObjectMeta(name="tpu", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(
                name="c", resources=api.ResourceRequirements(
                    requests={api.RESOURCE_GPU: "1"}))]))
        assert not is_best_effort(p)
        assert qos_class(p) != BEST_EFFORT


class TestPLEG:
    def test_death_and_restart_events(self):
        rt = FakeRuntime()
        pleg = PLEG(rt)
        p = mk_pod("x")
        rt.sync_pod(p)
        assert pleg.relist() == []
        rt.kill_container("default/x", "c")
        evs = pleg.relist()
        assert len(evs) == 1 and evs[0].type == CONTAINER_DIED
        assert pleg.relist() == []          # no repeat for the same death
        rt.restart_container("default/x", "c")
        assert [e.type for e in pleg.relist()] == ["ContainerStarted"]


@pytest.fixture()
def node_env():
    server = APIServer().start()
    client = RESTClient.for_server(server, qps=2000, burst=2000)
    kl = Kubelet(client, "n-0", runtime=FakeRuntime(),
                 cadvisor=FakeCadvisor(),
                 heartbeat_period=0.5, sync_period=0.2, eviction_period=0.3)
    kl.start()
    yield client, kl
    kl.stop()
    server.stop()


def wait_for(fn, timeout=20, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


def pod_status(client, name):
    return client.get("pods", name, "default").status


class TestProbesE2E:
    def test_readiness_gates_ready_condition(self, node_env):
        client, kl = node_env
        client.create("pods", mk_pod(
            "web", readiness=exec_probe(period=1, failure_threshold=1)))
        # starts Running but NOT ready (no successful probe yet -> then
        # the first exec success flips it)
        wait_for(lambda: (pod_status(client, "web") or api.PodStatus()).phase
                 == api.POD_RUNNING, msg="pod running")
        wait_for(lambda: any(
            c.type == api.POD_READY and c.status == api.CONDITION_TRUE
            for c in (pod_status(client, "web").conditions or [])),
            msg="ready after probe success")
        # probe starts failing -> unready without a restart
        kl.runtime.set_exec_result("default/web", "c", 1)
        wait_for(lambda: any(
            c.type == api.POD_READY and c.status == api.CONDITION_FALSE
            for c in (pod_status(client, "web").conditions or [])),
            msg="unready after probe failures")
        sts = pod_status(client, "web").container_statuses or []
        assert sts and sts[0].restart_count == 0

    def test_liveness_failure_restarts_with_count(self, node_env):
        client, kl = node_env
        client.create("pods", mk_pod(
            "app", liveness=exec_probe(period=0, failure_threshold=2)))
        wait_for(lambda: (pod_status(client, "app") or api.PodStatus()).phase
                 == api.POD_RUNNING, msg="pod running")
        kl.runtime.set_exec_result("default/app", "c", 1)

        def restarted():
            sts = pod_status(client, "app").container_statuses or []
            return sts and sts[0].restart_count >= 1
        wait_for(restarted, msg="liveness kill + restart with count")
        # the restart cleared the probe's exec override? no — it persists;
        # make it healthy again and the pod stays Running
        kl.runtime.set_exec_result("default/app", "c", 0)
        time.sleep(1.0)
        assert pod_status(client, "app").phase == api.POD_RUNNING

    def test_restart_policy_never_fails_pod(self, node_env):
        client, kl = node_env
        client.create("pods", mk_pod("once", restart_policy="Never"))
        wait_for(lambda: (pod_status(client, "once") or api.PodStatus()).phase
                 == api.POD_RUNNING, msg="pod running")
        kl.runtime.kill_container("default/once", "c")
        wait_for(lambda: pod_status(client, "once").phase == api.POD_FAILED,
                 msg="policy Never -> Failed")
        assert pod_status(client, "once").reason == "ContainersDied"


class TestEvictionE2E:
    def test_memory_pressure_evicts_by_qos_and_flips_condition(self, node_env):
        client, kl = node_env
        client.create("pods", mk_pod("burstable", cpu="100m"))
        client.create("pods", mk_pod("besteffort"))
        wait_for(lambda: len(kl.runtime.running()) == 2, msg="both running")

        kl.cadvisor.memory_pressure = True
        # BestEffort is the first victim
        wait_for(lambda: pod_status(client, "besteffort").reason == "Evicted",
                 msg="besteffort evicted")
        assert pod_status(client, "besteffort").phase == api.POD_FAILED

        # node condition flips for the scheduler's
        # CheckNodeMemoryPressure predicate
        def pressure_cond():
            n = client.get("nodes", "n-0")
            return any(c.type == api.NODE_MEMORY_PRESSURE
                       and c.status == api.CONDITION_TRUE
                       for c in (n.status.conditions or []))
        wait_for(pressure_cond, msg="MemoryPressure=True on node")

        # next interval: the burstable pod goes too
        wait_for(lambda: pod_status(client, "burstable").reason == "Evicted",
                 msg="burstable evicted next")

        kl.cadvisor.memory_pressure = False
        wait_for(lambda: not pressure_cond(), msg="pressure clears")

    def test_stale_running_event_cannot_resurrect_evicted_pod(self, node_env):
        """An informer event still carrying phase=Running (snapshotted before
        the eviction) must not re-admit the pod: the kubelet's own terminal
        record is authoritative."""
        client, kl = node_env
        client.create("pods", mk_pod("victim"))
        wait_for(lambda: "default/victim" in kl.runtime.running(),
                 msg="running")
        # snapshot the pod as the informer would have seen it pre-eviction
        stale = client.get("pods", "victim", "default")
        stale.status = stale.status or api.PodStatus()
        stale.status.phase = api.POD_RUNNING

        kl.cadvisor.memory_pressure = True
        wait_for(lambda: pod_status(client, "victim").reason == "Evicted",
                 msg="evicted")
        assert "default/victim" not in kl.runtime.running()

        kl._sync_pod(stale)  # the stale event arrives late
        time.sleep(0.5)
        assert "default/victim" not in kl.runtime.running(), "resurrected!"
        assert pod_status(client, "victim").phase == api.POD_FAILED

    def test_scheduler_keeps_besteffort_off_pressured_node(self, node_env):
        """The other half of the loop: with MemoryPressure=True, the batch
        scheduler refuses BestEffort pods for that node."""
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        client, kl = node_env
        kl.cadvisor.memory_pressure = True
        wait_for(lambda: any(
            c.type == api.NODE_MEMORY_PRESSURE and c.status == api.CONDITION_TRUE
            for c in (client.get("nodes", "n-0").status.conditions or [])),
            msg="pressure visible")
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=8).run()
        try:
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name="be", namespace="default"),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="pause")])))
            # unschedulable: the only node is under memory pressure
            def unschedulable():
                p = client.get("pods", "be", "default")
                conds = (p.status.conditions or []) if p.status else []
                return any(c.type == api.POD_SCHEDULED
                           and c.status == api.CONDITION_FALSE
                           for c in conds) and not p.spec.node_name
            wait_for(unschedulable, msg="BestEffort refused under pressure")
        finally:
            sched.stop()
            factory.stop()
