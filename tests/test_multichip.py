"""Sharded-equivalence: the kernel over a 2x4 device mesh must produce
IDENTICAL assignments to the unsharded run (round-3 verdict #7).

Runs on the conftest's 8-virtual-CPU-device mesh — the same layout
(ops/sharding.py) the driver's dryrun_multichip validates. Identical
bindings, not just "all placed": sharding may change reduction order, but
selectHost semantics (max + round-robin tie-break) must survive the
cross-shard collectives bit-for-bit.
"""

import random

import jax
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.kernel import schedule_batch
from kubernetes_tpu.ops.sharding import make_mesh, schedule_batch_sharded
from kubernetes_tpu.ops.tensorize import Tensorizer
from kubernetes_tpu.scheduler.batch import ListServiceLister, make_plugin_args

from tests.test_kernel_gaps import (
    aff, anti, ebs_vol, gce_vol, mk_node, mk_pod, pref,
)

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs the 8-device mesh")


def feature_cluster(n_nodes, n_pods, seed=0):
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        labels = {api.LABEL_ZONE: f"z{i % 8}"}
        if i % 10 == 0:
            labels["disk"] = "ssd"
        taints = ([api.Taint(key="ded", value="x", effect="NoSchedule")]
                  if i % 50 == 0 else None)
        nodes.append(mk_node(f"n{i:04d}", labels=labels, taints=taints))
    svc = api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(port=80)]))
    apps = ["web", "db", "cache"]
    pending = []
    for i in range(n_pods):
        app = rng.choice(apps)
        affinity = volumes = None
        roll = rng.random()
        if roll < 0.04:
            affinity = anti({"aa": f"g{i % 5}"}, api.LABEL_HOSTNAME)
        elif roll < 0.08:
            affinity = aff({"app": "web"}, api.LABEL_ZONE)
        elif roll < 0.12:
            affinity = pref({"app": rng.choice(apps)}, api.LABEL_ZONE,
                            weight=rng.choice([10, 50]),
                            anti_=rng.random() < 0.5)
        elif roll < 0.16:
            volumes = [ebs_vol(f"vol-{rng.randrange(8)}")]
        elif roll < 0.18:
            volumes = [gce_vol(f"pd-{rng.randrange(8)}", ro=True)]
        labels = {"app": app}
        if affinity and roll < 0.04:
            labels["aa"] = f"g{i % 5}"
        pending.append(mk_pod(f"p{i:05d}", labels=labels,
                              cpu="100m", mem="256Mi",
                              affinity=affinity, volumes=volumes))
    args = make_plugin_args(nodes, service_lister=ListServiceLister([svc]))
    return Tensorizer(plugin_args=args).build(nodes, [], pending)


@needs_8
class TestShardedEquivalence:
    # the big-shape tests pin the SERIAL program explicitly (wave=0):
    # SPMD-partitioning the wave program's while/cond body on 8 virtual
    # CPU devices costs minutes of XLA compile at these shapes — the
    # wave-under-mesh equivalence is pinned at a small shape below and at
    # the full shape by bench.py's detail.sharded equality assert on
    # real hardware
    def test_large_batch_identical_assignments(self):
        """>=512 pods / >=1k nodes, full feature mix, 2x4 mesh == 1 device."""
        ct = feature_cluster(n_nodes=1024, n_pods=512)
        unsharded = schedule_batch(ct, wave=0)
        sharded = schedule_batch_sharded(ct, make_mesh(8), wave=0)
        assert sharded == unsharded
        assert sum(1 for g in unsharded if g) >= 500  # meaningful placement

    def test_wave_commit_survives_sharding(self):
        """The wave-commit program over the mesh == unsharded wave ==
        serial, at a small full-feature shape (the big-shape wave proof
        runs on real hardware via bench detail.sharded)."""
        from kubernetes_tpu.ops.fixtures import feature_batch

        ct = feature_batch(n_nodes=48, n_pods=32, with_existing=True)
        serial = schedule_batch(ct, wave=0)
        wave_un = schedule_batch(ct, wave=16)
        wave_sh = schedule_batch_sharded(ct, make_mesh(8), wave=16)
        assert wave_un == serial
        assert wave_sh == serial

    def test_tie_breaking_survives_sharding(self):
        """All-identical nodes + no-request pods: every step is a full tie;
        the round-robin selection must pick the same hosts across shards."""
        nodes = [mk_node(f"t{i:03d}") for i in range(256)]
        pods = [mk_pod(f"q{i}") for i in range(64)]
        args = make_plugin_args(nodes)
        ct = Tensorizer(plugin_args=args).build(nodes, [], pods)
        unsharded = schedule_batch(ct, wave=0)
        sharded = schedule_batch_sharded(ct, make_mesh(8), wave=0)
        assert sharded == unsharded

    def test_bench_shape_with_existing_pod_carries(self):
        """Round-4 verdict #8: sharded == unsharded at bench-like shapes —
        >=2k nodes with the FULL carry surface traced, including the
        existing-pod sym/te tables (the driver's dryrun_multichip runs this
        same config; here it's pinned in the suite)."""
        from kubernetes_tpu.ops.fixtures import feature_batch
        from kubernetes_tpu.ops.kernel import features_of

        ct = feature_batch(n_nodes=2048, n_pods=384, with_existing=True)
        feats = features_of(ct)
        assert feats.sym and feats.te and feats.req and feats.anti \
            and feats.pref and feats.disk and feats.ebs and feats.gce \
            and feats.ports
        unsharded = schedule_batch(ct, wave=0)
        sharded = schedule_batch_sharded(ct, make_mesh(8), wave=0)
        assert sharded == unsharded
        assert all(g is not None for g in unsharded[: ct.n_real_pods])

    def test_mesh_shapes(self):
        """1x8 and 2x4 meshes agree with each other and the single device."""
        import numpy as np
        from jax.sharding import Mesh

        ct = feature_cluster(n_nodes=256, n_pods=64, seed=3)
        unsharded = schedule_batch(ct, wave=0)
        m24 = make_mesh(8)
        assert dict(zip(m24.axis_names, m24.devices.shape)) == {
            "pods": 2, "nodes": 4}
        m18 = Mesh(np.array(jax.devices()[:8]).reshape(1, 8),
                   ("pods", "nodes"))
        assert schedule_batch_sharded(ct, m24, wave=0) == unsharded
        assert schedule_batch_sharded(ct, m18, wave=0) == unsharded
