"""Scheduler integration: full shell against a live in-process API server —
the reference's test/integration/scheduler_test.go pattern, including the
minimum end-to-end slice (BASELINE config #1: 100 pods / 10 nodes)."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.scheduler.factory import ConfigFactory


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=5000, burst=5000)


def mk_pod(name, cpu="100m", mem="500Mi", ns="default", scheduler_name="",
           selector=None):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.PodSpec(
            scheduler_name=scheduler_name,
            node_selector=selector,
            containers=[api.Container(
                name="c", image="pause",
                resources=api.ResourceRequirements(
                    requests={"cpu": cpu, "memory": mem}))]))


def mk_node(name, cpu="4", mem="32Gi", pods="110", labels=None, ready=True):
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[api.NodeCondition(
                type="Ready", status="True" if ready else "False")]))


def wait_scheduled(client, n, ns="default", timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pods, _ = client.list("pods", ns)
        done = [p for p in pods if p.spec.node_name]
        if len(done) >= n:
            return done
        time.sleep(0.05)
    raise AssertionError(f"only {len(done)}/{n} pods scheduled in {timeout}s")


@pytest.fixture()
def running_scheduler(server, client):
    factory = ConfigFactory(client)
    factory.run()
    sched = factory.create_from_provider().run()
    yield factory, sched
    sched.stop()
    factory.stop()


class TestSchedulerE2E:
    def test_schedules_pending_pod(self, client, running_scheduler):
        client.create("nodes", mk_node("n1"))
        client.create("pods", mk_pod("p1"))
        done = wait_scheduled(client, 1)
        assert done[0].spec.node_name == "n1"
        conds = {c.type: c.status for c in done[0].status.conditions}
        assert conds["PodScheduled"] == "True"

    def test_unschedulable_then_recovers(self, client, running_scheduler):
        """No nodes -> FailedScheduling + condition; node appears -> pod lands
        (the reference's integration unschedulable-node cases)."""
        client.create("pods", mk_pod("stuck"))
        deadline = time.monotonic() + 10
        cond = None
        while time.monotonic() < deadline:
            pod = client.get("pods", "stuck", "default")
            for c in (pod.status.conditions or []):
                if c.type == "PodScheduled" and c.status == "False":
                    cond = c
                    break
            if cond:
                break
            time.sleep(0.05)
        assert cond is not None and cond.reason == "Unschedulable"
        client.create("nodes", mk_node("late-node"))
        done = wait_scheduled(client, 1, timeout=15)  # backoff retry (~1s)
        assert done[0].spec.node_name == "late-node"

    def test_not_ready_node_excluded(self, client, running_scheduler):
        client.create("nodes", mk_node("bad", ready=False))
        client.create("nodes", mk_node("good"))
        client.create("pods", mk_pod("p"))
        assert wait_scheduled(client, 1)[0].spec.node_name == "good"

    def test_respects_node_selector(self, client, running_scheduler):
        client.create("nodes", mk_node("plain"))
        client.create("nodes", mk_node("ssd", labels={"disk": "ssd"}))
        client.create("pods", mk_pod("picky", selector={"disk": "ssd"}))
        assert wait_scheduled(client, 1)[0].spec.node_name == "ssd"

    def test_capacity_spreads_pods(self, client, running_scheduler):
        """Nodes fill up: pods overflow to the emptier node."""
        client.create("nodes", mk_node("n1", cpu="1", pods="2"))
        client.create("nodes", mk_node("n2", cpu="4", pods="110"))
        for i in range(6):
            client.create("pods", mk_pod(f"p{i}", cpu="500m"))
        done = wait_scheduled(client, 6)
        by_node = {}
        for p in done:
            by_node.setdefault(p.spec.node_name, []).append(p)
        assert len(by_node.get("n1", [])) <= 2
        assert len(by_node.get("n2", [])) >= 4

    def test_multi_scheduler_dispatch(self, client, running_scheduler):
        """Pods naming another scheduler are ignored (factory.go:426-432)."""
        client.create("nodes", mk_node("n1"))
        client.create("pods", mk_pod("mine"))
        client.create("pods", mk_pod("theirs", scheduler_name="other-scheduler"))
        wait_scheduled(client, 1)
        time.sleep(0.5)
        theirs = client.get("pods", "theirs", "default")
        assert not theirs.spec.node_name

    def test_events_recorded(self, client, running_scheduler):
        client.create("nodes", mk_node("n1"))
        client.create("pods", mk_pod("p1"))
        wait_scheduled(client, 1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            events, _ = client.list("events", "default")
            if any(e.reason == "Scheduled" for e in events):
                return
            time.sleep(0.05)
        raise AssertionError("no Scheduled event recorded")


class TestE2ESlice:
    def test_100_pods_10_nodes(self, server, client, running_scheduler):
        """BASELINE config #1: 100 pods / 10 nodes, PodFitsResources-capable
        default provider; all pods scheduled, no node overcommitted."""
        for i in range(10):
            client.create("nodes", mk_node(f"node-{i:02d}", cpu="4", mem="32Gi"))
        t0 = time.monotonic()
        for i in range(100):
            client.create("pods", mk_pod(f"pod-{i:03d}"))
        done = wait_scheduled(client, 100, timeout=60)
        elapsed = time.monotonic() - t0
        by_node = {}
        for p in done:
            by_node.setdefault(p.spec.node_name, 0)
            by_node[p.spec.node_name] += 1
        # capacity: 4000m/node, 100m/pod -> all fit; spreading should use
        # every node
        assert len(by_node) == 10
        assert sum(by_node.values()) == 100
        for node, count in by_node.items():
            assert count * 100 <= 4000, f"{node} overcommitted"
        print(f"\n100 pods / 10 nodes in {elapsed:.2f}s "
              f"({100 / elapsed:.0f} pods/s)")
