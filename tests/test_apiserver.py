"""API server integration: CRUD, selectors, watch streaming, bindings over
real HTTP sockets (the reference's httptest.Server pattern,
test/integration/framework/master_utils.go)."""

import http.client
import json
import threading

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.apiserver import APIServer


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


def req(server, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
    headers = {"Content-Type": "application/json"} if body is not None else {}
    conn.request(method, path, body=json.dumps(body) if body is not None else None,
                 headers=headers)
    resp = conn.getresponse()
    data = json.loads(resp.read() or b"{}")
    conn.close()
    return resp.status, data


def mk_pod_body(name, ns="default", labels=None, cpu="100m"):
    return scheme.encode(api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(requests={"cpu": cpu, "memory": "500Mi"}))])))


class TestCRUD:
    def test_create_get_list_delete(self, server):
        code, created = req(server, "POST", "/api/v1/namespaces/default/pods",
                            mk_pod_body("web-1", labels={"app": "web"}))
        assert code == 201
        assert created["metadata"]["uid"]
        assert created["metadata"]["resourceVersion"]
        assert created["status"]["phase"] == "Pending"

        code, got = req(server, "GET", "/api/v1/namespaces/default/pods/web-1")
        assert code == 200 and got["metadata"]["name"] == "web-1"

        code, lst = req(server, "GET", "/api/v1/namespaces/default/pods")
        assert code == 200 and lst["kind"] == "PodList" and len(lst["items"]) == 1
        assert int(lst["metadata"]["resourceVersion"]) >= 1

        code, _ = req(server, "DELETE", "/api/v1/namespaces/default/pods/web-1")
        assert code == 200
        code, _ = req(server, "GET", "/api/v1/namespaces/default/pods/web-1")
        assert code == 404

    def test_validation_422(self, server):
        bad = {"kind": "Pod", "apiVersion": "v1",
               "metadata": {"name": "x", "namespace": "default"},
               "spec": {"containers": []}}
        code, status = req(server, "POST", "/api/v1/namespaces/default/pods", bad)
        assert code == 422 and status["reason"] == "Invalid"

    def test_duplicate_409(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("a"))
        code, status = req(server, "POST", "/api/v1/namespaces/default/pods",
                           mk_pod_body("a"))
        assert code == 409 and status["reason"] == "AlreadyExists"

    def test_cluster_scoped_nodes(self, server):
        node = scheme.encode(api.Node(
            metadata=api.ObjectMeta(name="n1", labels={"zone": "us-a"}),
            status=api.NodeStatus(capacity={"cpu": "4", "memory": "8Gi", "pods": "110"})))
        code, _ = req(server, "POST", "/api/v1/nodes", node)
        assert code == 201
        code, lst = req(server, "GET", "/api/v1/nodes")
        assert code == 200 and len(lst["items"]) == 1

    def test_update_conflict_on_stale_rv(self, server):
        _, created = req(server, "POST", "/api/v1/namespaces/default/pods",
                         mk_pod_body("a", labels={"v": "1"}))
        stale = dict(created)
        # first update succeeds
        created["metadata"]["labels"] = {"v": "2"}
        code, _ = req(server, "PUT", "/api/v1/namespaces/default/pods/a", created)
        assert code == 200
        # stale rv now conflicts
        stale["metadata"]["labels"] = {"v": "3"}
        code, status = req(server, "PUT", "/api/v1/namespaces/default/pods/a", stale)
        assert code == 409 and status["reason"] == "Conflict"

    def test_label_and_field_selectors(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods",
            mk_pod_body("w1", labels={"app": "web"}))
        req(server, "POST", "/api/v1/namespaces/default/pods",
            mk_pod_body("d1", labels={"app": "db"}))
        code, lst = req(server, "GET",
                        "/api/v1/namespaces/default/pods?labelSelector=app%3Dweb")
        assert [i["metadata"]["name"] for i in lst["items"]] == ["w1"]
        # unassigned-pod selector, the scheduler's ListWatch
        code, lst = req(server, "GET",
                        "/api/v1/pods?fieldSelector=spec.nodeName%3D")
        assert len(lst["items"]) == 2

    def test_status_subresource(self, server):
        _, created = req(server, "POST", "/api/v1/namespaces/default/pods",
                         mk_pod_body("a"))
        created["status"] = {"phase": "Running"}
        code, updated = req(server, "PUT",
                            "/api/v1/namespaces/default/pods/a/status", created)
        assert code == 200 and updated["status"]["phase"] == "Running"

    def test_healthz_version(self, server):
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        conn.request("GET", "/healthz")
        assert conn.getresponse().read() == b"ok"
        conn.request("GET", "/version")
        assert b"gitVersion" in conn.getresponse().read()
        conn.request("GET", "/metrics")
        assert b"apiserver_request_seconds" in conn.getresponse().read()
        conn.close()


class TestBinding:
    def test_bind_sets_node_name_and_condition(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("p1"))
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "metadata": {"name": "p1", "namespace": "default"},
                   "target": {"kind": "Node", "name": "n1"}}
        code, _ = req(server, "POST", "/api/v1/namespaces/default/bindings", binding)
        assert code == 201
        _, pod = req(server, "GET", "/api/v1/namespaces/default/pods/p1")
        assert pod["spec"]["nodeName"] == "n1"
        conds = {c["type"]: c["status"] for c in pod["status"]["conditions"]}
        assert conds["PodScheduled"] == "True"

    def test_double_bind_conflicts(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("p1"))
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "metadata": {"name": "p1", "namespace": "default"},
                   "target": {"kind": "Node", "name": "n1"}}
        assert req(server, "POST", "/api/v1/namespaces/default/bindings", binding)[0] == 201
        # same node again: idempotent success
        assert req(server, "POST", "/api/v1/namespaces/default/bindings", binding)[0] == 201
        binding["target"]["name"] = "n2"
        code, status = req(server, "POST", "/api/v1/namespaces/default/bindings", binding)
        assert code == 409

    def test_pod_subresource_binding_route(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("p2"))
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "target": {"kind": "Node", "name": "n9"}}
        code, _ = req(server, "POST",
                      "/api/v1/namespaces/default/pods/p2/binding", binding)
        assert code == 201
        _, pod = req(server, "GET", "/api/v1/namespaces/default/pods/p2")
        assert pod["spec"]["nodeName"] == "n9"

    def test_bind_missing_pod_404(self, server):
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "metadata": {"name": "ghost", "namespace": "default"},
                   "target": {"kind": "Node", "name": "n1"}}
        code, _ = req(server, "POST", "/api/v1/namespaces/default/bindings", binding)
        assert code == 404


class TestWatchHTTP:
    def _open_watch(self, server, path):
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
        conn.request("GET", path)
        resp = conn.getresponse()
        assert resp.status == 200
        return conn, resp

    def test_watch_streams_events(self, server):
        _, lst = req(server, "GET", "/api/v1/pods")
        rv = lst["metadata"]["resourceVersion"]
        conn, resp = self._open_watch(
            server, f"/api/v1/pods?watch=true&resourceVersion={rv}")

        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("w1"))
        line = resp.readline()
        ev = json.loads(line)
        assert ev["type"] == "ADDED"
        assert ev["object"]["metadata"]["name"] == "w1"

        req(server, "DELETE", "/api/v1/namespaces/default/pods/w1")
        ev2 = json.loads(resp.readline())
        assert ev2["type"] == "DELETED"
        conn.close()

    def test_watch_replays_from_rv(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("old"))
        conn, resp = self._open_watch(
            server, "/api/v1/pods?watch=true&resourceVersion=0")
        ev = json.loads(resp.readline())
        assert ev["type"] == "ADDED" and ev["object"]["metadata"]["name"] == "old"
        conn.close()

    def test_watch_410_on_compacted_rv(self, server):
        for i in range(3):
            req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body(f"p{i}"))
        server.registry.store.compact()
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        conn.request("GET", "/api/v1/pods?watch=true&resourceVersion=1")
        resp = conn.getresponse()
        assert resp.status == 410
        conn.close()

    def test_filtered_watch_synthesizes_deleted_on_set_exit(self, server):
        """A pod leaving the selected set (unassigned -> bound) must appear
        as DELETED on a spec.nodeName= watch, else informer caches go stale
        (reference cacher/etcd_watcher transform)."""
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("p"))
        conn, resp = self._open_watch(
            server, "/api/v1/pods?watch=true&resourceVersion=0&fieldSelector=spec.nodeName%3D")
        ev = json.loads(resp.readline())
        assert ev["type"] == "ADDED"
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "metadata": {"name": "p", "namespace": "default"},
                   "target": {"kind": "Node", "name": "n1"}}
        req(server, "POST", "/api/v1/namespaces/default/bindings", binding)
        ev2 = json.loads(resp.readline())
        assert ev2["type"] == "DELETED"
        assert ev2["object"]["metadata"]["name"] == "p"
        conn.close()

    def test_filtered_watch_synthesizes_added_on_set_entry(self, server):
        req(server, "POST", "/api/v1/namespaces/default/pods",
            mk_pod_body("p", labels={"app": "old"}))
        conn, resp = self._open_watch(
            server, "/api/v1/pods?watch=true&labelSelector=app%3Dnew")
        _, got = req(server, "GET", "/api/v1/namespaces/default/pods/p")
        got["metadata"]["labels"] = {"app": "new"}
        req(server, "PUT", "/api/v1/namespaces/default/pods/p", got)
        ev = json.loads(resp.readline())
        assert ev["type"] == "ADDED"  # entered the selected set
        conn.close()

    def test_unsupported_field_key_400(self, server):
        code, status = req(server, "GET",
                           "/api/v1/pods?fieldSelector=spec.nodename%3Dn1")
        assert code == 400 and "not supported" in status["message"]

    def test_put_cannot_assign_node_name(self, server):
        _, created = req(server, "POST", "/api/v1/namespaces/default/pods",
                         mk_pod_body("p"))
        created["spec"]["nodeName"] = "sneaky"
        code, status = req(server, "PUT", "/api/v1/namespaces/default/pods/p",
                           created)
        assert code == 422 and "bindings subresource" in status["message"]

    def test_stale_status_write_409(self, server):
        _, created = req(server, "POST", "/api/v1/namespaces/default/pods",
                         mk_pod_body("p"))
        fresh = dict(created)
        fresh["status"] = {"phase": "Running"}
        assert req(server, "PUT", "/api/v1/namespaces/default/pods/p/status",
                   fresh)[0] == 200
        stale = dict(created)  # still carries the old resourceVersion
        stale["status"] = {"phase": "Pending"}
        code, _ = req(server, "PUT", "/api/v1/namespaces/default/pods/p/status",
                      stale)
        assert code == 409

    def test_watch_field_selector_filters(self, server):
        conn, resp = self._open_watch(
            server, "/api/v1/pods?watch=true&fieldSelector=spec.nodeName%3Dn1")
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("unsched"))
        req(server, "POST", "/api/v1/namespaces/default/pods", mk_pod_body("sched"))
        binding = {"kind": "Binding", "apiVersion": "v1",
                   "metadata": {"name": "sched", "namespace": "default"},
                   "target": {"kind": "Node", "name": "n1"}}
        req(server, "POST", "/api/v1/namespaces/default/bindings", binding)
        ev = json.loads(resp.readline())
        # only the bound pod's MODIFIED event passes the filter
        assert ev["object"]["metadata"]["name"] == "sched"
        assert ev["object"]["spec"]["nodeName"] == "n1"
        conn.close()
