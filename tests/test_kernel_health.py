"""Kernel failure classification and escalation (round-3 verdict #4).

A transient device outage and a deterministic kernel bug must diverge:
device errors retry with backoff and flip a visible "degraded" state after N
consecutive failures; a programming error disables the device path
permanently ("failed"), logs at ERROR, and with strict=True re-raises.
The reference analogue is HandleCrash-plus-healthz visibility — a component
that silently stops doing its job is the failure mode being closed
(plugin/cmd/kube-scheduler/app/server.go:92-108 healthz mux).
"""

import logging

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.scheduler.factory import ConfigFactory
from kubernetes_tpu.scheduler.tpu import (
    HEALTH_DEGRADED, HEALTH_FAILED, HEALTH_OK, _is_device_error,
)
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

from tests.test_batch_scheduler import mk_node, mk_pod, wait_scheduled


class XlaRuntimeError(RuntimeError):
    """Stand-in with the real jaxlib exception's type name (classification
    is by name so jaxlib needn't be imported on the hot path)."""


class TestClassification:
    def test_transient_xla_statuses_are_device_errors(self):
        assert _is_device_error(XlaRuntimeError("UNAVAILABLE: tunnel down"))
        assert _is_device_error(XlaRuntimeError("INTERNAL: core dumped"))
        assert _is_device_error(ConnectionError("refused"))
        assert _is_device_error(TimeoutError())
        assert _is_device_error(OSError("broken pipe"))

    def test_deterministic_errors_are_bugs(self):
        assert not _is_device_error(XlaRuntimeError(
            "INVALID_ARGUMENT: shape mismatch"))
        assert not _is_device_error(KeyError("req_hit0"))
        assert not _is_device_error(TypeError("bad arg"))
        assert not _is_device_error(RuntimeError(
            "kernel returned 3 results for 5 pods"))
        # OOM at a fixed batch shape reproduces every retry
        assert not _is_device_error(XlaRuntimeError(
            "RESOURCE_EXHAUSTED: out of memory allocating carry"))
        # a deterministic error QUOTING a transient token stays a bug
        assert not _is_device_error(XlaRuntimeError(
            "INVALID_ARGUMENT: op 'scan' state UNKNOWN shape"))


@pytest.fixture()
def cluster():
    server = APIServer().start()
    client = RESTClient.for_server(server, qps=5000, burst=5000)
    for i in range(4):
        client.create("nodes", mk_node(f"n-{i}"))
    factory = ConfigFactory(client)
    factory.run()
    yield client, factory
    factory.stop()
    server.stop()


def make_sched(factory, **kw):
    return factory.create_batch_from_provider(batch_size=64, **kw)


class TestEscalation:
    def test_deterministic_bug_disables_device_path(self, cluster, caplog):
        client, factory = cluster
        sched = make_sched(factory)
        calls = []

        def broken_kernel(nodes, existing, pending):
            calls.append(len(pending))
            raise TypeError("carry shape bug")

        sched._run_kernel = broken_kernel
        for i in range(8):
            client.create("pods", mk_pod(f"p-{i}"))
        with caplog.at_level(logging.ERROR, logger="scheduler.tpu"):
            sched.run()
            try:
                wait_scheduled(client, 8, timeout=30)
            finally:
                sched.stop()
        # fallback still placed every pod...
        assert sched.health == HEALTH_FAILED
        assert not sched.healthy()
        assert "carry shape bug" in (sched.disabled_reason or "")
        # ...but the bug surfaced at ERROR with a traceback, not a warning
        errors = [r for r in caplog.records if r.levelno >= logging.ERROR]
        assert errors and "DISABLED" in errors[0].getMessage()
        # the device path was tried exactly once, then never again
        assert len(calls) == 1
        assert not sched.kernel_available()
        assert METRICS.counter_value("scheduler_kernel_fallbacks_total",
                                     reason="bug") >= 1

    def test_device_errors_backoff_then_degrade_then_recover(self, cluster):
        client, factory = cluster
        sched = make_sched(factory)
        now = [100.0]
        sched._clock = lambda: now[0]
        fail = [True]
        calls = []

        real_kernel = sched._run_kernel

        def flaky_kernel(nodes, existing, pending):
            calls.append(len(pending))
            if fail[0]:
                raise XlaRuntimeError("UNAVAILABLE: device tunnel down")
            return real_kernel(nodes, existing, pending)

        sched._run_kernel = flaky_kernel

        # 3 consecutive device failures -> degraded, each with fallback
        # (one pod per round so each round drains a fresh one-pod batch)
        for k in range(3):
            client.create("pods", mk_pod(f"d-{k}"))
            now[0] += 1000  # jump past any backoff window
            n = 0
            while n == 0:
                n = sched.schedule_batch_once(timeout=2.0)
            assert sched.health == (HEALTH_DEGRADED if k == 2 else HEALTH_OK)
        assert len(calls) == 3
        assert sched._consecutive_device_errors == 3

        # inside the backoff window the kernel isn't even attempted
        assert not sched.kernel_available()
        client.create("pods", mk_pod("d-skip"))
        n = 0
        while n == 0:
            n = sched.schedule_batch_once(timeout=2.0)
        assert len(calls) == 3  # no new device attempt

        # past the window, a success resets health to ok
        fail[0] = False
        now[0] += 1000
        assert sched.kernel_available()
        client.create("pods", mk_pod("d-ok"))
        n = 0
        while n == 0:
            n = sched.schedule_batch_once(timeout=2.0)
        assert len(calls) == 4
        assert sched.health == HEALTH_OK
        assert sched._consecutive_device_errors == 0
        wait_scheduled(client, 5, timeout=10)

    def test_persistent_device_errors_escalate_to_failed(self, cluster):
        """A 'transient' status that reproduces fail_after times in a row is
        deterministic in practice — it must stop burning a device attempt
        per backoff window forever."""
        client, factory = cluster
        sched = make_sched(factory)
        sched._fail_after = 4
        now = [0.0]
        sched._clock = lambda: now[0]

        def down(*a):
            raise XlaRuntimeError("INTERNAL: tunnel reset")

        sched._run_kernel = down
        for k in range(4):
            client.create("pods", mk_pod(f"e-{k}"))
            now[0] += 1000
            n = 0
            while n == 0:
                n = sched.schedule_batch_once(timeout=2.0)
        assert sched.health == HEALTH_FAILED
        assert not sched.kernel_available()
        # labeled as an outage, not a kernel bug
        assert "persistent-device" in sched.disabled_reason
        assert METRICS.counter_value("scheduler_kernel_fallbacks_total",
                                     reason="persistent-device") >= 1
        # the failed state re-arms after the cooldown and can recover
        sched._run_kernel = real = sched.__class__._run_kernel.__get__(sched)
        client.create("pods", mk_pod("e-rec"))
        now[0] += 10_000
        assert sched.kernel_available()
        n = 0
        while n == 0:
            n = sched.schedule_batch_once(timeout=2.0)
        assert sched.health == HEALTH_OK and sched.disabled_reason is None
        wait_scheduled(client, 5, timeout=10)

    def test_strict_mode_reraises_bugs(self, cluster):
        client, factory = cluster
        sched = make_sched(factory, strict=True)
        sched._run_kernel = lambda *a: (_ for _ in ()).throw(
            KeyError("missing tensor"))
        client.create("pods", mk_pod("s-0"))
        with pytest.raises(KeyError):
            n = 0
            while n == 0:
                n = sched.schedule_batch_once(timeout=2.0)
