"""ResourceQuota, ServiceAccount/Tokens, GarbageCollector, PodGC, HPA
controllers (reference pkg/controller/{resourcequota,serviceaccount,
garbagecollector,gc,podautoscaler} behaviors)."""

import base64
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apis import autoscaling, extensions as ext
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.controllers.deployment_controller import DeploymentController
from kubernetes_tpu.controllers.garbagecollector import (
    GarbageCollector, PodGCController,
)
from kubernetes_tpu.controllers.podautoscaler import (
    ANN_CPU_UTILIZATION, HorizontalController,
)
from kubernetes_tpu.controllers.replicaset_controller import ReplicaSetController
from kubernetes_tpu.controllers.resourcequota_controller import (
    ResourceQuotaController,
)
from kubernetes_tpu.controllers.serviceaccounts_controller import (
    ServiceAccountsController, TokensController, generate_token,
)


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=2000, burst=2000)


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.03)
    raise AssertionError("condition not met")


def _template(labels):
    return api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels=dict(labels)),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")]))


def _pod(name, labels=None, cpu="100m", mem="64Mi"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels or {}),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(
                requests={"cpu": cpu, "memory": mem}))]))


class TestResourceQuotaController:
    def test_recalculates_usage(self, client):
        client.create("resourcequotas", api.ResourceQuota(
            metadata=api.ObjectMeta(name="quota", namespace="default"),
            spec=api.ResourceQuotaSpec(hard={"pods": "10", "cpu": "2"})),
            "default")
        ctrl = ResourceQuotaController(client, resync_seconds=0.2)
        ctrl.start()
        try:
            for i in range(3):
                client.create("pods", _pod(f"p{i}", cpu="100m"), "default")

            def usage_ok():
                q = client.get("resourcequotas", "quota", "default")
                u = (q.status.used or {}) if q.status else {}
                return u.get("pods") == "3" and u.get("cpu") == "300m"
            _wait(usage_ok)

            # deletion replenishes
            client.delete("pods", "p0", "default")
            _wait(lambda: (client.get("resourcequotas", "quota", "default")
                           .status.used or {}).get("pods") == "2")
        finally:
            ctrl.stop()


class TestServiceAccountControllers:
    def test_default_sa_created_and_recreated(self, client):
        sac = ServiceAccountsController(client)
        sac.start()
        try:
            client.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="team-a")))
            _wait(lambda: client.get("serviceaccounts", "default", "team-a"))
            client.delete("serviceaccounts", "default", "team-a")
            _wait(lambda: client.get("serviceaccounts", "default", "team-a"))
        finally:
            sac.stop()

    def test_token_secret_created_and_linked(self, client):
        tc = TokensController(client, signing_key=b"test-key")
        tc.start()
        try:
            client.create("serviceaccounts", api.ServiceAccount(
                metadata=api.ObjectMeta(name="robot", namespace="default")),
                "default")
            _wait(lambda: client.get("secrets", "robot-token", "default"))
            secret = client.get("secrets", "robot-token", "default")
            assert secret.type == api.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN
            token = base64.b64decode(secret.data["token"]).decode()
            assert token.count(".") == 2  # compact JWT
            # sa.secrets references the token secret
            _wait(lambda: any(r.name == "robot-token" for r in
                              (client.get("serviceaccounts", "robot",
                                          "default").secrets or [])))
        finally:
            tc.stop()

    def test_token_is_deterministic_hmac(self):
        t1 = generate_token(b"k", "ns", "sa", "uid1", "sa-token")
        t2 = generate_token(b"k", "ns", "sa", "uid1", "sa-token")
        assert t1 == t2
        assert generate_token(b"other", "ns", "sa", "uid1", "sa-token") != t1


class TestGarbageCollector:
    def test_cascade_deployment_to_pods(self, client):
        dc = DeploymentController(client)
        rsc = ReplicaSetController(client)
        gc = GarbageCollector(client)
        dc.start()
        rsc.start()
        gc.start()
        try:
            d = ext.Deployment(
                metadata=api.ObjectMeta(name="doomed", namespace="default"),
                spec=ext.DeploymentSpec(
                    replicas=2,
                    selector=api.LabelSelector(match_labels={"app": "doomed"}),
                    template=_template({"app": "doomed"})))
            client.create("deployments", d, "default")
            _wait(lambda: len(client.list("pods", "default",
                                          label_selector="app=doomed")[0]) == 2)
            # pods + RS carry ownerReferences
            rs = client.list("replicasets", "default")[0][0]
            assert rs.metadata.owner_references[0].kind == "Deployment"
            p = client.list("pods", "default",
                            label_selector="app=doomed")[0][0]
            assert p.metadata.owner_references[0].kind == "ReplicaSet"

            # stop the managing controllers so only GC acts, then delete
            dc.stop()
            rsc.stop()
            client.delete("deployments", "doomed", "default")
            _wait(lambda: len(client.list("replicasets", "default")[0]) == 0,
                  timeout=15)
            _wait(lambda: len(client.list("pods", "default",
                                          label_selector="app=doomed")[0]) == 0,
                  timeout=15)
        finally:
            gc.stop()

    def test_orphan_without_refs_untouched(self, client):
        gc = GarbageCollector(client)
        gc.start()
        try:
            client.create("pods", _pod("standalone"), "default")
            time.sleep(0.5)
            assert client.get("pods", "standalone", "default")
        finally:
            gc.stop()


class TestPodGC:
    def test_deletes_oldest_terminated_over_threshold(self, client):
        for i in range(5):
            p = _pod(f"dead-{i}")
            created = client.create("pods", p, "default")
            created.status = api.PodStatus(phase=api.POD_SUCCEEDED)
            client.update_status("pods", created)
        ctrl = PodGCController(client, threshold=2)
        ctrl.start()
        try:
            ctrl.enqueue(ctrl.KEY)
            _wait(lambda: len(client.list("pods", "default")[0]) == 2)
        finally:
            ctrl.stop()


class TestHorizontalController:
    def test_scales_up_on_high_utilization(self, client):
        rsc = ReplicaSetController(client)
        hpa_ctrl = HorizontalController(client, sync_seconds=0.2)
        rsc.start()
        hpa_ctrl.start()
        try:
            rs = api.ReplicaSet(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ReplicaSetSpec(
                    replicas=2,
                    selector=api.LabelSelector(match_labels={"app": "web"}),
                    template=_template({"app": "web"})))
            client.create("replicasets", rs, "default")
            _wait(lambda: len(client.list("pods", "default",
                                          label_selector="app=web")[0]) == 2)
            _wait(lambda: client.get("replicasets", "web", "default")
                  .status.replicas == 2)

            client.create("horizontalpodautoscalers",
                          autoscaling.HorizontalPodAutoscaler(
                              metadata=api.ObjectMeta(name="web-hpa",
                                                      namespace="default"),
                              spec=autoscaling.HorizontalPodAutoscalerSpec(
                                  scale_target_ref=autoscaling
                                  .CrossVersionObjectReference(
                                      kind="ReplicaSet", name="web"),
                                  min_replicas=1, max_replicas=10,
                                  target_cpu_utilization_percentage=50)),
                          "default")

            # pods report 100% utilization -> desired = ceil(2 * 100/50) = 4
            for p in client.list("pods", "default",
                                 label_selector="app=web")[0]:
                p.metadata.annotations = {ANN_CPU_UTILIZATION: "100"}
                client.update("pods", p, "default")

            _wait(lambda: client.get("replicasets", "web", "default")
                  .spec.replicas >= 4, timeout=40)
            # the controller scales the target first and writes HPA status
            # after — wait for the status write, don't race it
            _wait(lambda: (client.get("horizontalpodautoscalers", "web-hpa",
                                      "default").status or
                           autoscaling.HorizontalPodAutoscalerStatus())
                  .desired_replicas >= 4, timeout=20)
            hpa = client.get("horizontalpodautoscalers", "web-hpa", "default")
            assert hpa.status.desired_replicas >= 4
        finally:
            hpa_ctrl.stop()
            rsc.stop()

    def test_within_tolerance_no_scale(self, client):
        hpa_ctrl = HorizontalController(client, sync_seconds=0.2)
        rsc = ReplicaSetController(client)
        rsc.start()
        hpa_ctrl.start()
        try:
            rs = api.ReplicaSet(
                metadata=api.ObjectMeta(name="steady", namespace="default"),
                spec=api.ReplicaSetSpec(
                    replicas=2,
                    selector=api.LabelSelector(match_labels={"app": "steady"}),
                    template=_template({"app": "steady"})))
            client.create("replicasets", rs, "default")
            _wait(lambda: client.get("replicasets", "steady", "default")
                  .status.replicas == 2)
            client.create("horizontalpodautoscalers",
                          autoscaling.HorizontalPodAutoscaler(
                              metadata=api.ObjectMeta(name="steady-hpa",
                                                      namespace="default"),
                              spec=autoscaling.HorizontalPodAutoscalerSpec(
                                  scale_target_ref=autoscaling
                                  .CrossVersionObjectReference(
                                      kind="ReplicaSet", name="steady"),
                                  min_replicas=1, max_replicas=10,
                                  target_cpu_utilization_percentage=50)),
                          "default")
            for p in client.list("pods", "default",
                                 label_selector="app=steady")[0]:
                p.metadata.annotations = {ANN_CPU_UTILIZATION: "52"}  # within 10%
                client.update("pods", p, "default")
            time.sleep(1.0)
            assert client.get("replicasets", "steady", "default") \
                .spec.replicas == 2
        finally:
            hpa_ctrl.stop()
            rsc.stop()
