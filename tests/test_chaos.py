"""Fault injection: the chaos client and crash-recovery chaos tests.

Parity targets:
- pkg/client/chaosclient/chaosclient.go — probabilistic transport faults
- plugin/pkg/scheduler/schedulercache/cache.go:278-308 — assumed-pod TTL
  self-repair: a scheduler that dies (or loses its binds) between AssumePod
  and a landed binding must not lose pods or double-bind them; the system
  recovers by timeout + re-list, not rollback (SURVEY §5).
"""

import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.chaos import (
    ChaosConnectionReset, HTTPError, Latency, NetworkError, PathChaos,
    Probability, Times, install_chaos,
)
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.scheduler.factory import ConfigFactory, Scheduler

from tests.test_scheduler_e2e import mk_node, mk_pod, wait_scheduled


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=5000, burst=5000)


class TestChaosChain:
    def test_network_error_raises_simulated_reset(self, server):
        c = RESTClient.for_server(server)
        install_chaos(c, NetworkError())
        with pytest.raises(ChaosConnectionReset):
            c.list("pods", "default")

    def test_http_error_surfaces_as_api_error(self, server):
        c = RESTClient.for_server(server)
        install_chaos(c, HTTPError(503, "ServiceUnavailable"))
        with pytest.raises(ApiError) as ei:
            c.list("pods", "default")
        assert ei.value.code == 503

    def test_probability_is_seeded_and_deterministic(self, server):
        def run(seed):
            c = RESTClient.for_server(server)
            ctl = install_chaos(c, Probability(0.5, NetworkError()), seed=seed)
            outcomes = []
            for _ in range(40):
                try:
                    c.list("pods", "default")
                    outcomes.append(True)
                except ChaosConnectionReset:
                    outcomes.append(False)
            return outcomes, ctl.count()

        a, na = run(7)
        b, nb = run(7)
        other, _ = run(8)
        assert a == b and na == nb
        assert a != other  # different seed, different fault pattern
        assert 0 < na < 40  # actually probabilistic

    def test_path_scoping_only_hits_matching_requests(self, server):
        c = RESTClient.for_server(server)
        ctl = install_chaos(
            c, PathChaos(r"/bindings$", NetworkError(), methods={"POST"}))
        c.list("pods", "default")  # unaffected
        c.create("nodes", mk_node("n1"))  # unaffected
        binding = api.Binding(
            metadata=api.ObjectMeta(name="p", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n1"))
        with pytest.raises(ChaosConnectionReset):
            c.bind(binding, "default")
        assert ctl.count("NetworkError") == 1
        assert [(m, p) for _, m, p in ctl.interventions] == [
            ("POST", "/api/v1/namespaces/default/bindings")]

    def test_injected_429_retries_like_a_real_shed(self, server):
        """A chaos 429 must follow the real seam's contract: RESTClient
        retries flow-control sheds with backoff instead of raising — so a
        bounded 429 outage recovers transparently."""
        c = RESTClient.for_server(server)
        ctl = install_chaos(c, Times(2, HTTPError(429, "TooManyRequests")))
        c.list("pods", "default")  # retried through the injected sheds
        assert ctl.count("HTTPError(429)") == 2

    def test_injected_500_raises_without_retry(self, server):
        c = RESTClient.for_server(server)
        ctl = install_chaos(c, Times(1, HTTPError(500)))
        with pytest.raises(ApiError) as ei:
            c.list("pods", "default")
        assert ei.value.code == 500 and ctl.count() == 1

    def test_uninstall_heals(self, server):
        c = RESTClient.for_server(server)
        ctl = install_chaos(c, NetworkError())
        with pytest.raises(ChaosConnectionReset):
            c.list("pods", "default")
        ctl.uninstall()
        c.list("pods", "default")  # healed

    def test_latency_passes_through(self, server):
        c = RESTClient.for_server(server)
        install_chaos(c, Latency(0.05))
        t0 = time.monotonic()
        c.list("pods", "default")
        assert time.monotonic() - t0 >= 0.05

    def test_notifier_sees_interventions(self, server):
        c = RESTClient.for_server(server)
        seen = []
        install_chaos(c, HTTPError(500),
                      notifier=lambda iv, m, p: seen.append((iv.source, m)))
        with pytest.raises(ApiError):
            c.get("pods", "x", "default")
        assert seen == [("HTTPError(500)", "GET")]


class TestReflectorUnderChaos:
    def test_informer_syncs_through_flaky_transport(self, server, client):
        """A 30%-lossy client (lists AND watch opens fail) must still
        converge: the Reflector's retry/re-list loop is the recovery path."""
        for i in range(5):
            client.create("nodes", mk_node(f"n{i}"))
        flaky = RESTClient.for_server(server)
        install_chaos(flaky, Probability(0.3, NetworkError()), seed=3)
        inf = Informer(ListWatch(flaky, "nodes"), relist_backoff=0.05)
        inf.run()
        try:
            assert inf.wait_for_sync(10)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(inf.store.list_keys()) == 5:
                    break
                time.sleep(0.05)
            assert len(inf.store.list_keys()) == 5
            # and incremental events keep flowing post-sync
            client.create("nodes", mk_node("late"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if inf.store.get("late") is not None:
                    return
                time.sleep(0.05)
            raise AssertionError("late node never arrived through chaos")
        finally:
            inf.stop()


class _BindDroppingScheduler(Scheduler):
    """A scheduler whose process 'dies' between AssumePod and Bind: decisions
    are made and assumed, but the binding never leaves the box. Captures the
    decisions so the test can replay them later as a zombie binder."""

    def __init__(self, factory, algorithm):
        super().__init__(factory, algorithm)
        self.dropped = []
        self._dropped_lock = threading.Lock()

    def _spawn_bind(self, pod, dest, t_start, did_assume):
        with self._dropped_lock:
            self.dropped.append((pod, dest))


class TestSchedulerCrashMidBatch:
    def _fill(self, client, n_nodes=4, n_pods=12):
        for i in range(n_nodes):
            client.create("nodes", mk_node(f"n{i}", cpu="2", pods="5"))
        for i in range(n_pods):
            client.create("pods", mk_pod(f"p{i:02d}", cpu="500m"))

    def _wait_drained(self, factory, sched, n, timeout=15.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(sched.dropped) >= n:
                return
            time.sleep(0.05)
        raise AssertionError(
            f"scheduler only decided {len(sched.dropped)}/{n} pods")

    def test_successor_reschedules_everything(self, server, client):
        """Scheduler A assumes 12 pods then dies before any bind lands. A
        fresh scheduler B re-lists: every pod must end up bound exactly once
        with node capacity respected — nothing is lost with the assumes."""
        self._fill(client)
        fa = ConfigFactory(RESTClient.for_server(server, qps=1000, burst=1000))
        fa.run()
        a = _BindDroppingScheduler(
            fa, fa.create_from_provider().algorithm).run()
        self._wait_drained(fa, a, 12)
        a.stop()
        fa.stop()  # process death: cache, assumes, FIFO all gone

        # nothing was ever bound
        pods, _ = client.list("pods", "default")
        assert all(not p.spec.node_name for p in pods)

        fb = ConfigFactory(RESTClient.for_server(server, qps=1000, burst=1000))
        fb.run()
        b = fb.create_from_provider().run()
        try:
            done = wait_scheduled(client, 12, timeout=30)
            by_node = {}
            for p in done:
                by_node[p.spec.node_name] = by_node.get(p.spec.node_name, 0) + 1
            # 2 CPU/node, 500m/pod -> max 4 per node; pods cap 5
            assert sum(by_node.values()) == 12
            for node, cnt in by_node.items():
                assert cnt <= 4, f"{node} overcommitted after recovery"
        finally:
            b.stop()
            fb.stop()

    def test_zombie_binds_rejected_by_cas(self, server, client):
        """Scheduler A's binds arrive LATE — after successor B already bound
        the pods elsewhere. The BindingREST CAS (nodeName iff empty) must
        reject every conflicting zombie bind and keep B's assignments."""
        self._fill(client, n_nodes=3, n_pods=6)
        fa = ConfigFactory(RESTClient.for_server(server, qps=1000, burst=1000))
        fa.run()
        a = _BindDroppingScheduler(
            fa, fa.create_from_provider().algorithm).run()
        self._wait_drained(fa, a, 6)
        a.stop()
        fa.stop()
        zombie_decisions = list(a.dropped)

        fb = ConfigFactory(RESTClient.for_server(server, qps=1000, burst=1000))
        fb.run()
        b = fb.create_from_provider().run()
        try:
            done = wait_scheduled(client, 6, timeout=30)
            want = {p.metadata.name: p.spec.node_name for p in done}
        finally:
            b.stop()
            fb.stop()

        conflicts = 0
        for pod, dest in zombie_decisions:
            binding = api.Binding(
                metadata=api.ObjectMeta(name=pod.metadata.name,
                                        namespace="default"),
                target=api.ObjectReference(kind="Node", name=dest))
            try:
                client.bind(binding, "default")
            except ApiError as e:
                assert e.is_conflict
                conflicts += 1
        pods, _ = client.list("pods", "default")
        got = {p.metadata.name: p.spec.node_name for p in pods}
        assert got == want, "zombie binds moved pods"
        # every zombie bind either matched B's choice (idempotent no-op) or
        # conflicted; none may have re-assigned
        assert conflicts == sum(
            1 for pod, dest in zombie_decisions
            if want[pod.metadata.name] != dest)

    def test_bind_outage_heals_and_pods_land(self, server, client):
        """All POST /bindings fail (path-scoped chaos) while the scheduler
        runs: assumes must be rolled back on bind failure and pods requeued
        with backoff; once the outage heals, every pod lands."""
        for i in range(2):
            client.create("nodes", mk_node(f"n{i}"))
        sched_client = RESTClient.for_server(server, qps=1000, burst=1000)
        ctl = install_chaos(
            sched_client,
            PathChaos(r"/bindings$", NetworkError(), methods={"POST"}))
        f = ConfigFactory(sched_client)
        f.run()
        s = f.create_from_provider().run()
        try:
            for i in range(4):
                client.create("pods", mk_pod(f"p{i}"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and ctl.count("NetworkError") < 4:
                time.sleep(0.05)
            assert ctl.count("NetworkError") >= 4, "no binds were attempted"
            # during the outage nothing is bound
            pods, _ = client.list("pods", "default")
            assert all(not p.spec.node_name for p in pods)
            ctl.uninstall()  # heal
            done = wait_scheduled(client, 4, timeout=45)  # backoff retry ~1-2s
            assert len({p.metadata.name for p in done}) == 4
        finally:
            s.stop()
            f.stop()
