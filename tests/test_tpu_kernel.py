"""Differential tests: TPU kernel vs the sequential oracle.

The contract (BASELINE.json): identical bindings, pod for pod, over the
default provider's predicate+priority semantics. Runs on the virtual CPU
mesh (conftest); bench.py runs the same kernel on the real chip."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.kernel import Weights, schedule_batch
from kubernetes_tpu.ops.tensorize import Tensorizer
from kubernetes_tpu.scheduler.batch import (
    ListPodLister, ListServiceLister, make_plugin_args, oracle_batch, tpu_batch,
)


def mk_node(name, cpu="4", mem="32Gi", pods="110", labels=None, taints=None,
            conditions=None):
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=conditions or [api.NodeCondition(type="Ready", status="True")]))


def mk_pod(name, ns="default", cpu=None, mem=None, labels=None, node="",
           selector=None, affinity=None, tolerations=None, host_ports=()):
    requests = {}
    if cpu:
        requests["cpu"] = cpu
    if mem:
        requests["memory"] = mem
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(
            node_name=node, node_selector=selector, affinity=affinity,
            tolerations=tolerations,
            containers=[api.Container(
                name="c", image="pause",
                ports=[api.ContainerPort(host_port=p, container_port=p)
                       for p in host_ports],
                resources=api.ResourceRequirements(requests=requests)
                if requests else None)]))


def assert_same(nodes, existing, pending, args_oracle, args_tpu, **kw):
    got_oracle = oracle_batch(nodes, existing, pending, args_oracle, **kw)
    got_tpu = tpu_batch(nodes, existing, pending, args_tpu)
    assert got_tpu == got_oracle, (
        f"kernel disagrees with oracle:\n  oracle: {got_oracle}\n  tpu:    {got_tpu}")
    return got_oracle


def two_args(nodes, existing=(), services=()):
    """Fresh plugin args for each backend (oracle mutates its pod lister)."""
    def mk():
        return make_plugin_args(
            nodes, pod_lister=ListPodLister(list(existing)),
            service_lister=ListServiceLister(services))
    return mk(), mk()


class TestDifferentialBasic:
    def test_empty_cluster_spreads_by_least_requested(self):
        nodes = [mk_node(f"n{i}") for i in range(5)]
        pending = [mk_pod(f"p{i}", cpu="500m", mem="1Gi") for i in range(20)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert None not in got
        assert len(set(got)) == 5  # all nodes used

    def test_respects_existing_load(self):
        nodes = [mk_node("busy"), mk_node("idle")]
        existing = [mk_pod(f"e{i}", cpu="1", mem="8Gi", node="busy") for i in range(3)]
        pending = [mk_pod("p", cpu="100m", mem="100Mi")]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["idle"]

    def test_capacity_exhaustion_and_unschedulable(self):
        nodes = [mk_node("n1", cpu="1", pods="4")]
        pending = [mk_pod(f"p{i}", cpu="400m") for i in range(4)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got[:2] == ["n1", "n1"] and got[2:] == [None, None]

    def test_pod_count_cap(self):
        nodes = [mk_node("n1", pods="2"), mk_node("n2", pods="2")]
        pending = [mk_pod(f"p{i}") for i in range(6)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got.count(None) == 2

    def test_round_robin_ties(self):
        nodes = [mk_node(f"n{i}") for i in range(3)]
        pending = [mk_pod(f"p{i}") for i in range(6)]  # no requests: all tie
        a, b = two_args(nodes)
        assert_same(nodes, [], pending, a, b)

    def test_zero_request_on_overcommitted_node(self):
        nodes = [mk_node("n1", cpu="1", pods="10")]
        existing = [mk_pod("e", cpu="2", node="n1")]  # overcommitted externally
        pending = [mk_pod("z")]  # zero requests: passes resources, count ok
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["n1"]


class TestDifferentialPredicates:
    def test_node_selector(self):
        nodes = [mk_node("plain"), mk_node("ssd", labels={"disk": "ssd"})]
        pending = [mk_pod("p", selector={"disk": "ssd"}),
                   mk_pod("q", selector={"disk": "none"})]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got == ["ssd", None]

    def test_host_pinning(self):
        nodes = [mk_node("n1"), mk_node("n2")]
        pending = [mk_pod("p", node="n2"), mk_pod("q", node="ghost")]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got == ["n2", None]

    def test_taints(self):
        taint = api.Taint(key="dedicated", value="ml", effect="NoSchedule")
        nodes = [mk_node("tainted", cpu="8", taints=[taint]), mk_node("plain", cpu="2")]
        tol = [api.Toleration(key="dedicated", operator="Exists")]
        pending = [mk_pod("p"), mk_pod("ml", tolerations=tol, cpu="4")]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got == ["plain", "tainted"]

    def test_host_ports_dynamic(self):
        """Second pod with the same hostPort must go elsewhere — in-batch
        port booking."""
        nodes = [mk_node("n1"), mk_node("n2")]
        pending = [mk_pod("p1", host_ports=(8080,)), mk_pod("p2", host_ports=(8080,)),
                   mk_pod("p3", host_ports=(8080,))]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert set(got[:2]) == {"n1", "n2"} and got[2] is None

    def test_memory_pressure_gates_besteffort(self):
        pressured = mk_node("pressured", conditions=[
            api.NodeCondition(type="Ready", status="True"),
            api.NodeCondition(type="MemoryPressure", status="True")])
        nodes = [pressured, mk_node("ok", cpu="1")]
        pending = [mk_pod("be"), mk_pod("burst", cpu="100m")]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got[0] == "ok"

    def test_node_affinity_required(self):
        nodes = [mk_node("a", labels={"zone": "us-a"}),
                 mk_node("b", labels={"zone": "us-b"})]
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            required_during_scheduling_ignored_during_execution=api.NodeSelector(
                node_selector_terms=[api.NodeSelectorTerm(match_expressions=[
                    api.NodeSelectorRequirement(key="zone", operator="In",
                                                values=["us-b"])])])))
        pending = [mk_pod("p", affinity=aff)]
        a, b = two_args(nodes)
        assert assert_same(nodes, [], pending, a, b) == ["b"]

    @pytest.mark.parametrize("op,values,expect", [
        ("NotIn", ["us-a"], "b"),
        ("Exists", None, "a"),          # only "a" has the label... see body
        ("DoesNotExist", None, "b"),
        ("Gt", ["5"], "b"),
        ("Lt", ["5"], "a"),
    ])
    def test_node_affinity_operators(self, op, values, expect):
        nodes = [mk_node("a", labels={"cores": "2", "zone": "us-a"}),
                 mk_node("b", labels={"cores": "8"})]
        key = "zone" if op in ("NotIn", "Exists", "DoesNotExist") else "cores"
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            required_during_scheduling_ignored_during_execution=api.NodeSelector(
                node_selector_terms=[api.NodeSelectorTerm(match_expressions=[
                    api.NodeSelectorRequirement(key=key, operator=op,
                                                values=values)])])))
        pending = [mk_pod("p", affinity=aff)]
        a, b = two_args(nodes)
        got = assert_same(nodes, [], pending, a, b)
        assert got == [expect]


class TestDifferentialPriorities:
    def test_preferred_node_affinity(self):
        nodes = [mk_node("a", labels={"disk": "ssd"}), mk_node("b")]
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            preferred_during_scheduling_ignored_during_execution=[
                api.PreferredSchedulingTerm(weight=50, preference=api.NodeSelectorTerm(
                    match_expressions=[api.NodeSelectorRequirement(
                        key="disk", operator="In", values=["ssd"])]))]))
        pending = [mk_pod("p", affinity=aff, cpu="100m")]
        a, b = two_args(nodes)
        assert assert_same(nodes, [], pending, a, b) == ["a"]

    def test_prefer_no_schedule_avoidance(self):
        nodes = [mk_node("t", taints=[api.Taint(key="x", value="y",
                                                effect="PreferNoSchedule")]),
                 mk_node("clean")]
        pending = [mk_pod("p", cpu="100m")]
        a, b = two_args(nodes)
        assert assert_same(nodes, [], pending, a, b) == ["clean"]

    def test_selector_spread_with_service(self):
        nodes = [mk_node(f"n{i}") for i in range(3)]
        svc = api.Service(metadata=api.ObjectMeta(name="web", namespace="default"),
                          spec=api.ServiceSpec(selector={"app": "web"},
                                               ports=[api.ServicePort(port=80)]))
        existing = [mk_pod("e1", labels={"app": "web"}, node="n0", cpu="100m")]
        pending = [mk_pod(f"w{i}", labels={"app": "web"}, cpu="100m")
                   for i in range(4)]
        a, b = two_args(nodes, existing, services=[svc])
        got = assert_same(nodes, existing, pending, a, b)
        # spreading balances totals: n0 already holds the existing pod, so
        # every node ends with at least one service pod and at most two
        totals = {"n0": 1, "n1": 0, "n2": 0}
        for h in got:
            totals[h] += 1
        assert all(1 <= c <= 2 for c in totals.values()), totals

    def test_zone_aware_spread(self):
        za, zb = {api.LABEL_ZONE: "us-a"}, {api.LABEL_ZONE: "us-b"}
        nodes = [mk_node("a1", labels=za), mk_node("a2", labels=za),
                 mk_node("b1", labels=zb)]
        svc = api.Service(metadata=api.ObjectMeta(name="web", namespace="default"),
                          spec=api.ServiceSpec(selector={"app": "web"},
                                               ports=[api.ServicePort(port=80)]))
        existing = [mk_pod("e1", labels={"app": "web"}, node="a1", cpu="100m")]
        pending = [mk_pod("w1", labels={"app": "web"}, cpu="100m")]
        a, b = two_args(nodes, existing, services=[svc])
        got = assert_same(nodes, existing, pending, a, b)
        assert got == ["b1"]  # other zone wins via 2/3 zone weighting


class TestDifferentialInterPod:
    def test_anti_affinity_vs_existing(self):
        h = api.LABEL_HOSTNAME
        nodes = [mk_node("n1", labels={h: "n1"}), mk_node("n2", labels={h: "n2"})]
        existing = [mk_pod("e", labels={"app": "web"}, node="n1", cpu="100m")]
        anti = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"app": "web"}),
                    topology_key=h)]))
        pending = [mk_pod("p", labels={"app": "other"}, affinity=anti, cpu="100m")]
        a, b = two_args(nodes, existing)
        assert assert_same(nodes, existing, pending, a, b) == ["n2"]

    def test_symmetry_existing_anti_affinity(self):
        h = api.LABEL_HOSTNAME
        nodes = [mk_node("n1", labels={h: "n1"}), mk_node("n2", labels={h: "n2"})]
        lonely_anti = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"app": "web"}),
                    topology_key=h)]))
        existing = [mk_pod("lonely", labels={"app": "solo"}, node="n1",
                           affinity=lonely_anti, cpu="100m")]
        pending = [mk_pod("w", labels={"app": "web"}, cpu="100m")]
        a, b = two_args(nodes, existing)
        assert assert_same(nodes, existing, pending, a, b) == ["n2"]

    def test_required_affinity_zone_vs_existing(self):
        za, zb = {api.LABEL_ZONE: "us-a"}, {api.LABEL_ZONE: "us-b"}
        nodes = [mk_node("a1", labels=za), mk_node("a2", labels=za),
                 mk_node("b1", labels=zb)]
        existing = [mk_pod("db", labels={"app": "db"}, node="a1", cpu="100m")]
        aff = api.Affinity(pod_affinity=api.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"app": "db"}),
                    topology_key=api.LABEL_ZONE)]))
        pending = [mk_pod("web", labels={"app": "web"}, affinity=aff, cpu="100m")]
        a, b = two_args(nodes, existing)
        got = assert_same(nodes, existing, pending, a, b)
        assert got[0] in ("a1", "a2")  # same zone as db


class TestDifferentialRandomized:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_cluster(self, seed):
        rng = random.Random(seed)
        zones = ["us-a", "us-b", "us-c"]
        nodes = []
        for i in range(24):
            labels = {api.LABEL_HOSTNAME: f"n{i:02d}",
                      api.LABEL_ZONE: rng.choice(zones)}
            if rng.random() < 0.3:
                labels["disk"] = rng.choice(["ssd", "hdd"])
            taints = ([api.Taint(key="dedicated", value="ml", effect="NoSchedule")]
                      if rng.random() < 0.15 else None)
            nodes.append(mk_node(
                f"n{i:02d}", cpu=rng.choice(["2", "4", "8"]),
                mem=rng.choice(["8Gi", "16Gi", "32Gi"]),
                pods=str(rng.choice([8, 16, 110])), labels=labels, taints=taints))
        existing = []
        for i in range(30):
            n = rng.choice(nodes)
            existing.append(mk_pod(
                f"e{i:02d}", cpu=f"{rng.choice([100, 250, 500])}m",
                mem=f"{rng.choice([128, 512, 1024])}Mi",
                labels={"app": rng.choice(["web", "db", "cache"])},
                node=n.metadata.name))
        svc = api.Service(metadata=api.ObjectMeta(name="web", namespace="default"),
                          spec=api.ServiceSpec(selector={"app": "web"},
                                               ports=[api.ServicePort(port=80)]))
        pending = []
        for i in range(60):
            kw = {"cpu": f"{rng.choice([100, 200, 500])}m",
                  "mem": f"{rng.choice([128, 256, 512])}Mi",
                  "labels": {"app": rng.choice(["web", "db", "cache"])}}
            r = rng.random()
            if r < 0.2:
                kw["selector"] = {"disk": "ssd"}
            elif r < 0.3:
                kw["tolerations"] = [api.Toleration(key="dedicated", operator="Exists")]
            elif r < 0.35:
                kw["host_ports"] = (9000 + (i % 4),)
            pending.append(mk_pod(f"p{i:02d}", **kw))
        a, b = two_args(nodes, existing, services=[svc])
        assert_same(nodes, existing, pending, a, b)


class TestKernelMechanics:
    def test_no_overcommit_invariant(self):
        """Whatever the kernel assigns must satisfy capacity constraints."""
        rng = random.Random(42)
        nodes = [mk_node(f"n{i}", cpu="2", mem="4Gi", pods="10") for i in range(8)]
        pending = [mk_pod(f"p{i}", cpu=f"{rng.choice([100, 500, 900])}m",
                          mem=f"{rng.choice([256, 1024])}Mi") for i in range(64)]
        args = make_plugin_args(nodes)
        got = tpu_batch(nodes, [], pending, args)
        used = {n.metadata.name: [0, 0, 0] for n in nodes}
        for pod, host in zip(pending, got):
            if host is None:
                continue
            r = api.pod_resource_request(pod)
            used[host][0] += r[api.RESOURCE_CPU]
            used[host][1] += r[api.RESOURCE_MEMORY]
            used[host][2] += 1
        for name, (cpu, mem, cnt) in used.items():
            assert cpu <= 2000 and mem <= 4 * 2**30 and cnt <= 10, name

    def test_padding_insensitive(self):
        """Padded rows/columns must never be selected or affect choices."""
        nodes = [mk_node(f"n{i}") for i in range(3)]   # padded to 128
        pending = [mk_pod(f"p{i}", cpu="100m") for i in range(5)]  # padded to 8
        args = make_plugin_args(nodes)
        got = tpu_batch(nodes, [], pending, args)
        assert all(g in {"n0", "n1", "n2"} for g in got)

    def test_jit_cache_reuse(self):
        """Same padded shapes -> no recompile (cache keyed by shape)."""
        nodes = [mk_node(f"n{i}") for i in range(4)]
        args = make_plugin_args(nodes)
        t = Tensorizer(plugin_args=args)
        import kubernetes_tpu.ops.kernel as K
        ct1 = t.build(nodes, [], [mk_pod("a", cpu="1")])
        ct2 = t.build(nodes, [], [mk_pod("b", cpu="2")])
        r1 = schedule_batch(ct1)
        size_before = K._schedule_jit._cache_size()
        r2 = schedule_batch(ct2)
        assert K._schedule_jit._cache_size() == size_before
        assert r1[0] is not None and r2[0] is not None
