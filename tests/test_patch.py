"""Server-side PATCH: strategic merge + RFC-7386 merge, conflict retry.

Parity target: reference pkg/apiserver/resthandler.go:503-615 (PATCH verb
with three content types and in-server conflict retry) and
pkg/util/strategicpatch/patch.go (merge semantics). The headline property
(round-4 verdict #6): concurrent writers of disjoint fields — a label PATCH
and a status PATCH of one pod — must BOTH land, no lost update.
"""

import threading

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError


def mk_pod(name="p0", ns="default", labels=None):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(containers=[
            api.Container(name="main", image="img:1"),
            api.Container(name="side", image="side:1")]))


def mk_rc(name="rc0", ns="default"):
    return api.ReplicationController(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.ReplicationControllerSpec(
            replicas=1, selector={"app": "rc"},
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels={"app": "rc"}),
                spec=api.PodSpec(containers=[
                    api.Container(name="main", image="img:1"),
                    api.Container(name="side", image="side:1")]))))


@pytest.fixture()
def server():
    s = APIServer().start()
    try:
        yield s
    finally:
        s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=1000, burst=1000)


class TestStrategicPatch:
    def test_label_merge_and_delete(self, client):
        client.create("pods", mk_pod(labels={"a": "1", "b": "2"}))
        got = client.patch("pods", "p0",
                           {"metadata": {"labels": {"b": None, "c": "3"}}},
                           "default")
        assert got.metadata.labels == {"a": "1", "c": "3"}
        # and it persisted
        assert client.get("pods", "p0", "default").metadata.labels == {
            "a": "1", "c": "3"}

    def test_container_list_merges_by_name(self, client):
        client.create("pods", mk_pod())
        got = client.patch(
            "pods", "p0",
            {"spec": {"containers": [{"name": "main", "image": "img:2"}]}},
            "default")
        by_name = {c.name: c.image for c in got.spec.containers}
        # the named element updated; the sibling survived (merge-by-key,
        # not wholesale replace)
        assert by_name == {"main": "img:2", "side": "side:1"}

    def test_dollar_patch_delete_removes_element(self, client):
        # pod specs are immutable (ValidatePodUpdate), so the list-element
        # delete directive is exercised on an RC's pod template
        client.create("replicationcontrollers", mk_rc())
        got = client.patch(
            "replicationcontrollers", "rc0",
            {"spec": {"template": {"spec": {"containers": [
                {"name": "side", "$patch": "delete"}]}}}},
            "default")
        assert [c.name
                for c in got.spec.template.spec.containers] == ["main"]

    def test_status_subresource_patch(self, client):
        client.create("pods", mk_pod())
        got = client.patch_status("pods", "p0",
                                  {"status": {"phase": "Running"}}, "default")
        assert got.status.phase == "Running"
        # main-resource fields unchanged by a status patch
        assert got.spec.containers[0].image == "img:1"

    def test_resource_version_not_patchable(self, client):
        client.create("pods", mk_pod())
        with pytest.raises(ApiError) as ei:
            client.patch("pods", "p0",
                         {"metadata": {"resourceVersion": "1"}}, "default")
        assert ei.value.code == 400

    def test_unknown_patch_type_415(self, client):
        client.create("pods", mk_pod())
        with pytest.raises(ApiError) as ei:
            client.patch("pods", "p0", {"metadata": {}}, "default",
                         patch_type="application/json-patch+json")
        assert ei.value.code == 415

    def test_patch_missing_object_404(self, client):
        with pytest.raises(ApiError) as ei:
            client.patch("pods", "ghost", {"metadata": {}}, "default")
        assert ei.value.code == 404

    def test_delete_directive_on_absent_map_is_noop(self, client):
        """{k: null} aimed at a map the object doesn't have must not store
        a literal null (label selectors would then see a None-valued key)."""
        client.create("pods", mk_pod(labels=None))
        got = client.patch("pods", "p0",
                           {"metadata": {"labels": {"gone": None, "a": "1"}}},
                           "default")
        assert got.metadata.labels == {"a": "1"}

    def test_non_dict_body_400(self, client):
        client.create("pods", mk_pod())
        with pytest.raises(ApiError) as ei:
            client.request("PATCH", "/api/v1/namespaces/default/pods/p0",
                           ["not", "an", "object"],
                           content_type=RESTClient.STRATEGIC_PATCH)
        assert ei.value.code == 400

    def test_patch_on_binding_subresource_405(self, client):
        client.create("pods", mk_pod())
        with pytest.raises(ApiError) as ei:
            client.request("PATCH",
                           "/api/v1/namespaces/default/pods/p0/binding",
                           {"spec": {"nodeName": "sneaky"}},
                           content_type=RESTClient.STRATEGIC_PATCH)
        assert ei.value.code == 405
        # and the main resource is untouched
        assert client.get("pods", "p0", "default").spec.node_name in (None, "")

    def test_415_keeps_connection_usable(self, client):
        """The 415 path must drain the unread body or the next request on
        the same keep-alive connection parses garbage."""
        client.create("pods", mk_pod())
        for _ in range(3):
            with pytest.raises(ApiError) as ei:
                client.patch("pods", "p0", {"metadata": {"labels": {"x": "1"}}},
                             "default", patch_type="application/json-patch+json")
            assert ei.value.code == 415
            # same-thread connection reused for a normal request
            assert client.get("pods", "p0", "default").metadata.name == "p0"


class TestMergePatch:
    def test_lists_replace_wholesale(self, client):
        client.create("replicationcontrollers", mk_rc())
        got = client.patch(
            "replicationcontrollers", "rc0",
            {"spec": {"template": {"spec": {"containers": [
                {"name": "only", "image": "o:1"}]}}}},
            "default", patch_type=RESTClient.MERGE_PATCH)
        assert [c.name
                for c in got.spec.template.spec.containers] == ["only"]

    def test_null_deletes_key(self, client):
        client.create("pods", mk_pod(labels={"a": "1"}))
        got = client.patch("pods", "p0", {"metadata": {"labels": None}},
                           "default", patch_type=RESTClient.MERGE_PATCH)
        assert not got.metadata.labels


class TestConcurrentPatchers:
    def test_label_and_status_patches_both_land(self, client):
        """The lost-update surface PATCH exists to shrink: N writers on
        disjoint fields of one object, zero coordination, all must land."""
        client.create("pods", mk_pod())
        n = 16
        errs = []
        barrier = threading.Barrier(n * 2)

        def label_writer(i):
            try:
                barrier.wait()
                client.patch("pods", "p0",
                             {"metadata": {"labels": {f"k{i}": str(i)}}},
                             "default")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        def status_writer(i):
            try:
                barrier.wait()
                client.patch_status(
                    "pods", "p0",
                    {"status": {"phase": "Running",
                                "message": f"writer-{i}"}}, "default")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = ([threading.Thread(target=label_writer, args=(i,))
                    for i in range(n)]
                   + [threading.Thread(target=status_writer, args=(i,))
                      for i in range(n)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        final = client.get("pods", "p0", "default")
        # EVERY label writer's key landed (no lost update) and status landed
        assert {f"k{i}": str(i) for i in range(n)}.items() <= (
            final.metadata.labels or {}).items()
        assert final.status.phase == "Running"

    def test_patch_vs_put_conflict_retry(self, client):
        """A PUT racing the server's get->merge->update window forces 409s;
        the server re-gets and re-applies (resthandler.go:562-615)."""
        client.create("pods", mk_pod(labels={"seed": "y"}))
        stop = threading.Event()

        def put_hammer():
            while not stop.is_set():
                try:
                    obj = client.get("pods", "p0", "default")
                    obj.metadata.labels = dict(obj.metadata.labels or {},
                                               put="1")
                    client.update("pods", obj)
                except ApiError:
                    pass  # the PUT side may conflict; that's its problem

        th = threading.Thread(target=put_hammer)
        th.start()
        try:
            for i in range(25):
                client.patch("pods", "p0",
                             {"metadata": {"labels": {f"p{i}": "1"}}},
                             "default")
        finally:
            stop.set()
            th.join()
        final = client.get("pods", "p0", "default")
        assert {f"p{i}" for i in range(25)} <= set(final.metadata.labels)


class TestKubectlOverPatch:
    def test_label_annotate_cordon_use_patch(self, server, client, capsys):
        from kubernetes_tpu.kubectl.cmd import main as kubectl
        client.create("pods", mk_pod(labels={"keep": "1"}))
        client.create(
            "nodes", api.Node(metadata=api.ObjectMeta(name="n0"),
                              spec=api.NodeSpec()))
        host = ["-s", f"127.0.0.1:{server.port}"]
        assert kubectl(host + ["label", "pods", "p0", "x=1"]) == 0
        assert kubectl(host + ["annotate", "pods", "p0", "note=hi"]) == 0
        assert kubectl(host + ["cordon", "n0"]) == 0
        pod = client.get("pods", "p0", "default")
        assert pod.metadata.labels == {"keep": "1", "x": "1"}
        assert (pod.metadata.annotations or {}).get("note") == "hi"
        assert client.get("nodes", "n0").spec.unschedulable is True
