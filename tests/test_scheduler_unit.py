"""Scheduler unit tests: predicates (table-driven), priorities, cache state
machine with injected time, generic scheduler — mirroring the reference's
predicates_test.go / priorities_test.go / cache_test.go patterns."""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import (
    DEFAULT_MEMORY_REQUEST, DEFAULT_MILLI_CPU_REQUEST, NodeInfo, SchedulerCache,
)
from kubernetes_tpu.scheduler import predicates as preds
from kubernetes_tpu.scheduler import priorities as prios
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler, PriorityConfig


def mk_pod(name="p", ns="default", cpu=None, mem=None, labels=None, node="",
           host_ports=(), selector=None, affinity=None, tolerations=None,
           volumes=None):
    requests = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if mem is not None:
        requests["memory"] = mem
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(
            node_name=node,
            containers=[api.Container(
                name="c", image="img",
                ports=[api.ContainerPort(host_port=p, container_port=p)
                       for p in host_ports],
                resources=api.ResourceRequirements(requests=requests) if requests else None)],
            node_selector=selector, affinity=affinity, tolerations=tolerations,
            volumes=volumes))


def mk_node(name="n1", cpu="4", mem="32Gi", pods="110", labels=None,
            taints=None, conditions=None, images=None):
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=conditions or [api.NodeCondition(type="Ready", status="True")],
            images=images))


def ni(node, *pods):
    info = NodeInfo(node)
    for p in pods:
        info.add_pod(p)
    return info


class TestPodFitsResources:
    def test_fits(self):
        preds.pod_fits_resources(mk_pod(cpu="1"), ni(mk_node(cpu="4")))

    def test_insufficient_cpu(self):
        info = ni(mk_node(cpu="4"), mk_pod("a", cpu="3", node="n1"))
        with pytest.raises(preds.InsufficientResource) as ei:
            preds.pod_fits_resources(mk_pod(cpu="2"), info)
        assert ei.value.resource == "cpu"
        assert (ei.value.requested, ei.value.used, ei.value.capacity) == (2000, 3000, 4000)

    def test_insufficient_memory(self):
        info = ni(mk_node(mem="1Gi"), mk_pod("a", mem="800Mi", node="n1"))
        with pytest.raises(preds.InsufficientResource, match="memory"):
            preds.pod_fits_resources(mk_pod(mem="300Mi"), info)

    def test_pod_count_cap(self):
        node = mk_node(pods="1")
        info = ni(node, mk_pod("a", node="n1"))
        with pytest.raises(preds.InsufficientResource, match="pods"):
            preds.pod_fits_resources(mk_pod("b"), info)

    def test_zero_request_always_fits_resources(self):
        info = ni(mk_node(cpu="1"), mk_pod("a", cpu="1", node="n1"))
        preds.pod_fits_resources(mk_pod("b"), info)  # no requests -> fits


class TestHostAndPorts:
    def test_pod_fits_host(self):
        preds.pod_fits_host(mk_pod(node="n1"), ni(mk_node("n1")))
        with pytest.raises(preds.PredicateFailure):
            preds.pod_fits_host(mk_pod(node="other"), ni(mk_node("n1")))
        preds.pod_fits_host(mk_pod(), ni(mk_node("n1")))  # unset: any node

    def test_host_ports(self):
        info = ni(mk_node(), mk_pod("a", host_ports=(8080,), node="n1"))
        with pytest.raises(preds.PredicateFailure, match="8080"):
            preds.pod_fits_host_ports(mk_pod(host_ports=(8080,)), info)
        preds.pod_fits_host_ports(mk_pod(host_ports=(9090,)), info)


class TestNodeSelectorAffinity:
    def test_node_selector(self):
        node = mk_node(labels={"disk": "ssd"})
        preds.pod_matches_node_selector(mk_pod(selector={"disk": "ssd"}), ni(node))
        with pytest.raises(preds.PredicateFailure):
            preds.pod_matches_node_selector(mk_pod(selector={"disk": "hdd"}), ni(node))

    @pytest.mark.parametrize("op,values,node_labels,fits", [
        ("In", ["us-a", "us-b"], {"zone": "us-a"}, True),
        ("In", ["us-a"], {"zone": "us-c"}, False),
        ("NotIn", ["us-a"], {"zone": "us-c"}, True),
        ("NotIn", ["us-a"], {"zone": "us-a"}, False),
        ("Exists", None, {"zone": "x"}, True),
        ("Exists", None, {}, False),
        ("DoesNotExist", None, {}, True),
        ("Gt", ["4"], {"zone": "8"}, True),
        ("Gt", ["4"], {"zone": "2"}, False),
        ("Lt", ["4"], {"zone": "2"}, True),
    ])
    def test_node_affinity_ops(self, op, values, node_labels, fits):
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            required_during_scheduling_ignored_during_execution=api.NodeSelector(
                node_selector_terms=[api.NodeSelectorTerm(match_expressions=[
                    api.NodeSelectorRequirement(key="zone", operator=op,
                                                values=values)])])))
        pod = mk_pod(affinity=aff)
        node = mk_node(labels=node_labels)
        if fits:
            preds.pod_matches_node_selector(pod, ni(node))
        else:
            with pytest.raises(preds.PredicateFailure):
                preds.pod_matches_node_selector(pod, ni(node))

    def test_terms_are_ored(self):
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            required_during_scheduling_ignored_during_execution=api.NodeSelector(
                node_selector_terms=[
                    api.NodeSelectorTerm(match_expressions=[
                        api.NodeSelectorRequirement(key="a", operator="In", values=["1"])]),
                    api.NodeSelectorTerm(match_expressions=[
                        api.NodeSelectorRequirement(key="b", operator="In", values=["2"])]),
                ])))
        preds.pod_matches_node_selector(mk_pod(affinity=aff),
                                        ni(mk_node(labels={"b": "2"})))


class TestTaints:
    def test_untolerated_noschedule(self):
        node = mk_node(taints=[api.Taint(key="dedicated", value="ml",
                                         effect="NoSchedule")])
        with pytest.raises(preds.PredicateFailure, match="dedicated"):
            preds.pod_tolerates_node_taints(mk_pod(), ni(node))

    def test_tolerated(self):
        node = mk_node(taints=[api.Taint(key="dedicated", value="ml",
                                         effect="NoSchedule")])
        pod = mk_pod(tolerations=[api.Toleration(key="dedicated", operator="Equal",
                                                 value="ml", effect="NoSchedule")])
        preds.pod_tolerates_node_taints(pod, ni(node))

    def test_prefer_no_schedule_ignored_by_predicate(self):
        node = mk_node(taints=[api.Taint(key="x", value="y",
                                         effect="PreferNoSchedule")])
        preds.pod_tolerates_node_taints(mk_pod(), ni(node))


class TestMemoryPressureAndDisk:
    def test_besteffort_blocked_on_pressure(self):
        node = mk_node(conditions=[
            api.NodeCondition(type="Ready", status="True"),
            api.NodeCondition(type="MemoryPressure", status="True")])
        with pytest.raises(preds.PredicateFailure, match="memory pressure"):
            preds.check_node_memory_pressure(mk_pod(), ni(node))
        # burstable pod is allowed
        preds.check_node_memory_pressure(mk_pod(cpu="1"), ni(node))

    def test_gce_pd_conflict(self):
        vol = api.Volume(name="d", gce_persistent_disk=api.GCEPersistentDiskVolumeSource(pd_name="pd1"))
        info = ni(mk_node(), mk_pod("a", node="n1", volumes=[vol]))
        with pytest.raises(preds.PredicateFailure, match="disk conflict"):
            preds.no_disk_conflict(mk_pod(volumes=[vol]), info)
        ro = api.Volume(name="d", gce_persistent_disk=api.GCEPersistentDiskVolumeSource(
            pd_name="pd1", read_only=True))
        info_ro = ni(mk_node(), mk_pod("a", node="n1", volumes=[ro]))
        preds.no_disk_conflict(mk_pod(volumes=[ro]), info_ro)  # both RO: ok

    def test_max_pd_volume_count(self):
        checker = preds.MaxPDVolumeCountChecker("gce-pd", 2)
        v = lambda pd: api.Volume(name=pd, gce_persistent_disk=api.GCEPersistentDiskVolumeSource(pd_name=pd))
        info = ni(mk_node(), mk_pod("a", node="n1", volumes=[v("pd1"), v("pd2")]))
        with pytest.raises(preds.PredicateFailure, match="max gce-pd"):
            checker(mk_pod(volumes=[v("pd3")]), info)
        checker(mk_pod(volumes=[v("pd1")]), info)  # already-attached: free


class FakePodLister:
    def __init__(self, pods):
        self.pods = pods

    def list(self, selector=None):
        if selector is None:
            return list(self.pods)
        return [p for p in self.pods if selector.matches(p.metadata.labels or {})]


class TestInterPodAffinity:
    def _checker(self, pods, nodes):
        node_map = {n.metadata.name: n for n in nodes}
        return preds.InterPodAffinity(FakePodLister(pods), node_map.get)

    def _aff_term(self, key, value, topo=api.LABEL_HOSTNAME):
        return api.PodAffinityTerm(
            label_selector=api.LabelSelector(match_labels={key: value}),
            topology_key=topo)

    def test_hard_affinity_satisfied(self):
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        existing = mk_pod("db", labels={"app": "db"}, node="n1")
        pod = mk_pod("web", affinity=api.Affinity(pod_affinity=api.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                self._aff_term("app", "db")])))
        self._checker([existing], [n1])(pod, ni(n1, existing))

    def test_hard_affinity_unsatisfied(self):
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        n2 = mk_node("n2", labels={api.LABEL_HOSTNAME: "n2"})
        existing = mk_pod("db", labels={"app": "db"}, node="n2")
        pod = mk_pod("web", affinity=api.Affinity(pod_affinity=api.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                self._aff_term("app", "db")])))
        with pytest.raises(preds.PredicateFailure):
            self._checker([existing], [n1, n2])(pod, ni(n1))

    def test_disregard_rule_first_pod_of_group(self):
        """Self-selecting affinity with no matches anywhere may schedule
        (predicates.go:818-844)."""
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        pod = mk_pod("web", labels={"app": "web"},
                     affinity=api.Affinity(pod_affinity=api.PodAffinity(
                         required_during_scheduling_ignored_during_execution=[
                             self._aff_term("app", "web")])))
        self._checker([], [n1])(pod, ni(n1))

    def test_disregard_not_applied_when_peer_exists_elsewhere(self):
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        n2 = mk_node("n2", labels={api.LABEL_HOSTNAME: "n2"})
        peer = mk_pod("web2", labels={"app": "web"}, node="n2")
        pod = mk_pod("web", labels={"app": "web"},
                     affinity=api.Affinity(pod_affinity=api.PodAffinity(
                         required_during_scheduling_ignored_during_execution=[
                             self._aff_term("app", "web")])))
        with pytest.raises(preds.PredicateFailure):
            self._checker([peer], [n1, n2])(pod, ni(n1))

    def test_anti_affinity(self):
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        existing = mk_pod("web1", labels={"app": "web"}, node="n1")
        pod = mk_pod("web2", affinity=api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                self._aff_term("app", "web")])))
        with pytest.raises(preds.PredicateFailure, match="anti-affinity"):
            self._checker([existing], [n1])(pod, ni(n1, existing))

    def test_symmetry_existing_anti_affinity(self):
        """An existing pod's anti-affinity keeps matching pods away
        (predicates.go:883-921)."""
        n1 = mk_node("n1", labels={api.LABEL_HOSTNAME: "n1"})
        lonely = mk_pod("lonely", labels={"app": "lonely"}, node="n1",
                        affinity=api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                            required_during_scheduling_ignored_during_execution=[
                                self._aff_term("app", "web")])))
        pod = mk_pod("web", labels={"app": "web"})
        with pytest.raises(preds.PredicateFailure, match="existing pod"):
            self._checker([lonely], [n1])(pod, ni(n1, lonely))

    def test_zone_topology(self):
        za = {api.LABEL_ZONE: "us-a"}
        n1 = mk_node("n1", labels=za)
        n2 = mk_node("n2", labels=za)
        existing = mk_pod("db", labels={"app": "db"}, node="n2")
        pod = mk_pod("web", affinity=api.Affinity(pod_affinity=api.PodAffinity(
            required_during_scheduling_ignored_during_execution=[
                self._aff_term("app", "db", topo=api.LABEL_ZONE)])))
        # same zone, different node: satisfied
        self._checker([existing], [n1, n2])(pod, ni(n1))


class TestPriorities:
    def test_least_requested_math(self):
        """cpu (4000-2000)*10/4000=5, mem (32Gi-16Gi)*10/32Gi=5 -> 5."""
        node = mk_node("n1", cpu="4", mem="32Gi")
        info = {"n1": ni(node, mk_pod("a", cpu="2", mem="16Gi", node="n1"))}
        scores = prios.least_requested(mk_pod("x"), info, [node])
        # incoming pod adds nonzero defaults (100m, 200Mi)
        cpu_score = ((4000 - 2100) * 10) // 4000  # 4
        mem_score = ((32 * 2**30 - (16 * 2**30 + DEFAULT_MEMORY_REQUEST)) * 10) // (32 * 2**30)
        assert scores["n1"] == (cpu_score + mem_score) // 2

    def test_least_requested_empty_node_wins(self):
        n1, n2 = mk_node("n1"), mk_node("n2")
        info = {"n1": ni(n1, mk_pod("a", cpu="3", mem="20Gi", node="n1")),
                "n2": ni(n2)}
        scores = prios.least_requested(mk_pod("x", cpu="100m"), info, [n1, n2])
        assert scores["n2"] > scores["n1"]

    def test_balanced_resource(self):
        node = mk_node("n1", cpu="4", mem="32Gi")
        # perfectly balanced: cpu 50%, mem 50%
        info = {"n1": ni(node, mk_pod("a", cpu="1900m", mem=f"{16 * 2**30 - DEFAULT_MEMORY_REQUEST}", node="n1"))}
        scores = prios.balanced_resource_allocation(mk_pod("x", cpu="100m"), info, [node])
        assert scores["n1"] == 10

    def test_balanced_overcommit_zero(self):
        node = mk_node("n1", cpu="1", mem="1Gi")
        info = {"n1": ni(node, mk_pod("a", cpu="2", node="n1"))}
        assert prios.balanced_resource_allocation(mk_pod("x"), info, [node])["n1"] == 0

    def test_selector_spread(self):
        class FakeSvcLister:
            def get_pod_services(self, pod):
                return [api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                                    spec=api.ServiceSpec(selector={"app": "web"}))]

        class EmptyLister:
            def get_pod_controllers(self, pod):
                return []

            def get_pod_replica_sets(self, pod):
                return []

        spread = prios.SelectorSpread(FakeSvcLister(), EmptyLister(), EmptyLister())
        n1, n2 = mk_node("n1"), mk_node("n2")
        info = {"n1": ni(n1, mk_pod("w1", labels={"app": "web"}, node="n1"),
                         mk_pod("w2", labels={"app": "web"}, node="n1")),
                "n2": ni(n2, mk_pod("w3", labels={"app": "web"}, node="n2"))}
        scores = spread(mk_pod("w4", labels={"app": "web"}), info, [n1, n2])
        assert scores["n1"] == 0          # max count -> 0
        assert scores["n2"] == 5          # 10*(2-1)/2

    def test_node_affinity_preferred(self):
        aff = api.Affinity(node_affinity=api.NodeAffinity(
            preferred_during_scheduling_ignored_during_execution=[
                api.PreferredSchedulingTerm(weight=80, preference=api.NodeSelectorTerm(
                    match_expressions=[api.NodeSelectorRequirement(
                        key="zone", operator="In", values=["us-a"])])),
                api.PreferredSchedulingTerm(weight=20, preference=api.NodeSelectorTerm(
                    match_expressions=[api.NodeSelectorRequirement(
                        key="disk", operator="In", values=["ssd"])]))]))
        n1 = mk_node("n1", labels={"zone": "us-a", "disk": "ssd"})
        n2 = mk_node("n2", labels={"zone": "us-a"})
        n3 = mk_node("n3", labels={})
        scores = prios.node_affinity_priority(mk_pod(affinity=aff), {}, [n1, n2, n3])
        assert scores == {"n1": 10, "n2": 8, "n3": 0}

    def test_taint_toleration_priority(self):
        t = api.Taint(key="k", value="v", effect="PreferNoSchedule")
        n1 = mk_node("n1", taints=[t, api.Taint(key="k2", value="v", effect="PreferNoSchedule")])
        n2 = mk_node("n2", taints=[t])
        n3 = mk_node("n3")
        scores = prios.taint_toleration_priority(mk_pod(), {}, [n1, n2, n3])
        assert scores == {"n1": 0, "n2": 5, "n3": 10}

    def test_image_locality(self):
        img = api.ContainerImage(names=["img"], size_bytes=500 * 1024 * 1024)
        n1 = mk_node("n1", images=[img])
        n2 = mk_node("n2")
        pod = mk_pod()
        scores = prios.image_locality_priority(pod, {}, [n1, n2])
        assert scores["n2"] == 0 and 0 < scores["n1"] <= 10

    def test_equal_priority(self):
        assert prios.equal_priority(mk_pod(), {}, [mk_node("a"), mk_node("b")]) == {
            "a": 1, "b": 1}


class TestSchedulerCache:
    def test_assume_confirm_lifecycle(self):
        now = [100.0]
        cache = SchedulerCache(ttl=30, clock=lambda: now[0])
        cache.add_node(mk_node("n1"))
        pod = mk_pod("p", cpu="1", node="n1")
        cache.assume_pod(pod, now=now[0])
        assert cache.is_assumed(pod)
        info = cache.get_node_name_to_info_map()
        assert info["n1"].requested.milli_cpu == 1000
        # informer confirms
        cache.add_pod(pod)
        assert not cache.is_assumed(pod)
        assert cache.get_node_name_to_info_map()["n1"].requested.milli_cpu == 1000
        # expiry after confirm must not remove anything
        now[0] += 100
        assert cache.cleanup_expired() == []
        assert cache.get_node_name_to_info_map()["n1"].requested.milli_cpu == 1000

    def test_assume_expiry_rolls_back(self):
        now = [0.0]
        cache = SchedulerCache(ttl=30, clock=lambda: now[0])
        cache.add_node(mk_node("n1"))
        cache.assume_pod(mk_pod("p", cpu="1", node="n1"), now=0.0)
        now[0] = 31.0
        assert cache.cleanup_expired() == ["default/p"]
        assert cache.get_node_name_to_info_map()["n1"].requested.milli_cpu == 0

    def test_remove_pod(self):
        cache = SchedulerCache()
        cache.add_node(mk_node("n1"))
        pod = mk_pod("p", cpu="1", node="n1")
        cache.add_pod(pod)
        cache.remove_pod(pod)
        assert cache.get_node_name_to_info_map()["n1"].requested.milli_cpu == 0

    def test_snapshot_isolation(self):
        cache = SchedulerCache()
        cache.add_node(mk_node("n1"))
        snap = cache.get_node_name_to_info_map()
        cache.add_pod(mk_pod("p", cpu="1", node="n1"))
        assert snap["n1"].requested.milli_cpu == 0  # clone, not view


class TestGenericScheduler:
    def _mk(self, predicates=None, priorities=None):
        return GenericScheduler(
            predicates or {"PodFitsResources": preds.pod_fits_resources},
            priorities or [PriorityConfig(prios.least_requested)],
            parallel=False)

    def test_picks_least_loaded(self):
        n1, n2 = mk_node("n1"), mk_node("n2")
        info = {"n1": ni(n1, mk_pod("a", cpu="3", mem="20Gi", node="n1")),
                "n2": ni(n2)}
        assert self._mk().schedule(mk_pod("x", cpu="1"), info, [n1, n2]) == "n2"

    def test_fit_error_reasons(self):
        n1 = mk_node("n1", cpu="1")
        info = {"n1": ni(n1, mk_pod("a", cpu="1", node="n1"))}
        with pytest.raises(FitError) as ei:
            self._mk().schedule(mk_pod("x", cpu="1"), info, [n1])
        assert "Insufficient cpu" in ei.value.failed_predicates["n1"]

    def test_round_robin_tie_break(self):
        sched = self._mk(priorities=[PriorityConfig(prios.equal_priority)])
        nodes = [mk_node("a"), mk_node("b"), mk_node("c")]
        info = {n.metadata.name: ni(n) for n in nodes}
        picks = [sched.schedule(mk_pod(f"p{i}"), info, nodes) for i in range(6)]
        assert picks == ["a", "b", "c", "a", "b", "c"]

    def test_no_nodes(self):
        with pytest.raises(FitError, match="no nodes"):
            self._mk().schedule(mk_pod("x"), {}, [])

    def test_weighted_sum(self):
        def prio_a(pod, info, nodes):
            return {"n1": 1, "n2": 2}

        def prio_b(pod, info, nodes):
            return {"n1": 10, "n2": 0}

        sched = GenericScheduler(
            {}, [PriorityConfig(prio_a, weight=5), PriorityConfig(prio_b, weight=1)],
            parallel=False)
        nodes = [mk_node("n1"), mk_node("n2")]
        scores = sched.prioritize_nodes(mk_pod(), {}, nodes)
        assert scores == {"n1": 15, "n2": 10}


class TestObjectiveProviderSeam:
    """The objective registry rides the provider boundary exactly like the
    predicate/priority registries: register by name, select by name (config
    or policy file), loud KeyError on unknown names."""

    def test_builtin_objectives_registered(self):
        from kubernetes_tpu.scheduler import provider

        names = provider.objective_names()
        for name in ("default", "binpack", "preempt", "gang",
                     "gang_preempt"):
            assert name in names
        assert provider.get_objective("binpack").binpack
        assert provider.get_objective("gang_preempt").gang
        assert provider.get_objective("gang_preempt").preempt
        assert not provider.get_objective("default").enabled

    def test_register_custom_objective(self):
        from kubernetes_tpu.scheduler import provider

        cfg = provider.ObjectiveConfig(name="packed-trainings",
                                       binpack=True, gang=True,
                                       binpack_weight=3)
        provider.register_objective("packed-trainings", cfg)
        got = provider.get_objective("packed-trainings")
        assert got is cfg and got.enabled

    def test_unknown_objective_raises(self):
        from kubernetes_tpu.scheduler import provider

        with pytest.raises(KeyError, match="no-such-objective"):
            provider.get_objective("no-such-objective")

    def test_non_config_registration_rejected(self):
        from kubernetes_tpu.scheduler import provider

        with pytest.raises(TypeError):
            provider.register_objective("bad", {"binpack": True})

    def test_policy_objective_selection(self):
        from kubernetes_tpu.scheduler.provider import (
            PluginArgs, load_policy, policy_objective,
        )

        policy = {"predicates": [{"name": "PodFitsResources"}],
                  "priorities": [{"name": "LeastRequestedPriority",
                                  "weight": 2}],
                  "objective": "binpack"}
        assert policy_objective(policy).binpack
        predicates, priorities, _ext = load_policy(policy, PluginArgs())
        assert "PodFitsResources" in predicates
        assert priorities[0].weight == 2

    def test_policy_unknown_objective_fails_load(self):
        from kubernetes_tpu.scheduler.provider import PluginArgs, load_policy

        with pytest.raises(KeyError, match="typo-objective"):
            load_policy({"predicates": [], "priorities": [],
                         "objective": "typo-objective"}, PluginArgs())

    def test_provider_objective_key(self):
        from kubernetes_tpu.scheduler.provider import (
            get_provider, register_algorithm_provider,
        )

        register_algorithm_provider(
            "BinpackProviderForTest", ["PodFitsResources"],
            ["LeastRequestedPriority", "MostRequestedPriority"],
            objective="binpack")
        prov = get_provider("BinpackProviderForTest")
        assert prov["objective"] == "binpack"
        with pytest.raises(KeyError):
            register_algorithm_provider("BrokenProviderForTest", [], [],
                                        objective="not-registered")

    def test_most_requested_priority_math(self):
        # the binpack objective's sequential reference: fuller nodes win,
        # _calculate_score inverted with the same integer truncation
        node_a = mk_node("a", cpu="4000m", mem="10Gi")
        node_b = mk_node("b", cpu="4000m", mem="10Gi")
        hog = mk_pod("hog", cpu="2000m", mem="5Gi", node="a")
        info = {"a": ni(node_a, hog), "b": ni(node_b)}
        pod = mk_pod("new", cpu="1000m", mem="2560Mi")
        scores = prios.most_requested(pod, info, [node_a, node_b])
        # a: cpu (2000+1000)*10/4000 = 7; mem (5G+2.5G)*10/10G = 7 -> 7
        # b: cpu 1000*10/4000 = 2; mem 2.5*10/10 = 2 -> 2
        assert scores == {"a": 7, "b": 2}
