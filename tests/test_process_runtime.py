"""ProcessRuntime + kubelet node server: real processes behind the kubelet.

Parity target: reference pkg/kubelet/dockertools/docker_manager.go (a
runtime that runs real workloads) and pkg/kubelet/server/server.go:237-298
(logs/exec served on the node port). Round-4 verdict #5's done-criterion,
verbatim: an e2e test schedules a pod, reads real logs via kubectl logs,
kills the process, and PLEG observes + restart policy applies.
"""

import io
import os
import signal
import sys
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.kubelet.runtime import FakeCadvisor
from kubernetes_tpu.kubelet.server import KubeletServer


def mk_pod(name, command, restart_policy="Always", ns="default"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.PodSpec(
            restart_policy=restart_policy,
            containers=[api.Container(
                name="main", image="pause", command=command,
                resources=api.ResourceRequirements(
                    requests={"cpu": "100m", "memory": "64Mi"}))]))


def wait_for(cond, timeout=20.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class TestProcessRuntimeUnit:
    """Runtime alone: spawn/observe/kill/restart/logs/exec on real PIDs."""

    @pytest.fixture()
    def rt(self, tmp_path):
        rt = ProcessRuntime(root_dir=str(tmp_path / "pods"))
        try:
            yield rt
        finally:
            rt.cleanup()

    def test_spawn_observe_logs(self, rt):
        pod = mk_pod("w", ["/bin/sh", "-c",
                           "echo hello-from-pod; sleep 600"])
        rt.sync_pod(pod)
        assert rt.container_states("default/w") == {"main": "running"}
        pid = int(rt.running()["default/w"].container_ids[0]
                  .split("//")[1])
        assert os.path.exists(f"/proc/{pid}")
        wait_for(lambda: "hello-from-pod" in rt.logs("default/w", "main"),
                 msg="log line")

    def test_pause_equivalent_for_commandless_container(self, rt):
        rt.sync_pod(mk_pod("p", None))
        assert rt.container_states("default/p") == {"main": "running"}

    def test_kill_pod_reaps_process_group(self, rt):
        rt.sync_pod(mk_pod("k", ["/bin/sh", "-c", "sleep 600"]))
        pid = int(rt.running()["default/k"].container_ids[0].split("//")[1])
        rt.kill_pod("default/k")
        wait_for(lambda: not os.path.exists(f"/proc/{pid}")
                 or open(f"/proc/{pid}/stat").read().split()[2] == "Z",
                 msg="process reaped")
        assert rt.running() == {}

    def test_external_kill_observed_and_restart(self, rt):
        rt.sync_pod(mk_pod("c", ["/bin/sh", "-c", "echo run-$$; sleep 600"]))
        pid = int(rt.running()["default/c"].container_ids[0].split("//")[1])
        # the banner must hit the log before the kill, or .prev is empty
        wait_for(lambda: "run-" in rt.logs("default/c", "main"),
                 msg="first-incarnation banner")
        os.kill(pid, signal.SIGKILL)
        wait_for(lambda: rt.container_states("default/c")["main"] == "dead",
                 msg="death observed")
        rt.restart_container("default/c", "main")
        assert rt.container_states("default/c")["main"] == "running"
        rp = rt.running()["default/c"]
        assert rp.restart_counts["main"] == 1
        new_pid = int(rp.container_ids[0].split("//")[1])
        assert new_pid != pid
        # the previous incarnation's log survives
        wait_for(lambda: "run-" in rt.logs("default/c", "main",
                                           previous=True),
                 msg="previous log")

    def test_exec_runs_in_pod_context(self, rt):
        rt.sync_pod(mk_pod("e", ["/bin/sh", "-c", "sleep 600"]))
        rc, out = rt.exec("default/e", "main",
                          ["/bin/sh", "-c", "echo $POD_NAME:$CONTAINER_NAME"])
        assert rc == 0 and out.strip() == "e:main"
        rc, _ = rt.exec("default/e", "main", ["/bin/false"])
        assert rc == 1

    def test_exec_probe_runs_real_commands(self, rt):
        rt.sync_pod(mk_pod("pr", ["/bin/sh", "-c", "touch ready; sleep 600"]))
        wait_for(lambda: rt.exec_probe("default/pr", "main",
                                       ["test", "-f", "ready"]) == 0,
                 msg="probe file")
        assert rt.exec_probe("default/pr", "main",
                             ["test", "-f", "missing"]) != 0


class TestKubeletE2E:
    """The verdict's exact scenario through the full stack."""

    @pytest.fixture()
    def stack(self, tmp_path):
        server = APIServer().start()
        client = RESTClient.for_server(server)
        rt = ProcessRuntime(root_dir=str(tmp_path / "pods"))
        ks = KubeletServer(rt).start()
        kl = Kubelet(client, "pnode", runtime=rt, cadvisor=FakeCadvisor(),
                     heartbeat_period=1.0, sync_period=0.2)
        kl.server_port = ks.port
        kl.start()
        try:
            yield server, client, rt, ks, kl
        finally:
            kl.stop()
            ks.stop()
            rt.cleanup()
            server.stop()

    def _schedule(self, client, pod):
        client.create("pods", pod)
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name=pod.metadata.name),
            target=api.ObjectReference(kind="Node", name="pnode")),
            pod.metadata.namespace or "default")

    def test_logs_exec_kill_restart_via_kubectl(self, stack, capsys):
        server, client, rt, ks, kl = stack
        from kubernetes_tpu.kubectl.cmd import main as kubectl
        host = ["-s", f"127.0.0.1:{server.port}"]

        self._schedule(client, mk_pod(
            "web", ["/bin/sh", "-c", "echo serving-requests; sleep 600"]))
        # kubelet picks the binding up from its watch and starts a REAL pid
        wait_for(lambda: "default/web" in rt.running(), msg="pod running")
        wait_for(lambda: "serving-requests" in rt.logs("default/web", "main"),
                 msg="log output")
        # node published its kubelet endpoint
        wait_for(lambda: (client.get("nodes", "pnode").status.daemon_endpoints
                          or None) is not None, msg="daemon endpoint")

        # kubectl logs reads the real stream through the node server
        assert kubectl(host + ["logs", "web"]) == 0
        assert "serving-requests" in capsys.readouterr().out

        # kubectl exec runs a real argv in the pod context
        assert kubectl(host + ["exec", "web", "--", "/bin/sh", "-c",
                               "echo from-exec-$POD_NAME"]) == 0
        assert "from-exec-web" in capsys.readouterr().out

        # kill the real process; PLEG observes; restartPolicy=Always respawns
        pid = int(rt.running()["default/web"].container_ids[0].split("//")[1])
        os.kill(pid, signal.SIGKILL)
        wait_for(lambda: rt.running().get("default/web") is not None
                 and rt.running()["default/web"].restart_counts.get("main", 0)
                 >= 1, msg="PLEG-driven restart")
        new_pid = int(rt.running()["default/web"].container_ids[0]
                      .split("//")[1])
        assert new_pid != pid
        # restart visible in pod status through the API
        wait_for(lambda: (client.get("pods", "web", "default").status
                          .container_statuses or [None])[0] is not None
                 and client.get("pods", "web", "default").status
                 .container_statuses[0].restart_count >= 1,
                 msg="restartCount in API status")

    def test_restart_policy_never_goes_failed(self, stack):
        server, client, rt, ks, kl = stack
        self._schedule(client, mk_pod(
            "once", ["/bin/sh", "-c", "echo did-work; exit 3"],
            restart_policy="Never"))
        wait_for(lambda: client.get("pods", "once", "default").status.phase
                 == api.POD_FAILED, msg="phase=Failed")
        # no respawn happened
        rp = rt.running().get("default/once")
        assert rp is None or rp.restart_counts.get("main", 0) == 0

    def test_completed_command_succeeds(self, stack):
        server, client, rt, ks, kl = stack
        self._schedule(client, mk_pod(
            "job1", ["/bin/sh", "-c", "echo done"],
            restart_policy="OnFailure"))
        wait_for(lambda: client.get("pods", "job1", "default").status.phase
                 == api.POD_SUCCEEDED, msg="phase=Succeeded")

    def test_sidecar_clean_exit_does_not_kill_worker(self, stack):
        """OnFailure pod, one short task exiting 0 + one long worker: the
        clean exit must NOT kill the worker or mark the pod Succeeded;
        the pod completes only when all containers have exited."""
        server, client, rt, ks, kl = stack
        pod = api.Pod(
            metadata=api.ObjectMeta(name="duo", namespace="default"),
            spec=api.PodSpec(
                restart_policy="OnFailure",
                containers=[
                    api.Container(name="task", image="pause",
                                  command=["/bin/sh", "-c", "exit 0"]),
                    api.Container(name="worker", image="pause",
                                  command=["/bin/sh", "-c",
                                           "sleep 2; exit 0"])]))
        client.create("pods", pod)
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name="duo"),
            target=api.ObjectReference(kind="Node", name="pnode")), "default")
        wait_for(lambda: "default/duo" in rt.running(), msg="pod running")
        # the task exits immediately; the worker must survive it
        wait_for(lambda: rt.container_states("default/duo")
                 .get("task") == "dead", msg="task done")
        assert rt.container_states("default/duo").get("worker") == "running"
        assert client.get("pods", "duo", "default").status.phase \
            != api.POD_SUCCEEDED
        # both done -> Succeeded
        wait_for(lambda: client.get("pods", "duo", "default").status.phase
                 == api.POD_SUCCEEDED, msg="phase=Succeeded after both exit")

    def test_exec_with_container_flag_and_blank_arg(self, stack, capsys):
        server, client, rt, ks, kl = stack
        from kubernetes_tpu.kubectl.cmd import main as kubectl
        host = ["-s", f"127.0.0.1:{server.port}"]
        pod = api.Pod(
            metadata=api.ObjectMeta(name="two", namespace="default"),
            spec=api.PodSpec(containers=[
                api.Container(name="a", image="pause"),
                api.Container(name="b", image="pause")]))
        client.create("pods", pod)
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name="two"),
            target=api.ObjectReference(kind="Node", name="pnode")), "default")
        wait_for(lambda: "default/two" in rt.running(), msg="pod running")
        # -c selects the named container (REMAINDER must not eat the flag)
        assert kubectl(host + ["exec", "two", "-c", "b", "--", "/bin/sh",
                               "-c", "echo in-$CONTAINER_NAME"]) == 0
        assert "in-b" in capsys.readouterr().out
        # a blank argv element survives the query string round-trip
        assert kubectl(host + ["exec", "two", "--", "printf", "[%s]",
                               ""]) == 0
        assert "[]" in capsys.readouterr().out

    def test_bad_taillines_is_400_not_dropped_conn(self, stack):
        server, client, rt, ks, kl = stack
        import http.client as hc
        self._schedule(client, mk_pod("lg", None))
        wait_for(lambda: "default/lg" in rt.running(), msg="pod running")
        conn = hc.HTTPConnection("127.0.0.1", ks.port, timeout=5)
        try:
            conn.request("GET", "/containerLogs/default/lg/main?tailLines=abc")
            assert conn.getresponse().status == 400
        finally:
            conn.close()
