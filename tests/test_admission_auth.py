"""Admission chain + authn/authz coverage (reference pkg/admission,
plugin/pkg/admission/*, pkg/auth, plugin/pkg/auth)."""

import pytest

from kubernetes_tpu.admission import AdmissionError, Attributes, new_chain
from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.auth import (
    ABACAuthorizer, AuthzAttributes, BasicAuthenticator, RBACAuthorizer,
    TokenAuthenticator, UnionAuthenticator, UserInfo,
)
from kubernetes_tpu.apis import rbac
from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.registry.generic import Registry


def _pod(name, ns="default", cpu=None, privileged=False, **meta):
    sc = api.SecurityContext(privileged=True) if privileged else None
    res = (api.ResourceRequirements(requests={"cpu": cpu, "memory": "64Mi"})
           if cpu else None)
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, **meta),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="img", resources=res, security_context=sc)]))


class TestNamespacePlugins:
    def test_lifecycle_rejects_missing_and_terminating(self):
        reg = Registry()
        chain = new_chain(["NamespaceLifecycle"], registry=reg)
        with pytest.raises(AdmissionError):
            chain.admit(Attributes(resource="pods", namespace="nope",
                                   operation="CREATE", obj=_pod("p", "nope")))
        reg.create("namespaces", api.Namespace(
            metadata=api.ObjectMeta(name="dying"),
            status=api.NamespaceStatus(phase="Terminating")))
        with pytest.raises(AdmissionError) as e:
            chain.admit(Attributes(resource="pods", namespace="dying",
                                   operation="CREATE", obj=_pod("p", "dying")))
        assert "terminating" in str(e.value)
        with pytest.raises(AdmissionError):
            chain.admit(Attributes(resource="namespaces", name="default",
                                   operation="DELETE"))

    def test_autoprovision_creates_namespace(self):
        reg = Registry()
        chain = new_chain(["NamespaceAutoProvision"], registry=reg)
        chain.admit(Attributes(resource="pods", namespace="fresh",
                               operation="CREATE", obj=_pod("p", "fresh")))
        assert reg.get("namespaces", "fresh").metadata.name == "fresh"


class TestLimitRanger:
    def test_defaults_and_max(self):
        reg = Registry()
        reg.create("limitranges", api.LimitRange(
            metadata=api.ObjectMeta(name="lr", namespace="default"),
            spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                type="Container",
                default_request={"cpu": "100m", "memory": "64Mi"},
                max={"cpu": "2"})])), namespace="default")
        chain = new_chain(["LimitRanger"], registry=reg)
        pod = _pod("p")
        chain.admit(Attributes(resource="pods", namespace="default",
                               operation="CREATE", obj=pod))
        assert pod.spec.containers[0].resources.requests["cpu"] == "100m"
        big = _pod("big", cpu="4")
        with pytest.raises(AdmissionError) as e:
            chain.admit(Attributes(resource="pods", namespace="default",
                                   operation="CREATE", obj=big))
        assert "maximum cpu" in str(e.value)


class TestResourceQuota:
    def test_books_usage_and_rejects_over_quota(self):
        reg = Registry()
        reg.create("resourcequotas", api.ResourceQuota(
            metadata=api.ObjectMeta(name="q", namespace="default"),
            spec=api.ResourceQuotaSpec(hard={"pods": "2", "cpu": "1"})),
            namespace="default")
        chain = new_chain(["ResourceQuota"], registry=reg)
        chain.admit(Attributes(resource="pods", namespace="default",
                               operation="CREATE", obj=_pod("a", cpu="600m")))
        q = reg.get("resourcequotas", "q", "default")
        assert q.status.used["pods"] == "1"
        assert q.status.used["cpu"] == "600m"
        with pytest.raises(AdmissionError) as e:
            chain.admit(Attributes(resource="pods", namespace="default",
                                   operation="CREATE", obj=_pod("b", cpu="600m")))
        assert "exceeded quota" in str(e.value)
        # pod without cpu request still counts against pods
        chain.admit(Attributes(resource="pods", namespace="default",
                               operation="CREATE", obj=_pod("c")))
        with pytest.raises(AdmissionError):
            chain.admit(Attributes(resource="pods", namespace="default",
                                   operation="CREATE", obj=_pod("d")))


class TestPolicyPlugins:
    def test_security_context_deny(self):
        chain = new_chain(["SecurityContextDeny"])
        with pytest.raises(AdmissionError):
            chain.admit(Attributes(resource="pods", namespace="default",
                                   operation="CREATE",
                                   obj=_pod("p", privileged=True)))

    def test_always_pull_and_service_account_defaults(self):
        reg = Registry()
        chain = new_chain(["ServiceAccount", "AlwaysPullImages"], registry=reg)
        pod = _pod("p")
        chain.admit(Attributes(resource="pods", namespace="default",
                               operation="CREATE", obj=pod))
        assert pod.spec.service_account_name == "default"
        assert pod.spec.containers[0].image_pull_policy == "Always"

    def test_anti_affinity_limit(self):
        chain = new_chain(["LimitPodHardAntiAffinityTopology"])
        pod = _pod("p")
        pod.spec.affinity = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                api.PodAffinityTerm(topology_key=api.LABEL_ZONE)]))
        with pytest.raises(AdmissionError):
            chain.admit(Attributes(resource="pods", namespace="default",
                                   operation="CREATE", obj=pod))


class TestAdmissionOverHTTP:
    def test_quota_enforced_end_to_end(self):
        server = APIServer(admission_control=["ResourceQuota"]).start()
        try:
            c = RESTClient.for_server(server)
            server.registry.create("resourcequotas", api.ResourceQuota(
                metadata=api.ObjectMeta(name="q", namespace="default"),
                spec=api.ResourceQuotaSpec(hard={"pods": "1"})),
                namespace="default")
            c.create("pods", _pod("one"), namespace="default")
            with pytest.raises(ApiError) as e:
                c.create("pods", _pod("two"), namespace="default")
            assert e.value.code == 403
        finally:
            server.stop()


class TestReviewRegressions:
    def test_quota_released_on_delete(self):
        server = APIServer(admission_control=["ResourceQuota"]).start()
        try:
            c = RESTClient.for_server(server)
            server.registry.create("resourcequotas", api.ResourceQuota(
                metadata=api.ObjectMeta(name="q", namespace="default"),
                spec=api.ResourceQuotaSpec(hard={"pods": "1"})),
                namespace="default")
            for _ in range(3):  # create/delete cycles must not leak usage
                c.create("pods", _pod("cycle"), namespace="default")
                c.delete("pods", "cycle", namespace="default")
            q = server.registry.get("resourcequotas", "q", "default")
            assert q.status.used["pods"] == "0"
        finally:
            server.stop()

    def test_delete_on_scale_subresource_is_405(self):
        server = APIServer().start()
        try:
            c = RESTClient.for_server(server)
            server.registry.create("replicationcontrollers",
                                   api.ReplicationController(
                metadata=api.ObjectMeta(name="rc", namespace="default"),
                spec=api.ReplicationControllerSpec(
                    replicas=1, selector={"a": "b"},
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"a": "b"}),
                        spec=api.PodSpec(containers=[
                            api.Container(name="c", image="i")])))),
                namespace="default")
            with pytest.raises(ApiError) as e:
                c.request("DELETE",
                          "/api/v1/namespaces/default/replicationcontrollers/rc/scale")
            assert e.value.code == 405
            # the parent object must survive the probe
            assert c.get("replicationcontrollers", "rc", "default")
        finally:
            server.stop()

    def test_stale_scale_put_conflicts(self):
        from kubernetes_tpu.apis import extensions as ext
        reg = Registry()
        reg.create("replicationcontrollers", api.ReplicationController(
            metadata=api.ObjectMeta(name="rc", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=1, selector={"a": "b"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"a": "b"}),
                    spec=api.PodSpec(containers=[
                        api.Container(name="c", image="i")])))),
            namespace="default")
        stale = reg.get_scale("replicationcontrollers", "rc", "default")
        fresh = reg.get_scale("replicationcontrollers", "rc", "default")
        fresh.spec.replicas = 10
        reg.update_scale("replicationcontrollers", "rc", "default", fresh)
        stale.spec.replicas = 4
        from kubernetes_tpu.registry.generic import RegistryError
        with pytest.raises(RegistryError) as e:
            reg.update_scale("replicationcontrollers", "rc", "default", stale)
        assert e.value.code == 409

    def test_basic_auth_shared_password(self):
        b = BasicAuthenticator.from_csv("pw,alice,1\npw,bob,2\n")
        import base64
        for user in ("alice", "bob"):
            cred = base64.b64encode(f"{user}:pw".encode()).decode()
            assert b.authenticate({"Authorization": f"Basic {cred}"}).name == user

    def test_status_update_skips_admission(self):
        server = APIServer(admission_control=["LimitRanger"]).start()
        try:
            c = RESTClient.for_server(server)
            pod = c.create("pods", _pod("p"), namespace="default")
            # now add a LimitRange with a min that the existing pod violates
            server.registry.create("limitranges", api.LimitRange(
                metadata=api.ObjectMeta(name="lr", namespace="default"),
                spec=api.LimitRangeSpec(limits=[api.LimitRangeItem(
                    type="Container", min={"cpu": "500m"})])),
                namespace="default")
            pod.status = api.PodStatus(phase="Running")
            updated = c.update_status("pods", pod, namespace="default")
            assert updated.status.phase == "Running"
        finally:
            server.stop()


class TestAuthenticators:
    def test_token_and_basic_union(self):
        tok = TokenAuthenticator.from_csv("s3cret,alice,1,admins|devs\n")
        basic = BasicAuthenticator.from_csv("pw,bob,2\n")
        union = UnionAuthenticator([tok, basic])
        info = union.authenticate({"Authorization": "Bearer s3cret"})
        assert info.name == "alice" and "admins" in info.groups
        assert "system:authenticated" in info.groups
        import base64
        cred = base64.b64encode(b"bob:pw").decode()
        assert union.authenticate({"Authorization": f"Basic {cred}"}).name == "bob"


class TestAuthorizers:
    def test_abac(self):
        authz = ABACAuthorizer.from_file_text(
            '{"user":"alice","resource":"*","namespace":"*"}\n'
            '{"kind":"Policy","spec":{"user":"bob","readonly":true,"resource":"pods"}}\n')
        alice = UserInfo(name="alice")
        bob = UserInfo(name="bob")
        assert authz.authorize(AuthzAttributes(user=alice, verb="create",
                                               resource="pods", namespace="x"))
        assert authz.authorize(AuthzAttributes(user=bob, verb="get",
                                               resource="pods"))
        assert not authz.authorize(AuthzAttributes(user=bob, verb="create",
                                                   resource="pods"))

    def test_rbac(self):
        reg = Registry()
        reg.create("clusterroles", rbac.ClusterRole(
            metadata=api.ObjectMeta(name="pod-reader"),
            rules=[rbac.PolicyRule(verbs=["get", "list"], resources=["pods"],
                                   api_groups=[""])]))
        reg.create("clusterrolebindings", rbac.ClusterRoleBinding(
            metadata=api.ObjectMeta(name="read-pods"),
            subjects=[rbac.Subject(kind="User", name="carol")],
            role_ref=api.ObjectReference(kind="ClusterRole", name="pod-reader")))
        authz = RBACAuthorizer(reg)
        carol = UserInfo(name="carol")
        assert authz.authorize(AuthzAttributes(user=carol, verb="list",
                                               resource="pods", namespace="default"))
        assert not authz.authorize(AuthzAttributes(user=carol, verb="create",
                                                   resource="pods"))
        assert not authz.authorize(AuthzAttributes(user=UserInfo(name="eve"),
                                                   verb="list", resource="pods"))


class TestAuthOverHTTP:
    def test_secure_server_requires_token_and_authorizes(self):
        reg = Registry()
        authn = TokenAuthenticator.from_csv("tik,alice,1\nrok,bob,2\n")
        authz = ABACAuthorizer.from_file_text(
            '{"user":"alice","resource":"*","namespace":"*"}\n'
            '{"user":"bob","readonly":true,"resource":"pods","namespace":"*"}\n')
        server = APIServer(registry=reg, authenticator=authn,
                           authorizer=authz).start()
        try:
            anon = RESTClient.for_server(server)
            with pytest.raises(ApiError) as e:
                anon.list("pods", "default")
            assert e.value.code == 401

            alice = RESTClient.for_server(server, bearer_token="tik")
            alice.create("pods", _pod("p1"), namespace="default")

            bob = RESTClient.for_server(server, bearer_token="rok")
            pods, _ = bob.list("pods", "default")
            assert len(pods) == 1
            with pytest.raises(ApiError) as e:
                bob.create("pods", _pod("p2"), namespace="default")
            assert e.value.code == 403
        finally:
            server.stop()
