"""Wave-vs-serial parity: the wave commit must reproduce the serial FIFO
solve bit-for-bit — assignments, preemption victim counts, gang verdicts,
and every explain output (survivor counts, winner/runner-up score
decompositions) — across all five objective modes, on randomized clusters
that exercise the full carry surface (ports, disks, EBS/GCE volumes,
inter-pod affinity, spread groups, taints, priorities, gangs).

Also pins the degradation contract: a preemption storm (every pod needs a
victim nomination) collapses waves to single-pod commits — wave count
grows to P, the result stays exact — while a homogeneous no-conflict batch
solves in O(P/chunk) waves.
"""

import random

import jax
import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.kernel import Weights, _schedule_jit, features_of
from kubernetes_tpu.ops.tensorize import Tensorizer
from kubernetes_tpu.scheduler.batch import ListServiceLister, make_plugin_args
from kubernetes_tpu.scheduler.objectives.config import (
    GANG_LABEL, PRIORITY_ANNOTATION, gang_order, get_objective,
)

MODES = ["default", "binpack", "preempt", "gang", "gang_preempt"]


def mk_node(i, cpu="4", mem="16Gi", pods="32", extra_labels=None,
            taints=None):
    labels = {api.LABEL_HOSTNAME: f"n{i:03d}", api.LABEL_ZONE: f"z{i % 4}"}
    labels.update(extra_labels or {})
    return api.Node(
        metadata=api.ObjectMeta(name=f"n{i:03d}", labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


def mk_pod(name, cpu="200m", mem="256Mi", labels=None, ann=None, node="",
           selector=None, affinity=None, tolerations=None, host_port=None,
           volumes=None):
    ports = ([api.ContainerPort(container_port=8080, host_port=host_port)]
             if host_port else None)
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels, annotations=ann),
        spec=api.PodSpec(
            node_name=node, node_selector=selector, affinity=affinity,
            tolerations=tolerations, volumes=volumes,
            containers=[api.Container(
                name="c", image="pause", ports=ports,
                resources=api.ResourceRequirements(
                    requests={"cpu": cpu, "memory": mem}))]))


def build_cluster(seed, n_nodes=24, n_pods=40):
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        extra = {"disk": "ssd"} if i % 3 == 0 else None
        taints = ([api.Taint(key="ded", value="x", effect="NoSchedule")]
                  if i % 8 == 5 else None)
        nodes.append(mk_node(i, extra_labels=extra, taints=taints))
    svc = api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(port=80)]))
    existing = []
    for i in range(n_nodes):
        kw = {}
        if i % 5 == 0:
            kw["affinity"] = api.Affinity(
                pod_anti_affinity=api.PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=[
                        api.PodAffinityTerm(
                            label_selector=api.LabelSelector(
                                match_labels={"sym": f"s{i % 3}"}),
                            topology_key=api.LABEL_HOSTNAME)]))
        elif i % 5 == 1:
            kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    api.WeightedPodAffinityTerm(
                        weight=3,
                        pod_affinity_term=api.PodAffinityTerm(
                            label_selector=api.LabelSelector(
                                match_labels={"app": "web"}),
                            topology_key=api.LABEL_ZONE))]))
        existing.append(mk_pod(
            f"e{i:03d}", cpu=f"{rng.choice([300, 500, 800])}m",
            labels={"app": "existing"},
            ann={PRIORITY_ANNOTATION: str(i % 3)},
            node=f"n{i % n_nodes:03d}", **kw))
    pending = []
    for i in range(n_pods):
        labels = {"app": "web" if i % 3 == 0 else f"batch-{i % 5}"}
        kw = {}
        if i % 4 == 0:
            labels[GANG_LABEL] = f"g{i // 12}"
        if i % 8 == 1:
            kw["ann"] = {PRIORITY_ANNOTATION: "5"}
            kw["cpu"] = "900m"
        if i % 7 == 2:
            kw["selector"] = {"disk": "ssd"}
        if i % 7 == 4:
            kw["tolerations"] = [api.Toleration(key="ded",
                                                operator="Exists")]
        if i % 9 == 3:
            kw["host_port"] = 9000 + (i % 3)   # deliberate collisions
        if i % 11 == 6:
            kw["volumes"] = [api.Volume(
                name="d", aws_elastic_block_store=api.
                AWSElasticBlockStoreVolumeSource(
                    volume_id=f"vol-{i % 4}"))]
        if i % 13 == 7:
            labels["sym"] = f"s{i % 3}"        # target of existing anti
        pending.append(mk_pod(f"p{i:03d}", labels=labels, **kw))
    args = make_plugin_args(nodes, service_lister=ListServiceLister([svc]))
    return nodes, existing, pending, args


def solve(ct, obj, explain, wave):
    import jax.numpy as jnp
    arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
    feats = features_of(ct)
    out = _schedule_jit(arrays, ct.n_zones, Weights(), feats, explain,
                        obj, wave)
    return jax.tree_util.tree_map(np.asarray, out)


def assert_trees_equal(serial, wavey, where=""):
    ls, ts = jax.tree_util.tree_flatten_with_path(serial)[0], None
    lw = jax.tree_util.tree_flatten_with_path(wavey)[0]
    assert len(ls) == len(lw), f"{where}: tree structure differs"
    for (pa, va), (pb, vb) in zip(ls, lw):
        assert pa == pb, f"{where}: leaf path {pa} vs {pb}"
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (
            f"{where}: leaf {jax.tree_util.keystr(pa)} differs:\n"
            f"serial={np.asarray(va)}\nwave={np.asarray(vb)}")


# two seeds for the no-objective and everything-on extremes; one for the
# single-mode configs (each compiles 2 programs — suite-time budget)
@pytest.mark.parametrize("mode,seed", [
    ("default", 0), ("default", 1), ("binpack", 0), ("preempt", 0),
    ("gang", 0), ("gang_preempt", 0), ("gang_preempt", 1),
])
def test_mode_parity_explain(mode, seed):
    nodes, existing, pending, args = build_cluster(seed)
    obj = get_objective(mode)
    if obj is not None and obj.gang:
        pending, _ = gang_order(pending)
    ct = Tensorizer(plugin_args=args, objective=obj).build(
        nodes, existing, pending)
    serial = solve(ct, obj, True, 0)
    wavey, waves = solve(ct, obj, True, 16)
    assert int(waves) >= 1
    assert_trees_equal(serial, wavey, where=f"{mode}/seed{seed}/explain")


@pytest.mark.parametrize("mode", ["default", "gang_preempt"])
def test_mode_parity_plain(mode):
    nodes, existing, pending, args = build_cluster(2)
    obj = get_objective(mode)
    if obj is not None and obj.gang:
        pending, _ = gang_order(pending)
    ct = Tensorizer(plugin_args=args, objective=obj).build(
        nodes, existing, pending)
    serial = solve(ct, obj, False, 0)
    wavey, _waves = solve(ct, obj, False, 16)
    assert_trees_equal(serial, wavey, where=f"{mode}/plain")


def test_preemption_storm_degrades_to_serial():
    """Every pending pod needs a victim nomination: waves collapse to
    single-pod serial commits (wave count reaches P), result stays exact —
    the graceful-degradation contract."""
    nodes = [mk_node(i, cpu="2", pods="8") for i in range(6)]
    existing = [mk_pod(f"e{i:02d}", cpu="900m",
                       ann={PRIORITY_ANNOTATION: "0"},
                       node=f"n{i % 6:03d}") for i in range(12)]
    pending = [mk_pod(f"p{i:02d}", cpu="1500m",
                      ann={PRIORITY_ANNOTATION: "9"}) for i in range(12)]
    args = make_plugin_args(nodes)
    obj = get_objective("preempt")
    ct = Tensorizer(plugin_args=args, objective=obj).build(
        nodes, existing, pending)
    serial = solve(ct, obj, False, 0)
    wavey, waves = solve(ct, obj, False, 8)
    assert_trees_equal(serial, wavey, where="storm")
    # every real pod is a potential preemptor -> one wave each (padding
    # rows ride along in bulk waves)
    assert int(waves) >= len(pending)
    # the serial result really did preempt (victim counts nonzero)
    assert np.asarray(serial[1]["pk"]).sum() > 0


def test_homogeneous_batch_is_wavelike():
    """Identical no-conflict pods commit in O(P/chunk) waves — the
    tie-rotation prediction keeps the serial round-robin exact in bulk."""
    nodes = [mk_node(i, cpu="64", mem="256Gi", pods="256")
             for i in range(16)]
    pending = [mk_pod(f"p{i:03d}", cpu="100m", mem="128Mi")
               for i in range(96)]
    args = make_plugin_args(nodes)
    ct = Tensorizer(plugin_args=args).build(nodes, [], pending)
    serial = solve(ct, None, False, 0)
    wavey, waves = solve(ct, None, False, 32)
    assert np.array_equal(serial, wavey)
    pp = serial.shape[0]
    # perfect packing would be ceil(Pp/32) waves; allow a small slack for
    # tie-set wraps, but demand far fewer waves than pods
    assert int(waves) <= max(pp // 32 + 6, 8), int(waves)


def test_wave_count_metric_exported():
    from kubernetes_tpu.ops.kernel import schedule_batch
    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
    nodes = [mk_node(i) for i in range(4)]
    pending = [mk_pod(f"p{i}", cpu="100m") for i in range(6)]
    args = make_plugin_args(nodes)
    ct = Tensorizer(plugin_args=args).build(nodes, [], pending)
    names = schedule_batch(ct, wave=8)
    assert all(n is not None for n in names)
    series = METRICS._gauges.get("scheduler_kernel_wave_count", {})
    assert series and max(series.values()) >= 1.0
