"""Replicated control plane: quorum commits, election safety, and the
crash-recovery matrix (ROADMAP item 4 / ISSUE 14).

The matrix kills a member at each pipeline stage — pre-ack, post-ack/
pre-publish, mid-snapshot, mid-catch-up — and asserts the rejoined member
converges to the leader's state with no resourceVersion regressions. The
meta-invariant everywhere: an event a watcher has SEEN is on a durable
majority, so no single crash can un-happen it.
"""

import json
import os
import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.leaderelection import (
    LeaderElectionConfig, LeaderElector,
)
from kubernetes_tpu.discovery import DiscoveryProxy
from kubernetes_tpu.registry.generic import Registry
from kubernetes_tpu.storage import (
    DurableStore, MemStore, NoQuorum, ReplicatedStore,
)
from kubernetes_tpu.storage.replicated import StoreMember


@pytest.fixture()
def store(tmp_path):
    s = ReplicatedStore.local(str(tmp_path), quorum_deadline=1.0)
    yield s
    s.close()


def digests(group):
    return {m.id: m.state_digest() for m in group.members}


def assert_converged(group):
    ds = {m.state_digest() for m in group.alive_members()}
    assert len(ds) == 1, f"members diverged: {digests(group)}"


class TestQuorumCommit:
    def test_write_is_on_a_majority_before_publish(self, store):
        w = store.watch("/")
        store.create("/k", {"v": 1})
        ev = w.next(timeout=1)
        w.stop()
        # the event was published => the entry must already be durable on
        # a quorum of member disks
        on_disk = sum(
            1 for m in store.group.members
            if any(json.loads(line)["k"] == "/k"
                   for line in open(os.path.join(m._dir, "wal.log")))
        )
        assert ev is not None and on_disk >= store.group.quorum

    def test_leader_kill_preserves_acked_writes(self, store):
        rvs = [store.create(f"/k/{i}", {"i": i}) for i in range(5)]
        killed = store.group.kill_leader()
        # every acked write survives into the new leader's state
        rv6 = store.create("/k/after", {"i": 99})
        assert rv6 == rvs[-1] + 1  # rv stays monotonic across failover
        for i in range(5):
            assert store.get(f"/k/{i}")[0] == {"i": i}
        assert store.group.leader_id != killed
        assert store.group.leader_transitions == 1
        assert store.group.failovers  # the window was measured

    def test_no_quorum_blocks_writes_then_rolls_forward(self, tmp_path):
        s = ReplicatedStore.local(str(tmp_path), quorum_deadline=0.3)
        try:
            s.create("/k/committed", {"v": 1})
            ids = [m.id for m in s.group.members]
            for mid in ids[1:]:
                s.group.kill_member(mid)
            w = s.watch("/", since_rv=s.current_rv)
            with pytest.raises(NoQuorum):
                s.create("/k/stuck", {"v": 2})
            # NOT published, NOT readable: no observer may see a write
            # that never reached a majority
            assert w.next(timeout=0.1) is None
            with pytest.raises(Exception):
                s.get("/k/stuck")
            # quorum returns: the stuck entry must commit FIRST (its rv
            # slot is burned), then new writes proceed in order
            for mid in ids[1:]:
                s.group.restart_member(mid)
            rv = s.create("/k/next", {"v": 3})
            e1, e2 = w.next(timeout=1), w.next(timeout=1)
            assert (e1.key, e2.key) == ("/k/stuck", "/k/next")
            assert e2.rv == rv and e1.rv == rv - 1
            assert_converged(s.group)
            w.stop()
        finally:
            s.close()


class TestCrashRecoveryMatrix:
    """Kill a member at each pipeline stage; the rejoined member must
    converge with no rv regression."""

    def _fill(self, store, n=8):
        for i in range(n):
            store.create(f"/k/{i}", {"i": i})

    def test_kill_pre_ack(self, store):
        group = store.group
        victim = next(m for m in group.members
                      if m.id != group.leader_id)

        def kill_before_delivery(method, member):
            if method == "append_entries" and member is victim \
                    and victim.alive:
                victim.kill()  # dies before it could ack

        group.transport.before_send = kill_before_delivery
        self._fill(store)  # quorum still reachable via the other follower
        group.transport.before_send = None
        rv_before = victim._rv
        group.restart_member(victim.id)
        assert victim._rv >= rv_before  # catch-up never regresses
        assert victim._rv == group.leader()._rv
        assert_converged(group)

    def test_kill_post_ack_pre_publish(self, store):
        group = store.group
        seen = []
        w = store.watch("/")
        orig_apply = store._apply_committed
        state = {"killed": None}

        def kill_after_quorum(entry, prev):
            # the entry IS durable on a quorum here; the publish has not
            # happened yet — kill an acker, then publish anyway
            if state["killed"] is None:
                victim = next(m for m in group.members
                              if m.id != group.leader_id)
                victim.kill()
                state["killed"] = victim
            return orig_apply(entry, prev)

        store._apply_committed = kill_after_quorum
        rv = store.create("/k/x", {"v": 1})
        store._apply_committed = orig_apply
        ev = w.next(timeout=1)
        assert ev is not None and ev.rv == rv  # published exactly once
        assert w.next(timeout=0.1) is None
        self._fill(store)  # keep writing on the surviving quorum
        group.restart_member(state["killed"].id)
        assert_converged(group)
        assert state["killed"]._rv == group.leader()._rv
        w.stop()
        seen  # silence lint

    def test_kill_mid_snapshot(self, store):
        group = store.group
        self._fill(store)
        victim = next(m for m in group.members
                      if m.id != group.leader_id)
        victim.kill()
        # the crash window: snapshot.tmp written, never renamed — and the
        # WAL still holds everything (truncation follows the rename)
        with open(os.path.join(victim._dir, "snapshot.json.tmp"),
                  "w") as f:
            f.write('{"rv": 999, "te')  # torn mid-serialize
        self._fill_more(store)
        group.restart_member(victim.id)
        assert_converged(group)
        assert victim._rv == group.leader()._rv

    def _fill_more(self, store):
        for i in range(8, 12):
            store.create(f"/k/{i}", {"i": i})

    def test_kill_mid_catch_up(self, store):
        group = store.group
        self._fill(store)
        victim = next(m for m in group.members
                      if m.id != group.leader_id)
        victim.kill()
        self._fill_more(store)  # victim now lags

        calls = {"n": 0}

        def kill_during_catchup(method, member):
            if method in ("append_entries", "install_snapshot") \
                    and member is victim and calls["n"] == 0:
                calls["n"] += 1
                victim.kill()  # dies again mid-catch-up

        group.transport.before_send = kill_during_catchup
        group.restart_member(victim.id)  # this catch-up is interrupted
        group.transport.before_send = None
        assert not victim.alive or victim._rv <= group.leader()._rv
        group.restart_member(victim.id)  # second rejoin completes
        assert_converged(group)
        assert victim._rv == group.leader()._rv
        assert calls["n"] == 1

    def test_compacted_leader_serves_snapshot_catchup(self, tmp_path):
        # the WAL-tail path is gone after compaction: catch-up must fall
        # back to a full snapshot install, not fabricate a partial log
        s = ReplicatedStore.local(str(tmp_path), snapshot_every=5,
                                  quorum_deadline=1.0)
        try:
            group = s.group
            victim = next(m for m in group.members
                          if m.id != group.leader_id)
            victim.kill()
            for i in range(12):  # crosses members' snapshot threshold
                s.create(f"/k/{i}", {"i": i})
            lead = group.leader()
            assert lead._snap_rv > 0  # the leader really compacted
            assert lead.read_log_tail(0) is None  # tail unavailable
            group.restart_member(victim.id)
            assert_converged(group)
        finally:
            s.close()


class TestMemberDurability:
    def test_torn_mid_file_member_wal_stops_and_logs(self, tmp_path, caplog):
        d = str(tmp_path / "m")
        m = StoreMember("m0", d)
        m.append_entries(1, [
            {"m": 1, "t": "ADDED", "k": f"/k/{i}", "rv": i + 1,
             "o": {"i": i}} for i in range(3)])
        m.kill()
        # tear the SECOND line and keep two good lines after it: recovery
        # must stop at the tear (no fabricated history across the hole)
        # and say how many entries it dropped
        path = os.path.join(d, "wal.log")
        lines = open(path).read().splitlines()
        lines[1] = lines[1][: len(lines[1]) // 2]
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        with caplog.at_level("WARNING", logger="storage.replicated"):
            r = StoreMember("m0", d)
        assert r._rv == 1  # stopped at the tear
        assert r.dropped_entries == 2  # the torn line + the good one after
        assert any("dropped 2 entries" in rec.getMessage()
                   for rec in caplog.records)

    def test_member_restart_replays_snapshot_plus_tail(self, tmp_path):
        d = str(tmp_path / "m")
        m = StoreMember("m0", d, snapshot_every=4)
        for i in range(10):
            m.append_entries(2, [{"m": 2, "t": "ADDED", "k": f"/k/{i}",
                                  "rv": i + 1, "o": {"i": i}}])
        assert m._snap_rv > 0
        digest = m.state_digest()
        m.kill()
        r = StoreMember("m0", d)
        assert r.state_digest() == digest
        assert r.last_entry_term == 2


class TestRegistryContracts:
    """The typed layer above L0, parameterized over all three stores: the
    bind CAS and the watch-410 contract must hold identically."""

    @pytest.fixture(params=["mem", "durable", "replicated"])
    def registry(self, request, tmp_path):
        if request.param == "mem":
            s = MemStore()
        elif request.param == "durable":
            s = DurableStore(str(tmp_path / "d"))
        else:
            s = ReplicatedStore.local(str(tmp_path / "r"))
        yield Registry(s)
        close = getattr(s, "close", None)
        if close:
            close()

    def _pod(self, name):
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace="default"),
            spec=api.PodSpec(containers=[
                api.Container(name="c", image="pause")]))

    def test_bind_cas_and_watch(self, registry):
        registry.create("pods", self._pod("p1"))
        w = registry.watch("pods", "default", since_rv=0)
        binding = api.Binding(
            metadata=api.ObjectMeta(name="p1", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n1"))
        registry.bind_pod(binding, "default")
        got = registry.get("pods", "p1", "default")
        assert got.spec.node_name == "n1"
        # re-binding to a DIFFERENT node loses the CAS exactly like the
        # reference (same-node re-bind is idempotent)
        from kubernetes_tpu.registry.generic import RegistryError
        binding2 = api.Binding(
            metadata=api.ObjectMeta(name="p1", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n2"))
        with pytest.raises(RegistryError):
            registry.bind_pod(binding2, "default")
        evs = [w.next(timeout=1), w.next(timeout=1)]
        assert [e.type for e in evs] == ["ADDED", "MODIFIED"]
        w.stop()


class TestReplicatedApiserverE2E:
    def test_two_apiservers_one_quorum_with_failover(self, tmp_path):
        """Both apiservers serve one replicated store behind the proxy;
        killing the primary apiserver AND the storage leader mid-traffic
        loses nothing acknowledged."""
        s = ReplicatedStore.local(str(tmp_path))
        reg = Registry(s)
        s1, s2 = APIServer(reg).start(), APIServer(reg).start()
        proxy = DiscoveryProxy([f"127.0.0.1:{s1.port}",
                                f"127.0.0.1:{s2.port}"]).start()
        client = RESTClient(port=proxy.port, qps=1000, burst=1000)
        try:
            for i in range(5):
                client.create("pods", api.Pod(
                    metadata=api.ObjectMeta(name=f"p{i}",
                                            namespace="default"),
                    spec=api.PodSpec(containers=[
                        api.Container(name="c", image="i")])))
            s1.stop()
            s.group.kill_leader()
            # writes keep landing through the surviving apiserver + quorum
            client.create("pods", api.Pod(
                metadata=api.ObjectMeta(name="after", namespace="default"),
                spec=api.PodSpec(containers=[
                    api.Container(name="c", image="i")])))
            pods, _ = client.list("pods", "default")
            assert len(pods) == 6
            assert s.group.leader_transitions == 1
        finally:
            proxy.stop()
            s1.stop()
            s2.stop()
            s.close()


class TestLeaderKillSoak:
    def test_chaos_soak_reports_failover_and_zero_lost_binds(self):
        """The chaos scenario end to end at smoke scale: kill the storage
        leader + the primary apiserver mid-churn; the report must carry a
        recorded failover, zero lost acked bindings, member convergence,
        and wedged=False."""
        from kubernetes_tpu.observability.soak import SoakConfig, run_soak
        cfg = SoakConfig(num_nodes=4, create_rate=20, duration_seconds=4,
                         scrape_period=1, batch_size=16,
                         scenario="leader_kill", kill_at_fraction=0.3,
                         rejoin_after=0.5)
        report = run_soak(cfg)
        fo = report.get("failover")
        assert report.get("wedged") is False, (report.get("error"), fo)
        assert fo, "leader_kill report must carry its failover block"
        assert fo["chaos_fired"] is True
        assert fo["lost_bindings"] == 0
        assert fo["leader_transitions"] >= 1
        assert fo["failover_seconds"] is not None
        assert fo["acked_binds_tracked"] > 0
        assert fo["members_converged"] is True
        assert report.get("flight_recorder_bundle")


class TestLeaseRelease:
    def test_graceful_stop_hands_over_immediately(self, tmp_path):
        """The release-on-stop satellite: a cleanly-stopped leader zeroes
        the lease and the successor acquires in ~retry_period, not
        lease_duration."""
        server = APIServer(Registry(MemStore())).start()
        mk = lambda name: RESTClient.for_server(  # noqa: E731
            server, qps=1000, burst=1000, user_agent=name)
        cfg = dict(lock_namespace="default", lock_name="ha-lock",
                   lease_duration=30.0,  # a crash handover would take 30s
                   renew_deadline=5.0, retry_period=0.1)
        flags = {"a": threading.Event(), "b": threading.Event()}
        a = LeaderElector(mk("a"), LeaderElectionConfig(identity="a", **cfg),
                          on_started_leading=flags["a"].set)
        b = LeaderElector(mk("b"), LeaderElectionConfig(identity="b", **cfg),
                          on_started_leading=flags["b"].set)
        try:
            a.run()
            assert flags["a"].wait(10)
            b.run()
            time.sleep(0.3)  # b is now in its acquire loop, blocked on a
            assert not b.is_leader
            t0 = time.monotonic()
            a.stop()  # graceful: releases the lease record
            assert flags["b"].wait(10), "successor never acquired"
            handover = time.monotonic() - t0
            # far faster than the 30s lease a crash would cost; generous
            # bound for slow CI
            assert handover < 10.0
        finally:
            a.stop()
            b.stop()
            server.stop()
