"""Discovery proxy: one endpoint fronting several API planes.

Parity target: reference cmd/kubernetes-discovery — merged /apis group
discovery plus transparent routing of resource requests to the upstream
serving their group. Driven with a real RESTClient pointed at the proxy,
CRUD-ing resources that live on different upstreams, including a
streaming watch through the proxy.
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apis import federation as fedapi
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.discovery import DiscoveryProxy


@pytest.fixture()
def planes():
    core = APIServer().start()
    fed = APIServer().start()
    proxy = DiscoveryProxy([f"127.0.0.1:{core.port}",
                            f"127.0.0.1:{fed.port}"]).start()
    try:
        yield core, fed, proxy
    finally:
        proxy.stop()
        core.stop()
        fed.stop()


def test_merged_group_discovery(planes):
    core, fed, proxy = planes
    client = RESTClient(port=proxy.port)
    doc = client.request("GET", "/apis")
    names = {g["name"] for g in doc["groups"]}
    assert "federation" in names and "batch" in names


def test_core_requests_route_to_primary(planes):
    core, fed, proxy = planes
    client = RESTClient(port=proxy.port)
    client.create("pods", api.Pod(
        metadata=api.ObjectMeta(name="p", namespace="default"),
        spec=api.PodSpec(containers=[api.Container(name="c", image="i")])))
    # landed on the primary, not the secondary
    assert RESTClient.for_server(core).get("pods", "p", "default")
    from kubernetes_tpu.client.rest import ApiError
    with pytest.raises(ApiError):
        RESTClient.for_server(fed).get("pods", "p", "default")


def test_group_requests_route_by_group(planes):
    core, fed, proxy = planes
    # the cluster registry object is written through the proxy and must
    # land on the upstream addressed by its group — here both serve the
    # group, so primary precedence applies
    client = RESTClient(port=proxy.port)
    client.create("clusters", fedapi.Cluster(
        metadata=api.ObjectMeta(name="m1"),
        spec=fedapi.ClusterSpec(server_address="127.0.0.1:1")))
    assert RESTClient.for_server(core).get("clusters", "m1")


def test_watch_streams_through_proxy(planes):
    core, fed, proxy = planes
    client = RESTClient(port=proxy.port)
    stream = client.watch("pods", "default")
    try:
        direct = RESTClient.for_server(core)
        direct.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="w1", namespace="default"),
            spec=api.PodSpec(containers=[
                api.Container(name="c", image="i")])))
        deadline = time.monotonic() + 10
        got = None
        it = iter(stream)
        while time.monotonic() < deadline and got is None:
            etype, obj = next(it)
            if etype == "ADDED" and obj.metadata.name == "w1":
                got = obj
        assert got is not None
    finally:
        stream.stop()


def test_unknown_group_404(planes):
    core, fed, proxy = planes
    client = RESTClient(port=proxy.port)
    from kubernetes_tpu.client.rest import ApiError
    with pytest.raises(ApiError) as ei:
        client.request("GET", "/apis/nosuch.group/v1/things")
    assert ei.value.code == 404


def test_entrypoint(planes):
    import subprocess
    import sys
    core, fed, proxy = planes
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.discovery",
         "--server", f"127.0.0.1:{core.port}",
         "--server", f"127.0.0.1:{fed.port}", "--port", "0"],
        stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert "discovery proxy listening on" in line, line
        port = int(line.strip().rsplit(":", 1)[1])
        doc = RESTClient(port=port).request("GET", "/apis")
        assert any(g["name"] == "federation" for g in doc["groups"])
    finally:
        proc.terminate()
        proc.wait(timeout=10)
