"""Component entrypoints + localup: a real multi-process cluster
(round-3 verdict #6).

Every component boots as its own OS process via `python -m`, flags bound
to componentconfig objects served live at /configz, and kubectl (also a
subprocess) drives the cluster end to end — the local-up-cluster.sh
experience (reference plugin/cmd/* binaries + hack/local-up-cluster.sh)."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from kubernetes_tpu.localup import LocalCluster


def kubectl(master, *args):
    out = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.kubectl", "-s", master, *args],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    return out.stdout


@pytest.mark.slow
def test_multiprocess_cluster_end_to_end(tmp_path):
    cluster = LocalCluster(nodes=2, port=0,
                           data_dir=str(tmp_path / "apiserver"))
    cluster.start(timeout=90)
    try:
        master = cluster.master_url

        # kubectl sees both hollow nodes Ready
        out = kubectl(master, "get", "nodes")
        assert "node-00" in out and "node-01" in out

        # /configz serves the live componentconfig on the apiserver
        with urllib.request.urlopen(f"{master}/configz", timeout=10) as r:
            configz = json.loads(r.read())
        assert configz["apiserver"]["data_dir"].endswith("apiserver")
        assert configz["apiserver"]["max_in_flight"] == 400

        # create a pod via kubectl -f; the out-of-process scheduler binds
        # it and the kubelet runs it
        manifest = tmp_path / "pod.json"
        manifest.write_text(json.dumps({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "hello", "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "pause",
                "resources": {"requests": {"cpu": "100m",
                                           "memory": "64Mi"}}}]},
        }))
        kubectl(master, "create", "-f", str(manifest))

        deadline = time.monotonic() + 60
        phase = ""
        while time.monotonic() < deadline:
            out = kubectl(master, "get", "pods")
            if "Running" in out:
                phase = "Running"
                break
            time.sleep(0.5)
        assert phase == "Running", out

        # scale via a deployment-less RC path: kubectl run creates an RC,
        # the controller-manager (separate process) stamps replicas
        kubectl(master, "run", "web", "--image=pause", "--replicas=3")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            out = kubectl(master, "get", "pods")
            if out.count("Running") >= 4:  # hello + 3 replicas
                break
            time.sleep(0.5)
        assert out.count("Running") >= 4, out
    finally:
        cluster.stop()

    # durability across a full cluster restart: same data-dir, objects back
    cluster2 = LocalCluster(nodes=2, port=0,
                            data_dir=str(tmp_path / "apiserver"))
    cluster2.start(timeout=90)
    try:
        out = kubectl(cluster2.master_url, "get", "pods")
        assert "hello" in out
    finally:
        cluster2.stop()
