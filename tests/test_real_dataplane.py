"""The service dataplane moving REAL bytes to REAL backend processes.

Parity target: reference pkg/proxy/userspace (proxysocket.go relay +
roundrobin.go) fronting real workloads — the round-4 verdict's "the fake
IS the only implementation" gap, closed: process-runtime pods serve HTTP,
the endpoints controller publishes their (dialable) addresses, and the
userspace proxier relays client connections — including on the service's
actual NodePort — round-robin across them.
"""

import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.controllers.endpoints_controller import EndpointsController
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.kubelet.runtime import FakeCadvisor
from kubernetes_tpu.proxy.userspace import UserspaceProxier


def wait_for(cond, timeout=30.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def http_pod(name, port, body, app):
    """A real HTTP server process answering with a fixed body."""
    script = (
        "import http.server\n"
        "class H(http.server.BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        f"        data = {body!r}.encode()\n"
        "        self.send_response(200)\n"
        "        self.send_header('Content-Length', str(len(data)))\n"
        "        self.end_headers()\n"
        "        self.wfile.write(data)\n"
        "    def log_message(self, *a):\n"
        "        pass\n"
        f"http.server.HTTPServer(('127.0.0.1', {port}), H).serve_forever()\n")
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels={"app": app}),
        spec=api.PodSpec(containers=[api.Container(
            name="srv", image="python",
            command=["python3", "-c", script],
            ports=[api.ContainerPort(name="http", container_port=port)])]))


def fetch(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=5) as r:
        return r.read().decode()


@pytest.fixture()
def stack(tmp_path):
    server = APIServer().start()
    client = RESTClient.for_server(server)
    rt = ProcessRuntime(root_dir=str(tmp_path / "pods"))
    kl = Kubelet(client, "dpnode", runtime=rt, cadvisor=FakeCadvisor(),
                 heartbeat_period=5.0, sync_period=0.2)
    kl.start()
    epc = EndpointsController(client)
    epc.start()
    try:
        yield server, client, rt
    finally:
        epc.stop()
        kl.stop()
        rt.cleanup()
        server.stop()
        # give daemon relay threads a beat to release their sockets
        time.sleep(0.1)


def _bind(client, name):
    client.bind(api.Binding(
        metadata=api.ObjectMeta(name=name),
        target=api.ObjectReference(kind="Node", name="dpnode")), "default")


def test_selector_service_relays_to_real_backend(stack):
    """Full chain with real bytes: selector -> endpoints controller ->
    dialable 127.0.0.1 address + named-port resolution -> relay."""
    server, client, rt = stack
    client.create("pods", http_pod("b1", 18081, "hello-from-b1", app="one"))
    _bind(client, "b1")
    wait_for(lambda: "default/b1" in rt.running(), msg="backend running")
    client.create("services", api.Service(
        metadata=api.ObjectMeta(name="one", namespace="default"),
        spec=api.ServiceSpec(
            selector={"app": "one"},
            ports=[api.ServicePort(port=80, name="web",
                                   target_port="http")])))

    def ep_ready():
        try:
            ep = client.get("endpoints", "one", "default")
        except Exception:
            return None
        for ss in (ep.subsets or []):
            for a in (ss.addresses or []):
                for p in (ss.ports or []):
                    return (a.ip, p.port)
        return None
    addr = wait_for(ep_ready, msg="dialable endpoint")
    assert addr == ("127.0.0.1", 18081), addr

    proxier = UserspaceProxier(client).start()
    try:
        wait_for(lambda: "default/one:web" in proxier.port_map,
                 msg="relay socket")
        port = proxier.port_map["default/one:web"]
        assert wait_for(lambda: _try(fetch, port) == "hello-from-b1",
                        msg="real bytes through the relay")
    finally:
        proxier.stop()


def test_round_robin_and_nodeport_over_real_processes(stack):
    """Two real server processes (distinct host ports) behind ONE selector
    service: per-pod named-port resolution puts each in its own endpoints
    subset, the relay round-robins across both, and the service's actual
    NodePort accepts connections."""
    server, client, rt = stack
    client.create("pods", http_pod("b1", 18083, "hello-from-b1", app="m"))
    client.create("pods", http_pod("b2", 18084, "hello-from-b2", app="m"))
    _bind(client, "b1")
    _bind(client, "b2")
    wait_for(lambda: len(rt.running()) == 2, msg="backends running")
    client.create("services", api.Service(
        metadata=api.ObjectMeta(name="multi", namespace="default"),
        spec=api.ServiceSpec(
            type="NodePort",
            selector={"app": "m"},
            ports=[api.ServicePort(port=80, name="web", target_port="http",
                                   node_port=31888)])))

    def both_endpoints():
        try:
            ep = client.get("endpoints", "multi", "default")
        except Exception:
            return None
        ports = sorted(p.port for ss in (ep.subsets or [])
                       for p in (ss.ports or []))
        return ports == [18083, 18084] or None
    wait_for(both_endpoints, msg="per-pod resolved endpoint subsets")

    proxier = UserspaceProxier(client).start()
    try:
        wait_for(lambda: "default/multi:web" in proxier.port_map,
                 msg="relay socket")
        relay = proxier.port_map["default/multi:web"]
        for port, what in ((relay, "relay"), (31888, "nodePort")):
            seen = set()
            deadline = time.monotonic() + 30
            while len(seen) < 2 and time.monotonic() < deadline:
                out = _try(fetch, port)
                if out:
                    seen.add(out)
                else:
                    time.sleep(0.2)
            assert seen == {"hello-from-b1", "hello-from-b2"}, (what, seen)
    finally:
        proxier.stop()


def _try(fn, *args):
    try:
        return fn(*args)
    except Exception:
        return None
