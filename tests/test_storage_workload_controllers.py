"""PersistentVolume binder, PetSet, ScheduledJob controllers + cron parser
(reference pkg/controller/{persistentvolume,petset,scheduledjob})."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apis import apps, batch
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.controllers.persistentvolume_controller import (
    CLAIM_BOUND, RECLAIM_DELETE, RECLAIM_RECYCLE, VOLUME_AVAILABLE,
    VOLUME_BOUND, VOLUME_RELEASED, PersistentVolumeController,
)
from kubernetes_tpu.controllers.petset_controller import PetSetController
from kubernetes_tpu.controllers.scheduledjob_controller import (
    ScheduledJobController,
)
from kubernetes_tpu.utils import cron


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=2000, burst=2000)


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.03)
    raise AssertionError("condition not met")


def _pv(name, size="10Gi", policy="Retain", modes=("ReadWriteOnce",)):
    return api.PersistentVolume(
        metadata=api.ObjectMeta(name=name),
        spec=api.PersistentVolumeSpec(
            capacity={"storage": size}, access_modes=list(modes),
            persistent_volume_reclaim_policy=policy,
            gce_persistent_disk=api.GCEPersistentDiskVolumeSource(
                pd_name=name)))


def _pvc(name, size="5Gi", modes=("ReadWriteOnce",)):
    return api.PersistentVolumeClaim(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PersistentVolumeClaimSpec(
            access_modes=list(modes),
            resources=api.ResourceRequirements(requests={"storage": size})))


class TestCron:
    def test_every_minute(self):
        s = cron.parse("* * * * *")
        t = s.next_after(0)
        assert t == 60

    def test_specific_time(self):
        s = cron.parse("30 14 * * *")
        # 1970-01-01 00:00 -> first match 14:30 same day
        assert s.next_after(0) == 14 * 3600 + 30 * 60

    def test_step_and_list(self):
        s = cron.parse("*/15 0 * * *")
        assert s.next_after(0) == 15 * 60
        s2 = cron.parse("0,30 * * * *")
        assert s2.next_after(0) == 30 * 60

    def test_bad_spec(self):
        with pytest.raises(cron.CronParseError):
            cron.parse("not a cron")
        with pytest.raises(cron.CronParseError):
            cron.parse("61 * * * *")


class TestPersistentVolumeController:
    def test_bind_smallest_fit_and_recycle(self, client):
        ctrl = PersistentVolumeController(client)
        ctrl.start()
        try:
            client.create("persistentvolumes", _pv("big", "100Gi",
                                                   RECLAIM_RECYCLE))
            client.create("persistentvolumes", _pv("small", "10Gi",
                                                   RECLAIM_RECYCLE))
            _wait(lambda: client.get("persistentvolumes", "small")
                  .status.phase == VOLUME_AVAILABLE)

            client.create("persistentvolumeclaims", _pvc("data", "5Gi"),
                          "default")
            _wait(lambda: client.get("persistentvolumeclaims", "data",
                                     "default").status.phase == CLAIM_BOUND)
            pvc = client.get("persistentvolumeclaims", "data", "default")
            assert pvc.spec.volume_name == "small"  # smallest fit wins
            _wait(lambda: client.get("persistentvolumes", "small")
                  .status.phase == VOLUME_BOUND)

            # deleting the claim recycles the volume back to Available
            client.delete("persistentvolumeclaims", "data", "default")
            _wait(lambda: client.get("persistentvolumes", "small")
                  .status.phase == VOLUME_AVAILABLE)
            assert client.get("persistentvolumes", "small") \
                .spec.claim_ref is None
        finally:
            ctrl.stop()

    def test_retain_goes_released_and_delete_removes(self, client):
        ctrl = PersistentVolumeController(client)
        ctrl.start()
        try:
            client.create("persistentvolumes", _pv("keep", "10Gi", "Retain"))
            client.create("persistentvolumes", _pv("gone", "10Gi",
                                                   RECLAIM_DELETE))
            client.create("persistentvolumeclaims", _pvc("a", "5Gi"),
                          "default")
            _wait(lambda: client.get("persistentvolumeclaims", "a", "default")
                  .status.phase == CLAIM_BOUND)
            bound_to = client.get("persistentvolumeclaims", "a",
                                  "default").spec.volume_name
            client.delete("persistentvolumeclaims", "a", "default")
            if bound_to == "keep":
                _wait(lambda: client.get("persistentvolumes", "keep")
                      .status.phase == VOLUME_RELEASED)
            else:
                _wait(lambda: not any(
                    v.metadata.name == "gone"
                    for v in client.list("persistentvolumes")[0]))
        finally:
            ctrl.stop()

    def test_capacity_too_small_stays_pending(self, client):
        ctrl = PersistentVolumeController(client)
        ctrl.start()
        try:
            client.create("persistentvolumes", _pv("tiny", "1Gi"))
            client.create("persistentvolumeclaims", _pvc("huge", "500Gi"),
                          "default")
            time.sleep(0.5)
            pvc = client.get("persistentvolumeclaims", "huge", "default")
            assert (pvc.status.phase if pvc.status else "") != CLAIM_BOUND
        finally:
            ctrl.stop()


class TestPetSetController:
    def _petset(self, replicas=3):
        return apps.PetSet(
            metadata=api.ObjectMeta(name="db", namespace="default"),
            spec=apps.PetSetSpec(
                replicas=replicas, service_name="db",
                selector=api.LabelSelector(match_labels={"app": "db"}),
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "db"}),
                    spec=api.PodSpec(containers=[api.Container(
                        name="db", image="db:1")])),
                volume_claim_templates=[api.PersistentVolumeClaim(
                    metadata=api.ObjectMeta(name="data"),
                    spec=api.PersistentVolumeClaimSpec(
                        access_modes=["ReadWriteOnce"],
                        resources=api.ResourceRequirements(
                            requests={"storage": "1Gi"})))]))

    def _make_ready(self, client, name):
        p = client.get("pods", name, "default")
        p.status = api.PodStatus(
            phase=api.POD_RUNNING,
            conditions=[api.PodCondition(type=api.POD_READY,
                                         status=api.CONDITION_TRUE)])
        client.update_status("pods", p)

    def test_ordinal_sequential_bringup_with_claims(self, client):
        ctrl = PetSetController(client)
        ctrl.start()
        try:
            client.create("petsets", self._petset(3), "default")
            # only pet 0 at first (sequential)
            _wait(lambda: client.get("pods", "db-0", "default"))
            time.sleep(0.3)
            pods = client.list("pods", "default", label_selector="app=db")[0]
            assert [p.metadata.name for p in pods] == ["db-0"]
            # claim created with the {template}-{pet} name and mounted
            pvc = client.get("persistentvolumeclaims", "data-db-0", "default")
            assert pvc.spec.resources.requests["storage"] == "1Gi"
            p0 = client.get("pods", "db-0", "default")
            assert p0.spec.volumes[0].persistent_volume_claim.claim_name == \
                "data-db-0"

            self._make_ready(client, "db-0")
            _wait(lambda: client.get("pods", "db-1", "default"))
            self._make_ready(client, "db-1")
            _wait(lambda: client.get("pods", "db-2", "default"))
            self._make_ready(client, "db-2")
            _wait(lambda: client.get("petsets", "db", "default")
                  .status.replicas == 3)
        finally:
            ctrl.stop()

    def test_scale_down_highest_ordinal_first(self, client):
        ctrl = PetSetController(client)
        ctrl.start()
        try:
            client.create("petsets", self._petset(2), "default")
            _wait(lambda: client.get("pods", "db-0", "default"))
            self._make_ready(client, "db-0")
            _wait(lambda: client.get("pods", "db-1", "default"))
            self._make_ready(client, "db-1")

            live = client.get("petsets", "db", "default")
            live.spec.replicas = 1
            client.update("petsets", live, "default")
            _wait(lambda: len(client.list("pods", "default",
                                          label_selector="app=db")[0]) == 1)
            assert client.get("pods", "db-0", "default")  # 0 survives
        finally:
            ctrl.stop()


class TestScheduledJobController:
    def test_fires_due_schedule_and_tracks_active(self, client):
        fake_now = [time.time()]
        ctrl = ScheduledJobController(client, sync_seconds=0.2,
                                      clock=lambda: fake_now[0])
        ctrl.start()
        try:
            sj = batch.ScheduledJob(
                metadata=api.ObjectMeta(name="tick", namespace="default"),
                spec=batch.ScheduledJobSpec(
                    schedule="* * * * *",
                    job_template=batch.JobTemplateSpec(
                        metadata=api.ObjectMeta(labels={"sj": "tick"}),
                        spec=batch.JobSpec(
                            parallelism=1, completions=1,
                            selector=api.LabelSelector(
                                match_labels={"sj": "tick"}),
                            template=api.PodTemplateSpec(
                                metadata=api.ObjectMeta(
                                    labels={"sj": "tick"}),
                                spec=api.PodSpec(containers=[api.Container(
                                    name="c", image="task")]))))))
            client.create("scheduledjobs", sj, "default")
            # jump the clock past the next minute boundary
            fake_now[0] = (int(time.time()) // 60 + 2) * 60 + 1
            _wait(lambda: len(client.list("jobs", "default")[0]) == 1)
            job = client.list("jobs", "default")[0][0]
            assert job.metadata.name.startswith("tick-")
            assert job.metadata.owner_references[0].kind == "ScheduledJob"
            st = client.get("scheduledjobs", "tick", "default").status
            assert st.last_schedule_time
            _wait(lambda: (client.get("scheduledjobs", "tick", "default")
                           .status.active or []) != [])
        finally:
            ctrl.stop()

    def test_suspend_blocks_firing(self, client):
        fake_now = [time.time()]
        ctrl = ScheduledJobController(client, sync_seconds=0.2,
                                      clock=lambda: fake_now[0])
        ctrl.start()
        try:
            sj = batch.ScheduledJob(
                metadata=api.ObjectMeta(name="halt", namespace="default"),
                spec=batch.ScheduledJobSpec(
                    schedule="* * * * *", suspend=True,
                    job_template=batch.JobTemplateSpec(
                        spec=batch.JobSpec())))
            client.create("scheduledjobs", sj, "default")
            fake_now[0] = (int(time.time()) // 60 + 2) * 60 + 1
            time.sleep(0.8)
            assert client.list("jobs", "default")[0] == []
        finally:
            ctrl.stop()
