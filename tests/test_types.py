"""Type machinery: codec roundtrips, scheme registry, helpers, validation."""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy, from_dict, scheme, to_dict
from kubernetes_tpu.api.validation import ValidationError, validate


def mk_pod():
    return api.Pod(
        metadata=api.ObjectMeta(name="web-1", namespace="default",
                                labels={"app": "web"}, uid="u1"),
        spec=api.PodSpec(
            containers=[api.Container(
                name="c", image="nginx",
                ports=[api.ContainerPort(container_port=80, host_port=8080)],
                resources=api.ResourceRequirements(
                    requests={"cpu": "100m", "memory": "500Mi"}))],
            node_selector={"disk": "ssd"},
            tolerations=[api.Toleration(key="k", operator="Exists", effect="NoSchedule")],
            affinity=api.Affinity(node_affinity=api.NodeAffinity(
                required_during_scheduling_ignored_during_execution=api.NodeSelector(
                    node_selector_terms=[api.NodeSelectorTerm(match_expressions=[
                        api.NodeSelectorRequirement(key="zone", operator="In",
                                                    values=["us-a", "us-b"])])]))),
        ),
        status=api.PodStatus(phase="Pending"),
    )


def test_pod_roundtrip_wire_names():
    pod = mk_pod()
    d = scheme.encode(pod)
    assert d["kind"] == "Pod" and d["apiVersion"] == "v1"
    assert d["spec"]["nodeSelector"] == {"disk": "ssd"}
    assert d["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "100m"
    assert d["spec"]["containers"][0]["ports"][0]["hostPort"] == 8080
    na = d["spec"]["affinity"]["nodeAffinity"]
    assert na["requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"][0][
        "matchExpressions"][0]["operator"] == "In"
    back = scheme.decode(d)
    assert back == pod


def test_omitempty():
    d = to_dict(api.Pod(metadata=api.ObjectMeta(name="x", namespace="ns")))
    assert "status" not in d
    assert "labels" not in d["metadata"]
    assert d["metadata"] == {"name": "x", "namespace": "ns"}


def test_unknown_fields_ignored():
    pod = from_dict(api.Pod, {"metadata": {"name": "a", "namespace": "b",
                                           "futureField": 42}})
    assert pod.metadata.name == "a"


def test_deep_copy_isolation():
    pod = mk_pod()
    cp = deep_copy(pod)
    assert cp == pod
    cp.metadata.labels["app"] = "changed"
    assert pod.metadata.labels["app"] == "web"


def test_node_roundtrip():
    node = api.Node(
        metadata=api.ObjectMeta(name="n1", labels={api.LABEL_ZONE: "us-a"}),
        spec=api.NodeSpec(unschedulable=True,
                          taints=[api.Taint(key="dedicated", value="ml", effect="NoSchedule")]),
        status=api.NodeStatus(
            capacity={"cpu": "4", "memory": "32Gi", "pods": "110"},
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))
    assert scheme.decode(scheme.encode(node)) == node
    alloc = api.node_allocatable(node)
    assert alloc["cpu"] == 4000
    assert alloc["memory"] == 32 * 2**30
    assert alloc["pods"] == 110


def test_pod_resource_request():
    req = api.pod_resource_request(mk_pod())
    assert req["cpu"] == 100
    assert req["memory"] == 500 * 2**20


def test_toleration_tolerates():
    t_no = api.Taint(key="k", value="v", effect="NoSchedule")
    assert api.Toleration(key="k", operator="Exists").tolerates(t_no)
    assert api.Toleration(key="k", value="v").tolerates(t_no)  # default op Equal
    assert not api.Toleration(key="k", value="other").tolerates(t_no)
    assert not api.Toleration(key="other", operator="Exists").tolerates(t_no)
    assert not api.Toleration(key="k", operator="Exists",
                              effect="PreferNoSchedule").tolerates(t_no)
    assert api.Toleration(key="k", operator="Exists", effect="").tolerates(t_no)
    # empty key + Exists is the tolerate-everything wildcard
    assert api.Toleration(key="", operator="Exists").tolerates(t_no)
    assert api.Toleration(key="", operator="Exists").tolerates(
        api.Taint(key="anything", value="x", effect="NoSchedule"))


def test_scheduler_name_annotation_fallback():
    pod = mk_pod()
    assert api.get_pod_scheduler_name(pod) == api.DEFAULT_SCHEDULER_NAME
    pod.metadata.annotations = {api.ANN_SCHEDULER_NAME: "tpu-scheduler"}
    assert api.get_pod_scheduler_name(pod) == "tpu-scheduler"
    pod.spec.scheduler_name = "explicit"
    assert api.get_pod_scheduler_name(pod) == "explicit"


def test_object_fields():
    pod = mk_pod()
    f = api.object_fields(pod)
    assert f["spec.nodeName"] == "" and f["metadata.name"] == "web-1"
    pod.spec.node_name = "n1"
    assert api.object_fields(pod)["spec.nodeName"] == "n1"


class TestValidation:
    def test_valid_pod(self):
        validate(mk_pod())

    def test_pod_no_containers(self):
        pod = api.Pod(metadata=api.ObjectMeta(name="x", namespace="d"), spec=api.PodSpec())
        with pytest.raises(ValidationError, match="containers"):
            validate(pod)

    def test_bad_name(self):
        pod = mk_pod()
        pod.metadata.name = "Not_A_DNS_Name!"
        with pytest.raises(ValidationError, match="DNS-1123"):
            validate(pod)

    def test_missing_namespace(self):
        pod = mk_pod()
        pod.metadata.namespace = ""
        with pytest.raises(ValidationError, match="namespace"):
            validate(pod)

    def test_node_cluster_scoped(self):
        node = api.Node(metadata=api.ObjectMeta(name="n1", namespace="oops"))
        with pytest.raises(ValidationError, match="cluster-scoped"):
            validate(node)

    def test_bad_quantity(self):
        pod = mk_pod()
        pod.spec.containers[0].resources.requests = {"cpu": "lots"}
        with pytest.raises(ValidationError, match="invalid quantity"):
            validate(pod)

    def test_negative_fractional_quantity(self):
        # ceil(-0.1) == 0 must not mask the negative sign
        pod = mk_pod()
        pod.spec.containers[0].resources.requests = {"cpu": "-100m"}
        with pytest.raises(ValidationError, match="non-negative"):
            validate(pod)

    def test_infinite_quantity(self):
        pod = mk_pod()
        pod.spec.containers[0].resources.requests = {"cpu": "inf"}
        with pytest.raises(ValidationError, match="invalid quantity"):
            validate(pod)

    def test_uppercase_name_rejected(self):
        pod = mk_pod()
        pod.metadata.name = "WEB-1"
        with pytest.raises(ValidationError, match="DNS-1123"):
            validate(pod)

    def test_generate_name_trailing_dash(self):
        pod = mk_pod()
        pod.metadata.name = ""
        pod.metadata.generate_name = "web-"
        validate(pod)  # prefix form must be accepted

    def test_binding(self):
        b = api.Binding(metadata=api.ObjectMeta(name="p", namespace="d"),
                        target=api.ObjectReference(kind="Node", name="n1"))
        validate(b)
        with pytest.raises(ValidationError, match="target.name"):
            validate(api.Binding(target=api.ObjectReference(kind="Node")))

    def test_rc_selector_template_mismatch(self):
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name="rc", namespace="d"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"app": "x"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "y"}))))
        with pytest.raises(ValidationError, match="satisfy selector"):
            validate(rc)
