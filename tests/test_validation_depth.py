"""Table-driven negative validation tests.

Parity target: reference pkg/api/validation/validation.go (name formats,
label/annotation rules, port ranges and names, probe invariants, pod-update
immutability, service port/type rules) — round-4 verdict #9: the apiserver
must reject what the reference rejects.
"""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.validation import (
    ValidationError, validate_node, validate_pod, validate_pod_update,
    validate_service,
)
from kubernetes_tpu.api.serialization import deep_copy


def base_pod(**spec_kw):
    return api.Pod(
        metadata=api.ObjectMeta(name="p", namespace="default"),
        spec=api.PodSpec(containers=[api.Container(name="c", image="i")],
                         **spec_kw))


def port(**kw):
    d = dict(container_port=80)
    d.update(kw)
    return api.ContainerPort(**d)


# (description, mutate(pod), expected error fragment)
BAD_PODS = [
    ("uppercase name",
     lambda p: setattr(p.metadata, "name", "Upper"), "DNS-1123"),
    ("name too long",
     lambda p: setattr(p.metadata, "name", "a" * 254), "DNS-1123"),
    ("name with underscore",
     lambda p: setattr(p.metadata, "name", "a_b"), "DNS-1123"),
    ("namespace not a label",
     lambda p: setattr(p.metadata, "namespace", "a.b"), "DNS-1123 label"),
    ("label key bad prefix",
     lambda p: setattr(p.metadata, "labels", {"-bad-/x": "1"}),
     "invalid key"),
    ("label key empty name part",
     lambda p: setattr(p.metadata, "labels", {"example.com/": "1"}),
     "invalid key"),
    ("label value too long",
     lambda p: setattr(p.metadata, "labels", {"k": "v" * 64}),
     "invalid value"),
    ("label value bad chars",
     lambda p: setattr(p.metadata, "labels", {"k": "no spaces"}),
     "invalid value"),
    ("annotation key invalid",
     lambda p: setattr(p.metadata, "annotations", {"bad key": "v"}),
     "invalid key"),
    ("annotations too large",
     lambda p: setattr(p.metadata, "annotations", {"k": "v" * (257 * 1024)}),
     "256KB"),
    ("bad restartPolicy",
     lambda p: setattr(p.spec, "restart_policy", "Sometimes"),
     "restartPolicy"),
    ("negative grace period",
     lambda p: setattr(p.spec, "termination_grace_period_seconds", -1),
     "terminationGracePeriodSeconds"),
    ("zero active deadline",
     lambda p: setattr(p.spec, "active_deadline_seconds", 0),
     "activeDeadlineSeconds"),
    ("bad nodeSelector key",
     lambda p: setattr(p.spec, "node_selector", {"bad key": "v"}),
     "nodeSelector"),
    ("container name uppercase",
     lambda p: setattr(p.spec.containers[0], "name", "Main"), "DNS-1123"),
    ("duplicate container names",
     lambda p: setattr(p.spec, "containers",
                       [api.Container(name="c", image="i"),
                        api.Container(name="c", image="j")]), "duplicate"),
    ("missing image",
     lambda p: setattr(p.spec.containers[0], "image", ""), "image"),
    ("bad imagePullPolicy",
     lambda p: setattr(p.spec.containers[0], "image_pull_policy", "Maybe"),
     "imagePullPolicy"),
    ("negative cpu request",
     lambda p: setattr(p.spec.containers[0], "resources",
                       api.ResourceRequirements(requests={"cpu": "-100m"})),
     "non-negative"),
    ("garbage memory quantity",
     lambda p: setattr(p.spec.containers[0], "resources",
                       api.ResourceRequirements(requests={"memory": "1Zi?"})),
     "invalid quantity"),
    ("request exceeds limit",
     lambda p: setattr(p.spec.containers[0], "resources",
                       api.ResourceRequirements(requests={"cpu": "2"},
                                                limits={"cpu": "1"})),
     "exceeds limit"),
    ("containerPort zero",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(container_port=0)]), "out of range"),
    ("containerPort too big",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(container_port=70000)]), "out of range"),
    ("hostPort too big",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(host_port=70000)]), "out of range"),
    ("port name too long",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(name="averyveryloooongname")]), "port name"),
    ("port name all digits",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(name="1234")]), "port name"),
    ("port name double dash",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(name="a--b")]), "port name"),
    ("bad protocol",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(protocol="SCTP")]), "protocol"),
    ("duplicate hostPort",
     lambda p: setattr(p.spec.containers[0], "ports",
                       [port(host_port=8080), port(container_port=81,
                                                   host_port=8080)]),
     "duplicate"),
    ("env name not C identifier",
     lambda p: setattr(p.spec.containers[0], "env",
                       [api.EnvVar(name="1BAD", value="x")]),
     "C identifier"),
    ("volume missing name",
     lambda p: setattr(p.spec, "volumes", [api.Volume(name="")]),
     "name: required"),
    ("duplicate volume names",
     lambda p: setattr(p.spec, "volumes",
                       [api.Volume(name="v",
                                   empty_dir=api.EmptyDirVolumeSource()),
                        api.Volume(name="v",
                                   empty_dir=api.EmptyDirVolumeSource())]),
     "duplicate"),
    ("toleration bad operator",
     lambda p: setattr(p.spec, "tolerations",
                       [api.Toleration(key="k", operator="Like")]),
     "operator"),
    ("toleration Exists with value",
     lambda p: setattr(p.spec, "tolerations",
                       [api.Toleration(key="k", operator="Exists",
                                       value="v")]),
     "must be empty"),
    ("probe without handler",
     lambda p: setattr(p.spec.containers[0], "liveness_probe", api.Probe()),
     "exactly one handler"),
    ("probe with two handlers",
     lambda p: setattr(p.spec.containers[0], "liveness_probe",
                       api.Probe(exec=api.ExecAction(command=["x"]),
                                 tcp_socket=api.TCPSocketAction(port=1))),
     "exactly one handler"),
    ("probe negative threshold",
     lambda p: setattr(p.spec.containers[0], "readiness_probe",
                       api.Probe(tcp_socket=api.TCPSocketAction(port=1),
                                 failure_threshold=-1)),
     "non-negative"),
]


@pytest.mark.parametrize("desc,mutate,fragment",
                         BAD_PODS, ids=[b[0] for b in BAD_PODS])
def test_pod_rejected(desc, mutate, fragment):
    pod = base_pod()
    mutate(pod)
    with pytest.raises(ValidationError) as ei:
        validate_pod(pod)
    assert fragment in str(ei.value), f"{desc}: {ei.value}"


def test_good_pod_passes():
    pod = base_pod(
        restart_policy="OnFailure",
        node_selector={"kubernetes.io/hostname": "n1"},
        volumes=[api.Volume(name="data",
                            empty_dir=api.EmptyDirVolumeSource())],
        tolerations=[api.Toleration(key="k", operator="Exists")])
    pod.metadata.labels = {"app": "web", "example.com/tier": "frontend"}
    pod.metadata.annotations = {"kubectl.kubernetes.io/last-applied": "{}"}
    pod.spec.containers[0].ports = [port(name="http", host_port=8080)]
    pod.spec.containers[0].env = [api.EnvVar(name="MODE", value="fast")]
    pod.spec.containers[0].liveness_probe = api.Probe(
        tcp_socket=api.TCPSocketAction(port=80))
    validate_pod(pod)  # no raise


BAD_SERVICES = [
    ("port zero", lambda s: setattr(s.spec.ports[0], "port", 0),
     "out of range"),
    ("bad protocol", lambda s: setattr(s.spec.ports[0], "protocol", "ICMP"),
     "protocol"),
    ("nodePort out of range",
     lambda s: setattr(s.spec.ports[0], "node_port", 40000), "30000-32767"),
    ("multi-port unnamed",
     lambda s: setattr(s.spec, "ports",
                       [api.ServicePort(port=80),
                        api.ServicePort(port=81)]), "name: required"),
    ("duplicate port names",
     lambda s: setattr(s.spec, "ports",
                       [api.ServicePort(port=80, name="web"),
                        api.ServicePort(port=81, name="web")]), "duplicate"),
    ("bad sessionAffinity",
     lambda s: setattr(s.spec, "session_affinity", "Sticky"),
     "sessionAffinity"),
    ("bad type", lambda s: setattr(s.spec, "type", "External"), "type"),
    ("bad selector value",
     lambda s: setattr(s.spec, "selector", {"app": "has space"}),
     "invalid value"),
]


@pytest.mark.parametrize("desc,mutate,fragment",
                         BAD_SERVICES, ids=[b[0] for b in BAD_SERVICES])
def test_service_rejected(desc, mutate, fragment):
    svc = api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                      spec=api.ServiceSpec(
                          ports=[api.ServicePort(port=80)]))
    mutate(svc)
    with pytest.raises(ValidationError) as ei:
        validate_service(svc)
    assert fragment in str(ei.value), f"{desc}: {ei.value}"


class TestPodUpdateImmutability:
    def test_image_change_allowed(self):
        old = base_pod()
        new = deep_copy(old)
        new.spec.containers[0].image = "i:v2"
        validate_pod_update(new, old)  # no raise

    def test_command_change_rejected(self):
        old = base_pod()
        new = deep_copy(old)
        new.spec.containers[0].command = ["new"]
        with pytest.raises(ValidationError):
            validate_pod_update(new, old)

    def test_resource_change_rejected(self):
        old = base_pod()
        new = deep_copy(old)
        new.spec.containers[0].resources = api.ResourceRequirements(
            requests={"cpu": "2"})
        with pytest.raises(ValidationError):
            validate_pod_update(new, old)

    def test_container_addition_rejected(self):
        old = base_pod()
        new = deep_copy(old)
        new.spec.containers.append(api.Container(name="d", image="j"))
        with pytest.raises(ValidationError):
            validate_pod_update(new, old)

    def test_restart_policy_change_rejected(self):
        old = base_pod()
        new = deep_copy(old)
        new.spec.restart_policy = "Never"
        with pytest.raises(ValidationError):
            validate_pod_update(new, old)

    def test_served_through_apiserver(self):
        """The registry enforces immutability on PUT; labels stay mutable."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.client.rest import ApiError
        server = APIServer().start()
        try:
            client = RESTClient.for_server(server)
            created = client.create("pods", base_pod())
            mutated = deep_copy(created)
            mutated.spec.restart_policy = "Never"
            with pytest.raises(ApiError) as ei:
                client.update("pods", mutated)
            assert ei.value.code == 422
            relabel = deep_copy(created)
            relabel.metadata.labels = {"new": "label"}
            assert client.update("pods", relabel).metadata.labels == {
                "new": "label"}
            reimage = client.get("pods", "p", "default")
            reimage.spec.containers[0].image = "i:v2"
            assert client.update(
                "pods", reimage).spec.containers[0].image == "i:v2"
        finally:
            server.stop()


def test_node_capacity_validated():
    node = api.Node(metadata=api.ObjectMeta(name="n"),
                    status=api.NodeStatus(capacity={"cpu": "-4"}))
    with pytest.raises(ValidationError):
        validate_node(node)


class TestHostileInputs:
    """Review-findings regressions: crashy/evasive inputs must 422, not 500."""

    def test_non_string_label_value_rejected_not_crash(self):
        pod = base_pod()
        pod.metadata.labels = {"version": 2}
        with pytest.raises(ValidationError):
            validate_pod(pod)

    def test_non_string_annotation_value_rejected(self):
        pod = base_pod()
        pod.metadata.annotations = {"k": ["not", "a", "string"]}
        with pytest.raises(ValidationError):
            validate_pod(pod)

    def test_trailing_newline_rejected(self):
        for mutate in (
                lambda p: setattr(p.metadata, "labels", {"k": "v\n"}),
                lambda p: setattr(p.spec.containers[0], "env",
                                  [api.EnvVar(name="FOO\n", value="x")]),
                lambda p: setattr(p.spec.containers[0], "ports",
                                  [port(name="http\n")])):
            pod = base_pod()
            mutate(pod)
            with pytest.raises(ValidationError):
                validate_pod(pod)

    def test_annotation_limit_counts_bytes(self):
        pod = base_pod()
        # 100k euro signs = 300KB utf-8 but only 100k characters
        pod.metadata.annotations = {"k": "€" * (100 * 1024)}
        with pytest.raises(ValidationError) as ei:
            validate_pod(pod)
        assert "256KB" in str(ei.value)

    def test_bad_node_selector_value(self):
        pod = base_pod(node_selector={"zone": "us east!"})
        with pytest.raises(ValidationError):
            validate_pod(pod)

    def test_newline_in_name_and_keys_rejected(self):
        for mutate in (
                lambda p: setattr(p.metadata, "name", "p\n"),
                lambda p: setattr(p.metadata, "labels", {"k\n": "v"}),
                lambda p: setattr(p.metadata, "annotations", {"k\n": "v"}),
                lambda p: setattr(p.spec, "node_selector", {"k\n": "v"})):
            pod = base_pod()
            mutate(pod)
            with pytest.raises(ValidationError):
                validate_pod(pod)

    def test_non_numeric_fields_422_not_500(self):
        for mutate in (
                lambda p: setattr(p.spec, "termination_grace_period_seconds",
                                  "abc"),
                lambda p: setattr(p.spec, "active_deadline_seconds", "zzz"),
                lambda p: setattr(p.spec.containers[0], "ports",
                                  [port(container_port="80")]),
                lambda p: setattr(p.spec.containers[0], "liveness_probe",
                                  api.Probe(
                                      tcp_socket=api.TCPSocketAction(port=1),
                                      failure_threshold="3")),
                lambda p: setattr(p.spec.containers[0], "env",
                                  [api.EnvVar(name=123, value="x")])):
            pod = base_pod()
            mutate(pod)
            with pytest.raises(ValidationError):
                validate_pod(pod)
