"""Scheduler decision ledger: per-predicate explainability (ISSUE 12).

The correctness anchor is oracle equivalence: on randomized fixtures the
kernel's per-predicate surviving-node counts and winner/runner-up score
decompositions must match a node-by-node replay of the Python
scheduler/predicates.py + priorities.py (observability/explain.py
oracle_breakdown) EXACTLY — and explain=off must stay bit-identical to the
plain solve.  Plus the delivery surfaces: reason-string formatting,
/explainz over live HTTP, ledger pruning, flight-recorder decisions,
signature-based event dedup, and the single requeue delay-worker.
"""

import json
import random
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.observability.explain import (
    LEDGER, PREDICATES, DecisionLedger, DecisionRecord, KernelFitError,
    format_assigned, format_reason, note_unschedulable, oracle_breakdown,
    reason_signature, render_explainz,
)
from kubernetes_tpu.scheduler.batch import (
    ListPodLister, ListServiceLister, make_plugin_args, tpu_batch,
)


def mk_node(name, cpu="4", mem="32Gi", pods="110", labels=None, taints=None,
            conditions=None):
    labels = dict(labels or {})
    labels.setdefault(api.LABEL_HOSTNAME, name)
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=conditions or [api.NodeCondition(type="Ready",
                                                        status="True")]))


def mk_pod(name, ns="default", cpu=None, mem=None, labels=None, node="",
           selector=None, affinity=None, tolerations=None, host_ports=()):
    requests = {}
    if cpu:
        requests["cpu"] = cpu
    if mem:
        requests["memory"] = mem
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(
            node_name=node, node_selector=selector, affinity=affinity,
            tolerations=tolerations,
            containers=[api.Container(
                name="c", image="pause",
                ports=[api.ContainerPort(host_port=p, container_port=p)
                       for p in host_ports],
                resources=api.ResourceRequirements(requests=requests)
                if requests else None)]))


def _assert_records_equal(kr, orr):
    assert kr.pod == orr.pod
    assert kr.nodes_total == orr.nodes_total
    assert kr.survivors == orr.survivors, (
        f"{kr.pod}: survivors {kr.survivors} != oracle {orr.survivors}\n"
        f"kernel elim {dict(kr.eliminations())} "
        f"oracle elim {dict(orr.eliminations())}")
    assert kr.node == orr.node
    if kr.node is None:
        return
    assert kr.score == pytest.approx(orr.score, abs=1e-4), kr.pod
    assert set(kr.components) == set(orr.components), kr.pod
    for name in orr.components:
        assert kr.components[name] == pytest.approx(
            orr.components[name], abs=1e-4), (kr.pod, name)
    assert kr.runner_up == orr.runner_up, kr.pod
    if kr.runner_up is not None:
        assert kr.runner_up_score == pytest.approx(
            orr.runner_up_score, abs=1e-4), kr.pod
        for name in orr.runner_up_components:
            assert kr.runner_up_components[name] == pytest.approx(
                orr.runner_up_components[name], abs=1e-4), (kr.pod, name)


class TestKernelOracleParity:
    """The acceptance anchor: kernel explain output == Python replay."""

    def _random_cluster(self, seed):
        rng = random.Random(seed)
        zones = ["us-a", "us-b", "us-c"]
        nodes = []
        for i in range(20):
            labels = {api.LABEL_HOSTNAME: f"n{i:02d}",
                      api.LABEL_ZONE: rng.choice(zones)}
            if rng.random() < 0.3:
                labels["disk"] = "ssd"
            taints = None
            r = rng.random()
            if r < 0.15:
                taints = [api.Taint(key="ded", value="ml",
                                    effect="NoSchedule")]
            elif r < 0.3:
                taints = [api.Taint(key="soft", value="x",
                                    effect="PreferNoSchedule")]
            nodes.append(mk_node(
                f"n{i:02d}", cpu=rng.choice(["2", "4"]),
                mem=rng.choice(["8Gi", "16Gi"]),
                pods=str(rng.choice([4, 110])), labels=labels, taints=taints))
        existing = []
        for i in range(12):
            existing.append(mk_pod(
                f"e{i:02d}", cpu="500m", mem="1Gi",
                labels={"app": rng.choice(["web", "db"])},
                node=rng.choice(nodes).metadata.name))
        svc = api.Service(
            metadata=api.ObjectMeta(name="web", namespace="default"),
            spec=api.ServiceSpec(selector={"app": "web"},
                                 ports=[api.ServicePort(port=80)]))
        pending = []
        for i in range(40):
            kw = {"cpu": f"{rng.choice([100, 500, 1500])}m", "mem": "256Mi",
                  "labels": {"app": rng.choice(["web", "db"])}}
            r = rng.random()
            if r < 0.2:
                kw["selector"] = {"disk": "ssd"}
            elif r < 0.3:
                kw["tolerations"] = [api.Toleration(key="ded",
                                                    operator="Exists")]
            elif r < 0.4:
                kw["host_ports"] = (9000 + (i % 3),)
            elif r < 0.5:
                kw["affinity"] = api.Affinity(
                    node_affinity=api.NodeAffinity(
                        preferred_during_scheduling_ignored_during_execution=[
                            api.PreferredSchedulingTerm(
                                weight=10,
                                preference=api.NodeSelectorTerm(
                                    match_expressions=[
                                        api.NodeSelectorRequirement(
                                            key="disk", operator="In",
                                            values=["ssd"])]))]))
            elif r < 0.6:
                kw["affinity"] = api.Affinity(
                    pod_anti_affinity=api.PodAntiAffinity(
                        required_during_scheduling_ignored_during_execution=[
                            api.PodAffinityTerm(
                                label_selector=api.LabelSelector(
                                    match_labels={"uniq": f"u{i}"}),
                                topology_key=api.LABEL_ZONE)]))
                kw["labels"]["uniq"] = f"u{i}"
            pending.append(mk_pod(f"p{i:02d}", **kw))
        # seeded hopeless pods: every breakdown bucket is exercised somewhere
        pending.append(mk_pod("huge", cpu="64"))
        pending.append(mk_pod("nosel", selector={"disk": "nvme"}))

        def args():
            return make_plugin_args(
                nodes, pod_lister=ListPodLister(list(existing)),
                service_lister=ListServiceLister([svc]))
        return nodes, existing, pending, args

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_parity(self, seed):
        nodes, existing, pending, args = self._random_cluster(seed)
        names, recs = tpu_batch(nodes, existing, pending, args(),
                                explain=True)
        orecs = oracle_breakdown(nodes, existing, pending, args(), names)
        assert len(recs) == len(orecs) == len(pending)
        assert any(r.node is None for r in recs)
        assert any(r.node is not None for r in recs)
        for kr, orr in zip(recs, orecs):
            _assert_records_equal(kr, orr)

    def test_explain_off_bit_identical(self):
        nodes, existing, pending, args = self._random_cluster(3)
        plain = tpu_batch(nodes, existing, pending, args())
        names, recs = tpu_batch(nodes, existing, pending, args(),
                                explain=True)
        assert names == plain
        # and the records name the same assignments
        assert [r.node for r in recs] == plain

    def test_seeded_unschedulable_exact_counts(self):
        """One pod, four nodes, four distinct elimination reasons."""
        nodes = [
            mk_node("n0"),                                     # no ssd label
            mk_node("n1", labels={"disk": "ssd"},
                    taints=[api.Taint(key="ded", value="x",
                                      effect="NoSchedule")]),  # untolerated
            mk_node("n2", cpu="1", labels={"disk": "ssd"}),    # cpu-full
            mk_node("n3", labels={"disk": "ssd"}),             # port clash
        ]
        existing = [mk_pod("hog", cpu="900m", node="n2"),
                    mk_pod("porter", node="n3", host_ports=(9000,))]
        pending = [mk_pod("p", cpu="200m", selector={"disk": "ssd"},
                          host_ports=(9000,))]
        args = make_plugin_args(nodes,
                                pod_lister=ListPodLister(list(existing)))
        names, recs = tpu_batch(nodes, existing, pending, args, explain=True)
        assert names == [None]
        rec = recs[0]
        assert dict(rec.eliminations()) == {
            "MatchNodeSelector": 1, "PodToleratesNodeTaints": 1,
            "InsufficientCPU": 1, "PodFitsHostPorts": 1}
        assert format_reason(rec) == (
            "0/4 nodes are available: 1 Insufficient cpu, "
            "1 MatchNodeSelector, 1 PodFitsHostPorts, "
            "1 PodToleratesNodeTaints.")
        assert reason_signature(rec) == (
            "InsufficientCPU", "MatchNodeSelector", "PodFitsHostPorts",
            "PodToleratesNodeTaints")

    def test_reasons_counter_from_kernel_and_fiterror(self):
        from kubernetes_tpu.scheduler.generic import FitError
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
        rec = DecisionRecord(
            pod="default/p", node=None, nodes_total=5,
            survivors=(3, 3, 3, 3, 3, 3, 0, 0, 0, 0, 0, 0, 0))
        pod = mk_pod("p")
        before = dict(METRICS.counter_series(
            "scheduler_unschedulable_reasons_total"))
        note_unschedulable(KernelFitError(pod, rec))
        note_unschedulable(FitError(pod, {
            "n1": "PodFitsResources: Insufficient cpu",
            "n2": "PodFitsResources: Insufficient cpu",
            "n3": "free text with no predicate key",
            "n4": "another node-specific reason n4"}))
        after = METRICS.counter_series("scheduler_unschedulable_reasons_total")

        def delta(pred):
            k = (("predicate", pred),)
            return after.get(k, 0.0) - before.get(k, 0.0)
        assert delta("MatchNodeSelector") == 2.0
        assert delta("InsufficientCPU") == 3.0
        assert delta("PodFitsResources") == 2.0
        # free-text reasons bucket into ONE label, never per-node series
        assert delta("Other") == 2.0


class TestReasonFormatting:
    def test_reference_style_breakdown(self):
        # "0/5000 nodes are available: 3200 Insufficient cpu,
        #  1800 MatchNodeSelector." — counts descending
        surv = [3200] * 6 + [0] * 7
        rec = DecisionRecord(pod="default/p", node=None, nodes_total=5000,
                             survivors=tuple(surv))
        assert format_reason(rec) == (
            "0/5000 nodes are available: 3200 Insufficient cpu, "
            "1800 MatchNodeSelector.")

    def test_all_rows_named(self):
        # one elimination per canonical row formats without KeyErrors
        n = len(PREDICATES)
        surv = tuple(n - i - 1 for i in range(n))
        rec = DecisionRecord(pod="d/p", node=None, nodes_total=n,
                             survivors=surv)
        msg = format_reason(rec)
        assert msg.startswith(f"0/{n} nodes are available: ")
        assert msg.count("1 ") == n

    def test_assigned_summary(self):
        rec = DecisionRecord(
            pod="default/p", node="n1", nodes_total=5,
            survivors=(5,) * 13, score=37.0,
            components={"least_requested": 7.0, "spread": 10.0},
            runner_up="n2", runner_up_score=36.0,
            runner_up_components={"least_requested": 6.0, "spread": 10.0})
        assert format_assigned(rec) == (
            "score 37 (least_requested=7 spread=10); "
            "runner-up n2 score 36")
        d = rec.to_dict()
        assert d["summary"] == format_assigned(rec)
        assert d["runner_up"] == "n2"

    def test_no_survivor_rows(self):
        rec = DecisionRecord(pod="d/p", node=None, nodes_total=0,
                             survivors=(0,) * 13)
        assert "no schedulable nodes" in format_reason(rec)


class TestDecisionLedger:
    def test_pruning_and_index(self):
        led = DecisionLedger(capacity=8)
        for i in range(20):
            led.add(DecisionRecord(pod=f"d/p{i}", node="n", nodes_total=1,
                                   survivors=(1,) * 13))
        assert len(led) == 8
        assert led.get("d/p0") is None          # evicted, index pruned
        assert led.get("d/p19") is not None
        tail = led.tail(4)
        assert [r.pod for r in tail] == ["d/p16", "d/p17", "d/p18", "d/p19"]
        assert led.tail(0) == []                # -0 slice must not mean "all"
        assert led.tail(-3) == []

    def test_latest_decision_wins(self):
        led = DecisionLedger(capacity=8)
        led.add(DecisionRecord(pod="d/p", node=None, nodes_total=1,
                               survivors=(0,) * 13))
        led.add(DecisionRecord(pod="d/p", node="n1", nodes_total=1,
                               survivors=(1,) * 13))
        assert led.get("d/p").node == "n1"

    def test_render_explainz(self):
        led = DecisionLedger(capacity=8)
        led.add(DecisionRecord(pod="d/p", node=None, nodes_total=3,
                               survivors=(0,) * 13))
        out = render_explainz(led)
        assert out["size"] == 1 and len(out["decisions"]) == 1
        assert out["decisions"][0]["reason"].startswith("0/3 nodes")
        one = render_explainz(led, pod="d/p")
        assert one["decision"]["pod"] == "d/p"
        assert render_explainz(led, pod="d/unknown")["decision"] is None
        assert render_explainz(led, n="bogus")["size"] == 1  # tolerant n=


class TestExplainzHTTP:
    def test_live_endpoint(self):
        from kubernetes_tpu.utils.debugserver import DebugServer
        LEDGER.clear()
        LEDGER.add(DecisionRecord(pod="default/web-1", node="n7",
                                  nodes_total=9, survivors=(9,) * 13,
                                  score=30.0, components={"spread": 10.0}))
        LEDGER.add(DecisionRecord(pod="default/web-2", node=None,
                                  nodes_total=9, survivors=(0,) * 13))
        srv = DebugServer(port=0).start()
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}", timeout=5) as r:
                    return json.loads(r.read())
            out = get("/explainz")
            assert out["size"] == 2
            assert [d["pod"] for d in out["decisions"]] == [
                "default/web-1", "default/web-2"]
            one = get("/explainz?pod=default/web-1")
            assert one["decision"]["node"] == "n7"
            assert get("/explainz?n=1")["decisions"][0]["pod"] == \
                "default/web-2"
        finally:
            srv.stop()
            LEDGER.clear()


class TestFlightRecorderDecisions:
    def test_bundle_carries_ledger_tail(self, tmp_path):
        from kubernetes_tpu.observability.flightrecorder import FlightRecorder
        LEDGER.clear()
        LEDGER.add(DecisionRecord(pod="default/stuck", node=None,
                                  nodes_total=4, survivors=(0,) * 13))
        rec = FlightRecorder(directory=str(tmp_path))
        path = rec.dump("test-wedge", trigger={"why": "test"})
        try:
            with open(path, encoding="utf-8") as f:
                bundle = json.load(f)
            assert isinstance(bundle["decisions"], list)
            assert bundle["decisions"][-1]["pod"] == "default/stuck"
            assert bundle["decisions"][-1]["reason"].startswith("0/4 nodes")
        finally:
            LEDGER.clear()


class TestEventSignature:
    def test_signature_joins_dedup_identity(self):
        from kubernetes_tpu.utils.events import EventCorrelator
        c = EventCorrelator(clock=lambda: 0.0)
        src = ("scheduler", "", "Pod", "default", "p", "")
        sim = ("Pod", "default", "p", "Warning", "FailedScheduling")
        k1 = c.correlate(src, sim, "0/5: 3 X, 2 Y", signature=("X", "Y"))
        k2 = c.correlate(src, sim, "0/5: 2 X, 3 Y", signature=("X", "Y"))
        # drifting counts, same histogram shape: ONE dedup identity (count
        # bump), with the newer message carried for the update
        assert k1[0] == k2[0]
        assert k2[1] == "0/5: 2 X, 3 Y"
        k3 = c.correlate(src, sim, "0/5: 5 Z", signature=("Z",))
        assert k3[0] != k1[0]

    def test_signature_storms_still_aggregate(self):
        from kubernetes_tpu.utils.events import (
            AGGREGATED_PREFIX, EventCorrelator,
        )
        c = EventCorrelator(clock=lambda: 0.0, max_similar=3)
        src = ("scheduler", "", "Pod", "default", "p", "")
        sim = ("Pod", "default", "p", "Warning", "FailedScheduling")
        last = None
        for i in range(6):
            last = c.correlate(src, sim, f"msg {i}", signature=(f"sig{i}",))
        assert last is not None and last[2] is True
        assert last[1].startswith(AGGREGATED_PREFIX)

    def test_plain_messages_unchanged(self):
        from kubernetes_tpu.utils.events import EventCorrelator
        c = EventCorrelator(clock=lambda: 0.0)
        src = ("kubelet", "", "Pod", "default", "p", "")
        sim = ("Pod", "default", "p", "Normal", "Pulled")
        k1 = c.correlate(src, sim, "pulled image")
        k2 = c.correlate(src, sim, "pulled image")
        k3 = c.correlate(src, sim, "pulled other")
        assert k1[0] == k2[0] and k3[0] != k1[0]


class TestRequeueWorker:
    def test_one_thread_drains_many(self):
        from kubernetes_tpu.scheduler.factory import _RequeueWorker
        fired = []
        stop = threading.Event()
        w = _RequeueWorker(fired.append, stop)
        try:
            for i in range(300):
                w.add(0.01, i)
            deadline = time.monotonic() + 10
            while len(fired) < 300 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(fired) == 300
            workers = [t for t in threading.enumerate()
                       if t.name == "scheduler-requeue"]
            assert len(workers) == 1, (
                f"{len(workers)} requeue threads for 300 requeues")
        finally:
            stop.set()
            w.wake()

    def test_due_order(self):
        from kubernetes_tpu.scheduler.factory import _RequeueWorker
        fired = []
        stop = threading.Event()
        w = _RequeueWorker(fired.append, stop)
        try:
            w.add(0.30, "late")
            w.add(0.05, "early")
            deadline = time.monotonic() + 5
            while len(fired) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert fired == ["early", "late"]
        finally:
            stop.set()
            w.wake()

    def test_stop_ends_worker(self):
        from kubernetes_tpu.scheduler.factory import _RequeueWorker
        stop = threading.Event()
        w = _RequeueWorker(lambda pod: None, stop)
        w.add(30.0, "never")
        stop.set()
        w.wake()
        w._thread.join(timeout=5)
        assert not w._thread.is_alive()


class TestLiveExplainPipeline:
    """The four-surface acceptance: event, condition, /explainz, describe."""

    @pytest.fixture()
    def server(self):
        from kubernetes_tpu.apiserver import APIServer
        s = APIServer().start()
        yield s
        s.stop()

    @pytest.fixture()
    def client(self, server):
        from kubernetes_tpu.client import RESTClient
        return RESTClient.for_server(server, qps=5000, burst=5000)

    def test_all_surfaces_agree(self, client):
        from kubernetes_tpu.kubectl.cmd import (
            _describe_lines, _object_events, _scheduling_lines,
        )

        def scheduling_lines(pod_obj):
            return _scheduling_lines(
                "pods", pod_obj, _object_events(client, "pods", pod_obj))
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        LEDGER.clear()
        for i in range(3):
            client.create("nodes", mk_node(f"n{i}", labels={"disk": "ssd"}))
        for i in range(3):
            client.create("pods", mk_pod(f"fits-{i}", cpu="100m"))
        client.create("pods", mk_pod("nofit", selector={"disk": "nvme"}))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=16).run()
        try:
            deadline = time.monotonic() + 60
            cond, bound = None, []
            while time.monotonic() < deadline:
                pods, _ = client.list("pods", "default")
                bound = [p for p in pods if p.spec and p.spec.node_name]
                nofit = next(p for p in pods
                             if p.metadata.name == "nofit")
                cond = next(
                    (c for c in ((nofit.status.conditions or [])
                                 if nofit.status else [])
                     if c.type == api.POD_SCHEDULED
                     and c.status == api.CONDITION_FALSE), None)
                if len(bound) >= 3 and cond is not None:
                    break
                time.sleep(0.05)
            assert len(bound) >= 3 and cond is not None
            assert sched.kernel_failures == 0

            # surface 1: the Unschedulable condition is the breakdown
            want = cond.message
            assert want == ("0/3 nodes are available: "
                            "3 MatchNodeSelector.")

            # surface 2: FailedScheduling event carries the same text (the
            # recorder posts async — poll, don't sample)
            sched.recorder.flush()
            deadline = time.monotonic() + 15
            failed = []
            while time.monotonic() < deadline:
                evs, _ = client.list(
                    "events", "default",
                    field_selector="involvedObject.kind=Pod,"
                                   "involvedObject.name=nofit")
                failed = [e for e in evs
                          if e.reason == "FailedScheduling"]
                if any(e.message == want for e in failed):
                    break
                time.sleep(0.05)
            assert failed and any(e.message == want for e in failed)

            # surface 3: the ledger (what /explainz serves)
            rec = LEDGER.get("default/nofit")
            assert rec is not None and format_reason(rec) == want
            for p in bound:
                lrec = LEDGER.get(f"default/{p.metadata.name}")
                assert lrec is not None and lrec.node == p.spec.node_name
                assert lrec.score is not None and lrec.components

            # surface 4: kubectl describe's Scheduling section
            nofit = client.get("pods", "nofit", "default")
            lines = scheduling_lines(nofit)
            assert lines[0] == "Scheduling:"
            assert lines[1] == f"  Unschedulable:\t{want}"
            # a bound pod renders decision + runner-up (from the Scheduled
            # event the scheduler stamped)
            sched.recorder.flush()
            deadline = time.monotonic() + 10
            dlines = []
            while time.monotonic() < deadline:
                p0 = client.get("pods", bound[0].metadata.name, "default")
                dlines = scheduling_lines(p0)
                if dlines:
                    break
                time.sleep(0.05)
            assert dlines and dlines[0] == "Scheduling:"
            assert any(line.startswith("  Decision:\tscore ")
                       for line in dlines)
            assert _describe_lines("pods", p0)  # smoke: still renders

            # requeue machinery: ONE delay-worker thread, not one per pod
            requeue_threads = [t for t in threading.enumerate()
                               if t.name == "scheduler-requeue"]
            assert len(requeue_threads) <= 1
        finally:
            sched.stop()
            factory.stop()
            LEDGER.clear()

    def test_explain_off_plain_failure_path(self, client):
        """KTPU_EXPLAIN off: scheduling still works, generic failure text."""
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        LEDGER.clear()
        client.create("nodes", mk_node("n0"))
        client.create("pods", mk_pod("fits", cpu="100m"))
        client.create("pods", mk_pod("nofit", selector={"disk": "nvme"}))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(
            batch_size=16, explain=False).run()
        try:
            deadline = time.monotonic() + 60
            cond = None
            while time.monotonic() < deadline and cond is None:
                nofit = client.get("pods", "nofit", "default")
                cond = next(
                    (c for c in ((nofit.status.conditions or [])
                                 if nofit.status else [])
                     if c.type == api.POD_SCHEDULED
                     and c.status == api.CONDITION_FALSE), None)
                time.sleep(0.05)
            assert cond is not None
            assert "no feasible node in batch" in (cond.message or "")
            assert LEDGER.get("default/nofit") is None
        finally:
            sched.stop()
            factory.stop()

    def test_status_write_failure_counted(self, client, monkeypatch, caplog):
        import logging
        from kubernetes_tpu.client.rest import ApiError
        from kubernetes_tpu.scheduler.factory import ConfigFactory, Scheduler
        from kubernetes_tpu.scheduler.generic import FitError
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
        client.create("nodes", mk_node("n0"))
        factory = ConfigFactory(client)
        factory.run()
        sched = Scheduler(factory, algorithm=None)
        try:
            real_request = client.request

            def failing(verb, path, *a, **kw):
                if verb == "PUT" and path.endswith("/status"):
                    raise ApiError(503, "ServiceUnavailable", "injected")
                return real_request(verb, path, *a, **kw)

            monkeypatch.setattr(client, "request", failing)
            before = METRICS.counter_totals().get(
                "scheduler_status_write_errors_total", 0.0)
            pod = mk_pod("doomed")
            with caplog.at_level(logging.WARNING, logger="scheduler"):
                sched._handle_failure(pod, FitError(pod, {"n0": "X: nope"}))
            after = METRICS.counter_totals().get(
                "scheduler_status_write_errors_total", 0.0)
            assert after == before + 1
            assert "Unschedulable status write failed" in caplog.text
        finally:
            sched.stop()
            factory.stop()
