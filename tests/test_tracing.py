"""ISSUE 11: cross-process tracing, apiserver audit log, flight recorder.

Covers the tentpole (traceparent through client -> apiserver -> storage,
structured audit with rotation + /auditz, flight-recorder bundles on
wedge/burn triggers) and the satellites (chaos visibility, per-trace span
lookup, retry-chain propagation through a chaos-injected 500).
"""

import json
import os
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.observability.audit import AUDIT, AuditLog, AuditRecord
from kubernetes_tpu.utils import trace


def wait_for(cond, timeout=30.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, user_agent="test-tracer")


@pytest.fixture(autouse=True)
def _clean_audit():
    AUDIT.clear()
    yield
    AUDIT.clear()


def mk_pod(name, ns="default"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")]))


def mk_node(name):
    return api.Node(
        metadata=api.ObjectMeta(name=name,
                                labels={api.LABEL_HOSTNAME: name}),
        status=api.NodeStatus(
            allocatable={"cpu": "4", "memory": "8Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


def audit_tail(**kw):
    return AUDIT.tail(**kw)


# --- trace context / ids ------------------------------------------------------

class TestTraceContext:
    def test_ids_are_w3c_shaped_hex(self):
        sp = trace.Span("x")
        assert len(sp.trace_id) == 32 and len(sp.span_id) == 16
        int(sp.trace_id, 16), int(sp.span_id, 16)  # pure hex

    def test_traceparent_round_trip(self):
        sp = trace.Span("x")
        header = trace.format_traceparent(sp)
        parsed = trace.parse_traceparent(header)
        assert parsed == (sp.trace_id, sp.span_id)

    def test_garbled_traceparent_degrades_to_none(self):
        for bad in (None, "", "xx", "00-zz-yy-01", "00-abc", "totally wrong"):
            assert trace.parse_traceparent(bad) is None

    def test_use_span_sets_and_restores(self):
        assert trace.current_span() is None
        sp = trace.Span("outer")
        with trace.use_span(sp):
            assert trace.current_span() is sp
            inner = trace.Span("inner", parent=sp)
            with trace.use_span(inner):
                assert trace.current_span() is inner
            assert trace.current_span() is sp
        assert trace.current_span() is None
        inner.finish(), sp.finish()

    def test_use_span_none_is_noop(self):
        with trace.use_span(None) as got:
            assert got is None
            assert trace.current_span() is None

    def test_spans_for_trace_and_clear(self):
        root = trace.Span("root")
        root.child("a").finish()
        root.finish()
        other = trace.Span("other")
        other.finish()
        got = trace.spans_for_trace(root.trace_id)
        assert {s.name for s in got} == {"root", "a"}
        trace.clear_recent()
        assert trace.spans_for_trace(root.trace_id) == []


# --- propagation client -> apiserver -> storage -------------------------------

class TestPropagation:
    def test_audit_record_shares_the_client_trace(self, server, client):
        root = trace.Span("op")
        with trace.use_span(root):
            client.list("pods")
        root.finish()
        rec = wait_for(
            lambda: next(iter(audit_tail(trace_id=root.trace_id)), None),
            msg="audit record on the client trace")
        assert rec.verb == "GET" and "/pods" in rec.path
        assert rec.status == 200
        assert rec.component == "test-tracer"
        assert rec.latency_seconds > 0
        # the client-side rest span is the server span's remote parent
        rest = [s for s in trace.spans_for_trace(root.trace_id)
                if s.name == "rest:GET"]
        assert rest and rec.parent_id == rest[0].span_id

    def test_untraced_request_gets_server_minted_trace(self, server, client):
        client.list("nodes")
        rec = wait_for(lambda: next(iter(audit_tail(path_contains="/nodes")),
                                    None), msg="audit record")
        assert rec.trace_id and rec.parent_id == ""

    def test_bind_audit_carries_cas_and_pod_trace(self, server, client):
        client.create("nodes", mk_node("n1"))
        client.create("pods", mk_pod("p1"))
        root = trace.Span("schedule_pod", pod="default/p1")
        binding = api.Binding(
            metadata=api.ObjectMeta(name="p1", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n1"))
        with trace.use_span(root):
            client.bind(binding, "default")
        root.finish()
        rec = wait_for(
            lambda: next(iter(audit_tail(trace_id=root.trace_id,
                                         path_contains="/bindings")), None),
            msg="binding audit record")
        assert rec.verb == "POST" and rec.status == 201
        # the binding rides guaranteed_update; uncontended -> 0 CAS retries,
        # and the field exists (the contended case is exercised below)
        assert rec.cas_retries == 0
        bound = client.get("pods", "p1", "default")
        assert bound.spec.node_name == "n1"

    def test_cas_retries_audited_on_contended_patch(self, server, client):
        """Storage CAS conflicts burned serving a request surface in its
        audit record (trace.note_cas_retry via MemStore.guaranteed_update
        and the PATCH retry loop)."""
        import threading

        client.create("pods", mk_pod("contended"))
        errs = []

        def patcher(i):
            c = RESTClient.for_server(server, user_agent=f"patcher-{i}")
            try:
                for k in range(8):
                    c.patch("pods", "contended",
                            {"metadata": {"labels": {f"k{i}-{k}": "v"}}},
                            namespace="default")
            except Exception as e:  # surface, don't deadlock the join
                errs.append(e)

        threads = [threading.Thread(target=patcher, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        recs = wait_for(
            lambda: [r for r in audit_tail(verb="PATCH")
                     if r.status == 200] or None,
            msg="patch audit records")
        assert len(recs) >= 8
        # the field is wired: at least plausibly-contended writes record it
        assert all(r.cas_retries >= 0 for r in recs)

    def test_watch_request_is_audited_with_trace(self, server, client):
        root = trace.Span("watcher")
        with trace.use_span(root):
            w = client.watch("pods", resource_version=0)
        w.stop()
        root.finish()
        rec = wait_for(
            lambda: next(iter(audit_tail(trace_id=root.trace_id)), None),
            msg="watch audit record")
        assert "watch=true" in rec.path

    def test_healthz_is_not_audited(self, server, client):
        import http.client as hc

        conn = hc.HTTPConnection("127.0.0.1", server.port, timeout=10)
        conn.request("GET", "/healthz")
        assert conn.getresponse().read() == b"ok"
        conn.close()
        client.list("pods")
        wait_for(lambda: len(AUDIT) > 0, msg="audit record")
        assert not audit_tail(path_contains="/healthz")


# --- retry-chain propagation (satellite: reflector through chaos 500) ---------

class TestRetryChainPropagation:
    def test_relist_through_injected_500_keeps_one_trace(self, server,
                                                         client):
        """A reflector whose first LIST dies on a chaos-injected 500 must
        retry under the SAME trace id, and the successful retry's audit
        record must carry the retry ordinal."""
        from kubernetes_tpu.client.chaos import (
            HTTPError, PathChaos, Times, install_chaos,
        )
        from kubernetes_tpu.client.informer import Informer
        from kubernetes_tpu.client.reflector import ListWatch

        client.create("pods", mk_pod("seed"))
        ctl = install_chaos(
            client,
            PathChaos(r"/api/v1/pods$", Times(1, HTTPError(500)),
                      methods={"GET"}),
            seed=7)
        inf = Informer(ListWatch(client, "pods"), relist_backoff=0.05)
        try:
            inf.run()
            assert inf.wait_for_sync(20), "informer never synced"
            assert ctl.count("HTTPError") == 1, "chaos 500 was not injected"
            # the successful LIST records the retry ordinal from the chain.
            # Wait for the 200-status record SPECIFICALLY: the client's
            # sync completes when it reads the response, but the server
            # writes the audit record after sending it — with TCP_NODELAY
            # those two races are actually visible
            lists = wait_for(
                lambda: [r for r in audit_tail(verb="GET")
                         if r.path == "/api/v1/pods" and r.status == 200],
                msg="successful audited LIST")
            assert lists[0].retries == 1, lists[0]
            chain_trace = lists[0].trace_id
            # ... and the watch opened after the retry stays ON that trace
            wait_for(lambda: [r for r in audit_tail(trace_id=chain_trace)
                              if "watch=true" in r.path],
                     msg="watch on the chain trace")
        finally:
            ctl.uninstall()
            inf.stop()
        # the chain span finishes when the pump exits; stop()'s join is
        # bounded, so poll rather than assert the instant stop() returns
        chains = wait_for(
            lambda: [s for s in trace.spans_for_trace(chain_trace)
                     if s.name == "reflector_sync"],
            timeout=10, msg="finished reflector chain span")
        assert chains[0].attrs.get("retries") == 1


# --- chaos visibility (satellite) ---------------------------------------------

class TestChaosVisibility:
    def test_interventions_counted_and_stamped_on_span(self, server, client):
        from kubernetes_tpu.client.chaos import (
            HTTPError, PathChaos, Times, install_chaos,
        )
        from kubernetes_tpu.client.rest import ApiError
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

        before = METRICS.counter_value(
            "rest_client_chaos_interventions_total", kind="HTTPError(503)")
        ctl = install_chaos(
            client, PathChaos(r"/pods", Times(1, HTTPError(503))), seed=1)
        root = trace.Span("chaotic_op")
        try:
            with trace.use_span(root):
                with pytest.raises(ApiError):
                    client.list("pods")
        finally:
            root.finish()
            ctl.uninstall()
        after = METRICS.counter_value(
            "rest_client_chaos_interventions_total", kind="HTTPError(503)")
        assert after == before + 1
        # the injected fault is attributable from the trace alone
        rest = [s for s in trace.spans_for_trace(root.trace_id)
                if s.name == "rest:GET"]
        assert rest and rest[0].attrs.get("chaos_intervention") \
            == "HTTPError(503)"
        assert rest[0].attrs.get("status") == 503


# --- audit log mechanics ------------------------------------------------------

class TestAuditLog:
    def _rec(self, i):
        return AuditRecord(ts="t", verb="GET", path=f"/p/{i}",
                           trace_id=f"{i:032x}")

    def test_ring_is_bounded_and_filtered(self):
        log = AuditLog(capacity=8)
        for i in range(20):
            log.record(self._rec(i))
        assert len(log) == 8
        assert [r.path for r in log.tail(3)] == ["/p/17", "/p/18", "/p/19"]
        assert log.tail(trace_id=f"{19:032x}")[0].path == "/p/19"
        assert log.tail(path_contains="/p/18")[0].path == "/p/18"
        # n <= 0 is empty, never "the whole ring" (out[-0:] trap)
        assert log.tail(0) == [] and log.tail(-5) == []

    def test_disk_sink_bounded_with_zero_backups(self, tmp_path):
        path = str(tmp_path / "audit0.log")
        log = AuditLog(capacity=8, path=path, max_bytes=400, backups=0)
        for i in range(80):
            log.record(self._rec(i))
        log.close()
        assert os.listdir(tmp_path) == ["audit0.log"]
        assert os.path.getsize(path) <= 800, "max_bytes must still bound"

    def test_disk_sink_rotates_bounded(self, tmp_path):
        path = str(tmp_path / "audit.log")
        log = AuditLog(capacity=16, path=path, max_bytes=600, backups=2)
        for i in range(60):
            log.record(self._rec(i))
        log.close()
        files = sorted(os.listdir(tmp_path))
        assert "audit.log" in files
        assert "audit.log.1" in files
        # bounded: never more than backups + live file
        assert len(files) <= 3, files
        # rotated files hold parseable JSON lines
        with open(tmp_path / "audit.log.1") as fh:
            for line in fh:
                assert json.loads(line)["verb"] == "GET"

    def test_auditz_endpoint_live(self, server, client):
        client.list("pods")
        wait_for(lambda: len(AUDIT) > 0, msg="audit record")
        out = client.request("GET", "/auditz?n=4")
        assert out["returned"] >= 1
        assert out["records"][-1]["path"].endswith("/auditz") is False
        fields = set(out["records"][0])
        assert {"verb", "path", "status", "trace_id", "cas_retries",
                "latency_seconds", "retries"} <= fields

    def test_auditz_on_debug_mux(self, server, client):
        import http.client as hc

        from kubernetes_tpu.utils.debugserver import DebugServer

        client.list("pods")
        wait_for(lambda: len(AUDIT) > 0, msg="audit record")
        dbg = DebugServer(port=0).start()
        try:
            conn = hc.HTTPConnection("127.0.0.1", dbg.port, timeout=10)
            conn.request("GET", "/auditz?n=2")
            resp = conn.getresponse()
            assert resp.status == 200
            doc = json.loads(resp.read().decode())
            assert doc["returned"] >= 1
            conn.close()
        finally:
            dbg.stop()


# --- flight recorder ----------------------------------------------------------

class TestFlightRecorder:
    def test_bundle_schema_and_pruning(self, tmp_path, server, client):
        from kubernetes_tpu.observability.flightrecorder import FlightRecorder

        client.list("pods")
        wait_for(lambda: len(AUDIT) > 0, msg="audit record")
        sp = trace.Span("doomed")
        sp.finish()
        fr = FlightRecorder(directory=str(tmp_path), keep=3)
        fr.note("round", n=1)
        fr.snapshot_metrics()
        paths = [fr.dump(f"reason-{i}") for i in range(5)]
        assert all(paths)
        bundles = fr.bundles()
        assert len(bundles) == 3, "pruning must keep the newest 3"
        doc = json.load(open(bundles[-1]))
        assert doc["kind"] == "ktpu-flight-recorder-bundle"
        assert doc["reason"] == "reason-4"
        assert any(s["name"] == "doomed" for s in doc["spans"])
        assert doc["audit"], "audit tail missing from bundle"
        assert any(n["kind"] == "round" for n in doc["notes"])
        assert any(n["kind"] == "metrics_delta" for n in doc["notes"])
        assert "counters" in doc["metrics"]

    def test_timed_out_span_survives_the_tail_cap(self, tmp_path, server,
                                                  client):
        """A wedge fires early, churn continues: the bundle must still carry
        the timed-out stage span even once >512 newer spans exist."""
        from kubernetes_tpu.observability.flightrecorder import FlightRecorder

        client.list("pods")
        wait_for(lambda: len(AUDIT) > 0, msg="audit record")
        hung = trace.Span("solve", timeout=True)
        hung.finish()
        for i in range(600):
            trace.Span(f"later-{i}").finish()
        fr = FlightRecorder(directory=str(tmp_path))
        doc = json.load(open(fr.dump("late-wedge")))
        assert doc["spans_truncated"] is True
        assert any(s["span_id"] == hung.span_id for s in doc["spans"]), \
            "timed-out span fell off the bundle tail"

    def test_rate_limit_per_reason(self, tmp_path):
        from kubernetes_tpu.observability.flightrecorder import FlightRecorder

        fr = FlightRecorder(directory=str(tmp_path), min_interval=60.0)
        assert fr.dump("hot", force=False) is not None
        assert fr.dump("hot", force=False) is None, "rate limit must hold"
        assert fr.dump("hot", force=True) is not None, "force must bypass"
        assert fr.dump("other", force=False) is not None, "per-reason limit"

    def test_stage_timeout_dumps_and_finishes_stage_span(self, tmp_path,
                                                         monkeypatch):
        """The watchdog trigger: a hung stage produces a StageTimeout AND a
        bundle containing the timed-out stage's (force-finished) span."""
        import kubernetes_tpu.observability.flightrecorder as fr_mod
        from kubernetes_tpu.ops import watchdog

        fr = fr_mod.FlightRecorder(directory=str(tmp_path))
        monkeypatch.setattr(fr_mod, "RECORDER", fr)
        root = trace.Span("batch")
        try:
            with pytest.raises(watchdog.StageTimeout) as ei:
                watchdog.run_stages(
                    lambda stage: stage("solve", lambda: time.sleep(30)),
                    deadlines={"solve": 0.2}, span=root, poll=0.02)
        finally:
            root.finish()
        assert ei.value.stage == "solve"
        bundles = fr.bundles()
        assert bundles, "stage timeout must dump a bundle"
        doc = json.load(open(bundles[-1]))
        assert doc["reason"] == "stage-timeout"
        assert doc["trigger"]["stage"] == "solve"
        timed_out = [s for s in doc["spans"]
                     if s["name"] == "solve" and s["attrs"].get("timeout")]
        assert timed_out, "bundle must contain the timed-out stage's span"
        assert timed_out[0]["trace_id"] == root.trace_id

    def test_slo_burn_transition_dumps_once(self, tmp_path, monkeypatch):
        import kubernetes_tpu.observability.flightrecorder as fr_mod
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        from kubernetes_tpu.utils.metrics import MetricsRegistry

        fr = fr_mod.FlightRecorder(directory=str(tmp_path))
        monkeypatch.setattr(fr_mod, "RECORDER", fr)
        scraper = Scraper()
        scraper.add_target("t", "127.0.0.1", 1)  # never fetched
        scraper.ingest("t", '# HELP g g (gauge)\n# TYPE g gauge\n'
                            'g{x="1"} 5\n', ts=0.0)
        spec = SLOSpec(name="g-low", target="t", sli="gauge", metric="g",
                       labels=(("x", "1"),), objective=1.0, bound="max",
                       windows=(Window(5.0, 1.0),))
        engine = SLOEngine(scraper, [spec], registry=MetricsRegistry())
        r1 = engine.evaluate()
        assert r1[0].verdict == "burning"
        assert len(fr.bundles()) == 1, "transition must dump"
        engine.evaluate()
        assert len(fr.bundles()) == 1, "sustained burn must not re-dump"
        doc = json.load(open(fr.bundles()[0]))
        assert doc["trigger"]["slo"] == "g-low"


# --- the acceptance path: seeded hang_stage soak ships its black box ----------

@pytest.mark.usefixtures("_clean_audit")
class TestWedgedSoakForensics:
    def test_wedged_soak_writes_diagnosable_bundle(self, monkeypatch,
                                                   tmp_path):
        """Acceptance: hang_stage soak ends wedged AND its bundle carries
        the timed-out stage's span, the audit records around it, and the
        SLO verdicts."""
        import kubernetes_tpu.observability.flightrecorder as fr_mod
        from kubernetes_tpu.observability.soak import SoakConfig, run_soak

        fr = fr_mod.FlightRecorder(directory=str(tmp_path))
        monkeypatch.setattr(fr_mod, "RECORDER", fr)
        # soak.py binds RECORDER at import time — repoint that reference too
        import kubernetes_tpu.observability.soak as soak_mod
        monkeypatch.setattr(soak_mod, "RECORDER", fr)

        cfg = SoakConfig(num_nodes=4, create_rate=20, duration_seconds=2.0,
                         scrape_period=0.8, batch_size=16,
                         heartbeat_period=2.0, drain_timeout=20,
                         hang_stage="solve")
        report = run_soak(cfg)
        assert report["wedged"] is True
        assert "solve" in report.get("stage_timeouts", {})
        path = report.get("flight_recorder_bundle")
        assert path and os.path.exists(path), report.get("error")
        doc = json.load(open(path))
        assert doc["reason"] == "soak-wedged"
        # 1. the timed-out stage's span
        hung = [s for s in doc["spans"]
                if s["name"] == "solve" and s["attrs"].get("timeout")]
        assert hung, "bundle must contain the timed-out solve span"
        # 2. the triggering audit records (the soak's own API churn)
        assert doc["audit"], "bundle must carry the audit tail"
        assert any(r["verb"] == "POST" for r in doc["audit"])
        # 3. the SLO verdicts
        assert doc["trigger"].get("slos"), "bundle must carry SLO verdicts"
        # and the rounds that led into the wedge rode the notes ring
        assert any(n["kind"] == "soak_round" for n in doc["notes"])
