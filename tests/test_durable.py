"""Durable storage: WAL + snapshot recovery (round-3 verdict #5).

The kill-and-restart contract: a restarted apiserver recovers every object
at the same resourceVersions; clients holding stale RVs get 410 and
re-list (the Reflector contract), so nothing above L0 special-cases crash
recovery (pkg/storage/etcd/etcd_helper.go / api_object_versioner.go
semantics)."""

import os

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.informer import Informer, ListWatch
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.registry.generic import Registry
from kubernetes_tpu.storage import Conflict, DurableStore


class TestRecovery:
    def test_restart_recovers_objects_and_rv(self, tmp_path):
        d = str(tmp_path)
        s = DurableStore(d)
        s.create("/pods/default/a", {"v": 1})
        rv_b = s.create("/pods/default/b", {"v": 2})
        s.update("/pods/default/a", {"v": 10})
        s.delete("/pods/default/b", expect_rv=rv_b)
        rv = s.current_rv
        s.close()

        r = DurableStore(d)
        assert r.current_rv == rv
        obj, orv = r.get("/pods/default/a")
        assert obj == {"v": 10}
        with pytest.raises(Exception):
            r.get("/pods/default/b")
        # writes continue from the recovered rv, monotonic
        assert r.create("/pods/default/c", {"v": 3}) == rv + 1
        r.close()

    def test_snapshot_truncates_wal_and_recovers(self, tmp_path):
        import time
        d = str(tmp_path)
        s = DurableStore(d, snapshot_every=10)
        for i in range(25):   # crosses snapshot boundaries
            s.create(f"/k/{i:02d}", {"i": i})
        # compaction is asynchronous: wait for quiescence
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
                s._snapshotting
                or os.path.exists(os.path.join(d, "wal.log.1"))):
            time.sleep(0.02)
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        wal_lines = open(os.path.join(d, "wal.log")).read().splitlines()
        assert len(wal_lines) < 25  # the log was compacted at least once
        s.close()

        r = DurableStore(d)
        items, rv = r.list("/k/")
        assert len(items) == 25 and rv == 25
        assert r.replayed == len(wal_lines)
        r.close()

    def test_crash_between_rotate_and_snapshot_loses_nothing(self, tmp_path):
        d = str(tmp_path)
        s = DurableStore(d)
        for i in range(6):
            s.create(f"/k/{i}", {"i": i})
        s.close()
        # simulate the crash window: WAL rotated, snapshot never written
        os.replace(os.path.join(d, "wal.log"), os.path.join(d, "wal.log.1"))
        open(os.path.join(d, "wal.log"), "w").close()
        r = DurableStore(d)
        assert r.count("/k/") == 6 and r.current_rv == 6
        # init folded the stale segment into a fresh snapshot
        assert not os.path.exists(os.path.join(d, "wal.log.1"))
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        r.close()
        r2 = DurableStore(d)   # and it stays recoverable
        assert r2.count("/k/") == 6
        r2.close()

    def test_torn_wal_tail_is_dropped(self, tmp_path):
        d = str(tmp_path)
        s = DurableStore(d)
        s.create("/k/good", {"v": 1})
        s.close()
        with open(os.path.join(d, "wal.log"), "a") as f:
            f.write('{"t":"ADDED","k":"/k/torn","rv":2,"o":{"v')  # crash
        r = DurableStore(d)
        assert r.count("/k/") == 1
        assert r.current_rv == 1
        r.close()

    def test_cas_semantics_preserved(self, tmp_path):
        s = DurableStore(str(tmp_path))
        rv = s.create("/k/x", {"n": 0})
        with pytest.raises(Conflict):
            s.update("/k/x", {"n": 1}, expect_rv=rv + 5)
        s.guaranteed_update("/k/x", lambda obj, _rv: {"n": obj["n"] + 1})
        assert s.get("/k/x")[0] == {"n": 1}
        s.close()

    def test_torn_mid_file_stops_at_tear_and_logs_drop_count(
            self, tmp_path, caplog):
        """A tear in the MIDDLE of the WAL (bit rot, torn sector) must stop
        recovery at the tear — applying later entries would fabricate
        history across the hole — and must say how much it dropped, never
        truncate silently."""
        import os
        d = str(tmp_path)
        s = DurableStore(d)
        for i in range(4):
            s.create(f"/k/{i}", {"i": i})
        s.close()
        path = os.path.join(d, "wal.log")
        lines = open(path).read().splitlines()
        lines[1] = lines[1][: len(lines[1]) // 2]  # tear entry #2
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        with caplog.at_level("WARNING", logger="storage.durable"):
            r = DurableStore(d)
        assert r.current_rv == 1          # stopped AT the tear
        assert r.count("/k/") == 1
        assert r.dropped_entries == 3     # the torn line + 2 good ones after
        assert any("dropped 3 entries" in rec.getMessage()
                   for rec in caplog.records)
        r.close()

    def test_close_drains_background_compaction(self, tmp_path):
        """close() must join an in-flight compaction thread instead of
        racing it over the files, and a compaction must never spawn after
        the store is flagged closed."""
        s = DurableStore(str(tmp_path), snapshot_every=10)
        for i in range(35):  # several threshold crossings
            s.create(f"/k/{i:02d}", {"i": i})
        s.close()
        t = s._snapshot_thread
        assert t is None or not t.is_alive()
        # the data survived whatever compaction state close() drained
        r = DurableStore(str(tmp_path))
        assert r.count("/k/") == 35
        r.close()

    def test_snapshot_after_close_is_logged_noop(self, tmp_path, caplog):
        s = DurableStore(str(tmp_path))
        s.create("/k/a", {"v": 1})
        s.close()
        with caplog.at_level("WARNING", logger="storage.durable"):
            s.snapshot()  # must not raise ValueError from the dead handle
        assert any("no-op" in rec.getMessage() for rec in caplog.records)
        r = DurableStore(str(tmp_path))
        assert r.get("/k/a")[0] == {"v": 1}
        r.close()


def mk_pod(name):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")]))


class TestKillAndRestartE2E:
    def test_apiserver_recovers_and_stale_watch_gets_410(self, tmp_path):
        d = str(tmp_path)
        server = APIServer(Registry(DurableStore(d))).start()
        client = RESTClient.for_server(server, qps=1000, burst=1000)
        for i in range(20):
            client.create("pods", mk_pod(f"p-{i:02d}"))
        client.create("nodes", api.Node(
            metadata=api.ObjectMeta(name="n-0"),
            status=api.NodeStatus(allocatable={"cpu": "4"})))
        _, rv_before = client.list("pods", "default")
        server.registry.store.close()
        server.stop()   # kill

        # restart on the same data dir
        server2 = APIServer(Registry(DurableStore(d))).start()
        try:
            client2 = RESTClient.for_server(server2, qps=1000, burst=1000)
            pods, rv_after = client2.list("pods", "default")
            assert len(pods) == 20
            assert int(rv_after) == int(rv_before)
            nodes, _ = client2.list("nodes")
            assert [n.metadata.name for n in nodes] == ["n-0"]

            # a watcher resuming from a pre-restart RV: the event window
            # died with the process -> 410 Gone -> client re-lists
            with pytest.raises(ApiError) as ei:
                stream = client2.watch("pods", "default",
                                       resource_version=1)
                next(iter(stream))
            assert ei.value.is_gone

            # the Reflector does that dance automatically and converges
            inf = Informer(ListWatch(client2, "pods"))
            inf.run()
            assert inf.wait_for_sync(10)
            assert len(inf.store.list()) == 20
            # and new writes keep flowing to it
            client2.create("pods", mk_pod("post-restart"))
            import time
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(inf.store.list()) == 21:
                    break
                time.sleep(0.05)
            assert len(inf.store.list()) == 21
            inf.stop()
        finally:
            server2.registry.store.close()
            server2.stop()
