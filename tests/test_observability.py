"""End-to-end scheduling observability (ISSUE 8).

- Events: recorder correlation (exact-dedup count bumps, similar-storm
  aggregation) and spam-filter semantics, posted through the live apiserver.
- Pipeline spans: IDs + parent links carried from pod arrival through queue
  wait, the kernel stages (tensorize/upload/compile|solve), and bind.
- Stage watchdogs: an injected kernel-stage hang surfaces as a
  scheduler_stage_timeout metric + structured StageTimeout within the stage
  deadline, and the batch falls back sequentially instead of wedging.
- SLI exposition: e2e scheduling latency, pod startup latency, informer
  watch lag, and workqueue depth/latency all served on /metrics.
- Round-5 hardening satellites: federation probe loop, route-controller
  CIDR reclaim, volume-manager lock scope, TLS verification opt-in.
"""

import io
import os
import threading
import time
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.events import EventCorrelator, EventRecorder
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS


def wait_for(cond, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=5000, burst=5000)


def mk_pod(name, ns="default"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(
                requests={"cpu": "100m", "memory": "100Mi"}))]))


def mk_node(name):
    return api.Node(
        metadata=api.ObjectMeta(name=name,
                                labels={api.LABEL_HOSTNAME: name}),
        status=api.NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


# --- events: correlation / aggregation / spam filter -------------------------

class TestEventCorrelation:
    def test_exact_repeat_bumps_count(self, client):
        rec = EventRecorder(client, "test-comp")
        pod = client.create("pods", mk_pod("dup"))
        for _ in range(3):
            rec.event(pod, "Warning", "FailedScheduling", "no nodes")
        rec.flush()
        wait_for(lambda: client.list("events", "default")[0]
                 and client.list("events", "default")[0][0].count == 3,
                 msg="count bump")
        evs, _ = client.list("events", "default")
        assert len(evs) == 1
        assert evs[0].reason == "FailedScheduling"

    def test_similar_storm_aggregates(self, client):
        """> max_similar events differing only in message collapse onto one
        '(combined from similar events)' aggregate whose count climbs."""
        rec = EventRecorder(
            client, "test-comp",
            correlator=EventCorrelator(max_similar=3))
        pod = client.create("pods", mk_pod("stormy"))
        for i in range(8):
            rec.event(pod, "Warning", "Unhealthy", f"probe failed #{i}")
        rec.flush()
        wait_for(lambda: any(
            e.message.startswith("(combined from similar events)")
            for e in client.list("events", "default")[0]),
            msg="aggregate event")
        evs, _ = client.list("events", "default")
        # 3 distinct events + 1 aggregate that soaked up the remaining 5
        assert len(evs) <= 4
        agg = [e for e in evs
               if e.message.startswith("(combined from similar events)")]
        assert len(agg) == 1
        wait_for(lambda: client.get(
            "events", agg[0].metadata.name, "default").count >= 5,
            msg="aggregate count climbs")

    def test_spam_filter_drops(self, client):
        """Beyond the per-(source, object) burst, events are dropped and
        counted — not posted."""
        rec = EventRecorder(
            client, "spammy",
            correlator=EventCorrelator(spam_burst=2, spam_qps=0.0))
        pod = client.create("pods", mk_pod("victim"))
        before = METRICS.counter_value("events_discarded_total",
                                       component="spammy")
        for i in range(10):
            rec.event(pod, "Warning", "Boom", f"m{i}")
        rec.flush()
        wait_for(lambda: METRICS.counter_value(
            "events_discarded_total", component="spammy") - before == 8,
            msg="spam drops counted")
        evs, _ = client.list("events", "default")
        assert len(evs) == 2

    def test_correlator_unit_semantics(self):
        c = EventCorrelator(max_similar=2, spam_burst=100)
        src = ("comp", "", "Pod", "ns", "p", "")
        sim = ("Pod", "ns", "p", "Warning", "Fail")
        k1, m1, agg1 = c.correlate(src, sim, "a")
        k2, m2, agg2 = c.correlate(src, sim, "a")
        assert k1 == k2 and not agg1 and not agg2  # exact dedup identity
        k3, _, agg3 = c.correlate(src, sim, "b")
        assert k3 != k1 and not agg3               # distinct message
        k4, m4, agg4 = c.correlate(src, sim, "c")
        assert agg4 and k4 == sim                  # storm -> aggregate
        assert m4.startswith("(combined from similar events)")


# --- pipeline spans + SLIs through a live control plane ----------------------

class TestPipelineObservability:
    @pytest.fixture()
    def cluster(self, server, client):
        from kubernetes_tpu.kubelet.kubelet import Kubelet
        from kubernetes_tpu.scheduler.factory import ConfigFactory

        trace.clear_recent()
        client.create("nodes", mk_node("n1"))
        kubelet = Kubelet(RESTClient.for_server(server), "n1",
                          sync_period=0.2, heartbeat_period=1.0)
        kubelet.start(register=False)
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=64).run()
        yield client, factory, sched
        sched.stop()
        factory.stop()
        kubelet.stop()

    def test_spans_and_slis_end_to_end(self, server, cluster):
        client, factory, sched = cluster
        client.create("pods", mk_pod("traced"))
        wait_for(lambda: (client.get("pods", "traced", "default").spec
                          .node_name), msg="pod bound")
        wait_for(lambda: (client.get("pods", "traced", "default").status
                          and client.get("pods", "traced",
                                         "default").status.phase == "Running"),
                 msg="pod running")

        # -- span propagation: pod root -> queue_wait + bind children ------
        root = wait_for(
            lambda: next((s for s in trace.recent_spans("schedule_pod")
                          if s.attrs.get("pod") == "default/traced"), None),
            msg="pod root span")
        names = {c.name for c in root.children}
        assert "queue_wait" in names and "bind" in names
        for c in root.children:
            assert c.parent_id == root.span_id
            assert c.trace_id == root.trace_id
            assert c.end is not None
        # the batch that solved it links back via the batch trace id
        batch_trace = root.attrs.get("batch_trace")
        assert batch_trace
        batch_roots = trace.recent_spans("schedule_batch",
                                         trace_id=batch_trace)
        assert batch_roots
        stage_names = {c.name for c in batch_roots[0].children}
        assert "tensorize" in stage_names and "upload" in stage_names
        assert stage_names & {"compile", "solve"}

        # -- SLI histograms non-empty on the registry ----------------------
        assert METRICS.hist_total(
            "scheduler_e2e_scheduling_latency_seconds") >= 1
        assert METRICS.hist_total("scheduler_pod_queue_wait_seconds") >= 1
        assert METRICS.hist_total("scheduler_informer_delivery_seconds") >= 1
        wait_for(lambda: METRICS.hist_total(
            "kubelet_pod_startup_latency_seconds") >= 1,
            msg="pod startup latency observed")
        assert METRICS.hist_total("scheduler_stage_seconds") >= 3

        # -- /metrics exposition (the per-component debug mux) -------------
        from kubernetes_tpu.utils.debugserver import DebugServer
        import http.client as hc
        dbg = DebugServer(port=0).start()
        try:
            conn = hc.HTTPConnection("127.0.0.1", dbg.port, timeout=5)
            conn.request("GET", "/metrics")
            body = conn.getresponse().read().decode()
            conn.close()
        finally:
            dbg.stop()
        for series in ("scheduler_e2e_scheduling_latency_seconds_bucket",
                       "kubelet_pod_startup_latency_seconds_bucket",
                       "scheduler_pod_queue_wait_seconds_bucket",
                       "scheduler_stage_seconds_bucket",
                       "informer_watch_lag_seconds"):
            assert series in body, f"{series} missing from /metrics"

        # -- events visible through kubectl --------------------------------
        from kubernetes_tpu.kubectl import cmd as kubectl
        out = io.StringIO()
        with redirect_stdout(out):
            rc = kubectl.main(["-s", f"127.0.0.1:{server.port}",
                               "get", "events"])
        assert rc == 0
        wait_for(lambda: "Scheduled" in _kubectl_out(server, "get", "events"),
                 msg="Scheduled event via kubectl")
        desc = _kubectl_out(server, "describe", "pod", "traced")
        assert "Events:" in desc and "Scheduled" in desc

    def test_workqueue_slis(self, server, client):
        """A named controller workqueue exports depth + latency series."""
        from kubernetes_tpu.controllers.replication_controller import (
            ReplicationManager,
        )
        mgr = ReplicationManager(client, workers=1)
        mgr.start()
        try:
            rc = api.ReplicationController(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ReplicationControllerSpec(
                    replicas=2, selector={"app": "web"},
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"app": "web"}),
                        spec=api.PodSpec(containers=[
                            api.Container(name="c", image="pause")]))))
            client.create("replicationcontrollers", rc)
            wait_for(lambda: len(client.list("pods", "default")[0]) == 2,
                     msg="RC created pods")
            wait_for(lambda: METRICS.hist_total(
                "workqueue_queue_latency_seconds") >= 1,
                msg="workqueue latency observed")
            wait_for(lambda: METRICS.hist_total(
                "workqueue_work_duration_seconds") >= 1,
                msg="workqueue work duration observed")
            assert "replication" in {
                dict(lk).get("queue") for lk in METRICS.hist_stats(
                    "workqueue_queue_latency_seconds")}
            # the controller's creations surfaced as events on the RC
            wait_for(lambda: any(
                e.reason == "SuccessfulCreate"
                for e in client.list("events", "default")[0]),
                msg="SuccessfulCreate event")
        finally:
            mgr.stop()


def _kubectl_out(server, *argv) -> str:
    from kubernetes_tpu.kubectl import cmd as kubectl
    out = io.StringIO()
    with redirect_stdout(out):
        kubectl.main(["-s", f"127.0.0.1:{server.port}", *argv])
    return out.getvalue()


# --- stage watchdogs ---------------------------------------------------------

class TestStageWatchdog:
    def test_hang_converts_to_stage_timeout(self):
        from kubernetes_tpu.ops.watchdog import StageTimeout, run_stages
        before = METRICS.counter_value("scheduler_stage_timeout_total",
                                       stage="upload")
        t0 = time.monotonic()
        with pytest.raises(StageTimeout) as ei:
            run_stages(lambda stage: stage("upload",
                                           lambda: time.sleep(30)),
                       deadlines={"upload": 0.3})
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0  # structured error within the deadline, no wedge
        assert ei.value.stage == "upload"
        assert "upload" in str(ei.value) and "deadline" in str(ei.value)
        assert METRICS.counter_value("scheduler_stage_timeout_total",
                                     stage="upload") == before + 1

    def test_stage_timeout_is_transient_for_classifier(self):
        from kubernetes_tpu.ops.watchdog import StageTimeout
        from kubernetes_tpu.scheduler.tpu import _is_device_error
        assert _is_device_error(StageTimeout("solve", 1.0))

    def test_abandoned_stage_leaves_mirror_lock_free(self):
        """A timed-out (abandoned) device stage must not strand the
        incremental mirror's lock: cache listeners block on it under the
        SchedulerCache lock, so a stranded lock would deadlock the whole
        informer pipeline — worse than the hang being converted."""
        from kubernetes_tpu.ops import watchdog
        from kubernetes_tpu.ops.incremental import IncrementalTensorizer
        inc = IncrementalTensorizer()
        inc._upload_staged = lambda plan, device=None: time.sleep(60)
        with pytest.raises(watchdog.StageTimeout):
            watchdog.run_stages(lambda stage: inc.schedule([], stage=stage),
                                deadlines={"upload": 0.3})
        assert inc._lock.acquire(timeout=2.0), \
            "mirror lock stranded by the abandoned upload worker"
        inc._lock.release()

    def test_injected_kernel_hang_falls_back(self, server, client):
        """A hang inside a kernel stage must not wedge the batch: the
        watchdog converts it to a StageTimeout, the timeout metric ticks,
        and the drained batch completes via the sequential fallback."""
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        client.create("nodes", mk_node("n1"))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(
            batch_size=16, stage_deadlines={"tensorize": 0.3})
        before = METRICS.counter_value("scheduler_stage_timeout_total",
                                       stage="tensorize")

        def hang_schedule(pending, weights=None, device=None, stage=None):
            return stage("tensorize", lambda: time.sleep(60))
        sched._inc.schedule = hang_schedule
        try:
            client.create("pods", mk_pod("survivor"))
            wait_for(lambda: len(factory.pending) >= 1, msg="pod queued")
            t0 = time.monotonic()
            n = sched.schedule_batch_once(timeout=5)
            assert n == 1
            assert time.monotonic() - t0 < 5.0
            assert METRICS.counter_value(
                "scheduler_stage_timeout_total",
                stage="tensorize") == before + 1
            assert sched.kernel_failures == 1
            # fell back sequentially: the pod still lands
            wait_for(lambda: client.get("pods", "survivor",
                                        "default").spec.node_name == "n1",
                     msg="fallback bound the pod")
        finally:
            sched.stop()
            factory.stop()


# --- compile-cache fingerprinting --------------------------------------------

class TestCompileCacheVisibility:
    def test_fingerprinted_dir_and_hit_miss_events(self, tmp_path):
        import jax

        from kubernetes_tpu.utils import platform as plat
        root = str(tmp_path / "xla")
        os.makedirs(root)
        # a legacy (pre-fingerprint) artifact in the root is rejected
        with open(os.path.join(root, "stale-aot-entry"), "w") as f:
            f.write("x")
        saved_dir = dict(plat._CACHE_STATE)
        try:
            d = plat.enable_persistent_compilation_cache(root)
            fp = plat.machine_fingerprint()
            assert os.path.basename(d) == fp
            assert os.path.exists(os.path.join(d, "MACHINE_FEATURES"))
            rejected = METRICS.counter_series("compile_cache_events_total")
            assert any(dict(lk).get("event") == "rejected" and v >= 1
                       for lk, v in rejected.items())

            # empty cache (marker only): nothing to hit -> "uncached"
            before = plat.compile_cache_snapshot()
            assert plat.record_compile_cache_event(before) == "uncached"
            # unchanged NON-EMPTY dir between snapshot and record -> hit
            with open(os.path.join(d, "seeded-entry"), "w") as f:
                f.write("x")
            before = plat.compile_cache_snapshot()
            assert plat.record_compile_cache_event(before) == "hit"
            # a new entry appeared -> miss
            before = plat.compile_cache_snapshot()
            with open(os.path.join(d, "new-entry"), "w") as f:
                f.write("x")
            assert plat.record_compile_cache_event(before) == "miss"
            series = METRICS.counter_series("compile_cache_events_total")
            labels = [dict(lk) for lk in series]
            assert all("fingerprint" in d2 for d2 in labels)
            assert {"hit", "miss"} <= {d2["event"] for d2 in labels}
        finally:
            plat._CACHE_STATE.update(saved_dir)
            jax.config.update("jax_compilation_cache_dir", None)

    def test_disabled_cache_is_visible(self):
        from kubernetes_tpu.utils import platform as plat
        saved = dict(plat._CACHE_STATE)
        plat._CACHE_STATE.update({"dir": "", "fingerprint": ""})
        try:
            assert plat.record_compile_cache_event(None) == "disabled"
        finally:
            plat._CACHE_STATE.update(saved)


# --- round-5 hardening satellites --------------------------------------------

class TestHardeningSatellites:
    def test_federation_probe_not_self_sustaining(self):
        """Status-only cluster updates (our own probe writes) must NOT
        re-enqueue; spec changes must."""
        from kubernetes_tpu.apis import federation as fedapi
        from kubernetes_tpu.federation.controller import (
            ClusterHealthController,
        )
        ctl = ClusterHealthController(RESTClient(), probe_period=5.0)
        try:
            def cluster(addr, ready):
                return fedapi.Cluster(
                    metadata=api.ObjectMeta(name="c1"),
                    spec=fedapi.ClusterSpec(server_address=addr),
                    status=fedapi.ClusterStatus(conditions=[
                        fedapi.ClusterCondition(
                            type=fedapi.CLUSTER_READY,
                            status="True" if ready else "False")]))
            # status flip only: no enqueue (the old self-sustaining loop)
            ctl._cluster_changed(cluster("127.0.0.1:1", True),
                                 cluster("127.0.0.1:1", False))
            assert len(ctl.queue) == 0
            # spec change: enqueue
            ctl._cluster_changed(cluster("127.0.0.1:1", True),
                                 cluster("127.0.0.1:2", True))
            wait_for(lambda: len(ctl.queue) == 1, timeout=2,
                     msg="spec change enqueued")
        finally:
            ctl.queue.shutdown()

    def test_route_controller_reclaims_cidr_on_patch_failure(self):
        from kubernetes_tpu.controllers.route_controller import (
            RouteController,
        )

        class FailingClient:
            def __init__(self):
                self.fail_code = 422

            def patch(self, *a, **kw):
                if self.fail_code:
                    raise ApiError(self.fail_code, "Boom", "injected")

        class FakeCloud:
            def __init__(self):
                self.routes = {}

            def list_routes(self):
                return dict(self.routes)

            def create_route(self, name, cidr):
                self.routes[name] = cidr

            def delete_route(self, name):
                self.routes.pop(name, None)

        fc = FailingClient()
        ctl = RouteController.__new__(RouteController)
        ctl.client = fc
        ctl.cloud = FakeCloud()
        import ipaddress
        ctl.net = ipaddress.ip_network("10.244.0.0/16")
        ctl.node_mask = 24
        ctl._cidr_lock = threading.Lock()
        ctl._issued = {}

        class Store:
            def __init__(self):
                self.nodes = {}

            def get(self, key):
                return self.nodes.get(key)

            def list(self):
                return list(self.nodes.values())

        class Inf:
            store = Store()
        ctl.node_informer = Inf()
        Inf.store.nodes["n1"] = api.Node(
            metadata=api.ObjectMeta(name="n1"), spec=api.NodeSpec())

        with pytest.raises(ApiError):
            ctl.sync("n1")
        assert ctl._issued == {}, \
            "definite 4xx rejection must reclaim the CIDR"
        # ambiguous failure (5xx: the write may have landed server-side)
        # keeps the guard entry, and the retry reuses the SAME subnet
        # instead of leaking one per attempt
        fc.fail_code = 500
        with pytest.raises(ApiError):
            ctl.sync("n1")
        assert ctl._issued == {"10.244.0.0/24": "n1"}
        fc.fail_code = 0
        ctl.sync("n1")
        # the guarded first subnet was handed out again, not leaked
        assert list(ctl._issued) == ["10.244.0.0/24"]
        # node deletion prunes its issued entries
        del Inf.store.nodes["n1"]
        ctl.sync("n1")
        assert ctl._issued == {}

    def test_volume_manager_resolves_pvc_outside_lock(self, tmp_path):
        from kubernetes_tpu.volume import VolumeManager
        vm = VolumeManager(str(tmp_path / "kubelet"))

        class Resolver:
            def __init__(self, vm):
                self.vm = vm
                self.lock_was_free = None

            def get(self, resource, name, ns=""):
                free = self.vm._lock.acquire(blocking=False)
                if free:
                    self.vm._lock.release()
                self.lock_was_free = free
                if resource == "persistentvolumeclaims":
                    return api.PersistentVolumeClaim(
                        metadata=api.ObjectMeta(name=name, namespace=ns),
                        spec=api.PersistentVolumeClaimSpec(
                            volume_name="pv1"))
                return api.PersistentVolume(
                    metadata=api.ObjectMeta(name=name),
                    spec=api.PersistentVolumeSpec(
                        host_path=api.HostPathVolumeSource(
                            path=str(tmp_path / "pv-data"))))

        vm.resolver = Resolver(vm)
        pod = api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="default"),
            spec=api.PodSpec(
                volumes=[api.Volume(
                    name="data",
                    persistent_volume_claim=api.
                    PersistentVolumeClaimVolumeSource(claim_name="cl"))],
                containers=[api.Container(
                    name="c", image="pause",
                    volume_mounts=[api.VolumeMount(name="data",
                                                   mount_path="/data")])]))
        views = vm.setup_pod(pod)
        assert vm.resolver.lock_was_free is True, \
            "PVC resolution must not run under the manager-wide lock"
        assert views["c"]["/data"] == str(tmp_path / "pv-data")
        assert vm.mounted("default/p")

    def test_tls_skip_verify_is_explicit_and_counted(self):
        class SecureStub:
            secure = True
            port = 1

        c = RESTClient.for_server(SecureStub())
        assert c.tls and not c.insecure_skip_verify, \
            "secure server must no longer imply skip-verify"
        before = METRICS.counter_value("tls_insecure_connections")
        insecure = RESTClient(tls=True, insecure_skip_verify=True)
        insecure._new_conn(1.0)
        assert METRICS.counter_value("tls_insecure_connections") == before + 1
