"""End-to-end scheduling observability (ISSUE 8 + ISSUE 10).

ISSUE 8:
- Events: recorder correlation (exact-dedup count bumps, similar-storm
  aggregation) and spam-filter semantics, posted through the live apiserver.
- Pipeline spans: IDs + parent links carried from pod arrival through queue
  wait, the kernel stages (tensorize/upload/compile|solve), and bind.
- Stage watchdogs: an injected kernel-stage hang surfaces as a
  scheduler_stage_timeout metric + structured StageTimeout within the stage
  deadline, and the batch falls back sequentially instead of wedging.
- SLI exposition: e2e scheduling latency, pod startup latency, informer
  watch lag, and workqueue depth/latency all served on /metrics.
- Round-5 hardening satellites: federation probe loop, route-controller
  CIDR reclaim, volume-manager lock scope, TLS verification opt-in.

ISSUE 10 (the cluster observatory):
- Exposition round trip: render() escaping / # HELP / le formatting parsed
  back losslessly by observability.scrape.parse_prometheus_text.
- Scraper delta math: counter deltas (reset-aware), windowed rates, and
  histogram-window quantiles over ingested rounds, plus a live HTTP scrape
  against a DebugServer.
- SLO engine: burn-rate arithmetic, multi-window gating (short-only spikes
  don't fire), explicit no_data on empty series, violation/recovery Events.
- Soak harness: a tier-1 churn smoke against HollowCluster with scraped
  steady-state SLIs, and a seeded kernel-stage hang that must end in
  wedged=true (never a hang, never success-shaped 0.0 pods/s).
- /profilez: live jax.profiler trace windows over the debug mux, and the
  always-on scheduler_kernel_device_seconds host/device split.
"""

import io
import os
import threading
import time
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.events import EventCorrelator, EventRecorder
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS


def wait_for(cond, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=5000, burst=5000)


def mk_pod(name, ns="default"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(
                requests={"cpu": "100m", "memory": "100Mi"}))]))


def mk_node(name):
    return api.Node(
        metadata=api.ObjectMeta(name=name,
                                labels={api.LABEL_HOSTNAME: name}),
        status=api.NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


# --- events: correlation / aggregation / spam filter -------------------------

class TestEventCorrelation:
    def test_exact_repeat_bumps_count(self, client):
        rec = EventRecorder(client, "test-comp")
        pod = client.create("pods", mk_pod("dup"))
        for _ in range(3):
            rec.event(pod, "Warning", "FailedScheduling", "no nodes")
        rec.flush()
        wait_for(lambda: client.list("events", "default")[0]
                 and client.list("events", "default")[0][0].count == 3,
                 msg="count bump")
        evs, _ = client.list("events", "default")
        assert len(evs) == 1
        assert evs[0].reason == "FailedScheduling"

    def test_similar_storm_aggregates(self, client):
        """> max_similar events differing only in message collapse onto one
        '(combined from similar events)' aggregate whose count climbs."""
        rec = EventRecorder(
            client, "test-comp",
            correlator=EventCorrelator(max_similar=3))
        pod = client.create("pods", mk_pod("stormy"))
        for i in range(8):
            rec.event(pod, "Warning", "Unhealthy", f"probe failed #{i}")
        rec.flush()
        wait_for(lambda: any(
            e.message.startswith("(combined from similar events)")
            for e in client.list("events", "default")[0]),
            msg="aggregate event")
        evs, _ = client.list("events", "default")
        # 3 distinct events + 1 aggregate that soaked up the remaining 5
        assert len(evs) <= 4
        agg = [e for e in evs
               if e.message.startswith("(combined from similar events)")]
        assert len(agg) == 1
        wait_for(lambda: client.get(
            "events", agg[0].metadata.name, "default").count >= 5,
            msg="aggregate count climbs")

    def test_spam_filter_drops(self, client):
        """Beyond the per-(source, object) burst, events are dropped and
        counted — not posted."""
        rec = EventRecorder(
            client, "spammy",
            correlator=EventCorrelator(spam_burst=2, spam_qps=0.0))
        pod = client.create("pods", mk_pod("victim"))
        before = METRICS.counter_value("events_discarded_total",
                                       component="spammy")
        for i in range(10):
            rec.event(pod, "Warning", "Boom", f"m{i}")
        rec.flush()
        wait_for(lambda: METRICS.counter_value(
            "events_discarded_total", component="spammy") - before == 8,
            msg="spam drops counted")
        evs, _ = client.list("events", "default")
        assert len(evs) == 2

    def test_correlator_unit_semantics(self):
        c = EventCorrelator(max_similar=2, spam_burst=100)
        src = ("comp", "", "Pod", "ns", "p", "")
        sim = ("Pod", "ns", "p", "Warning", "Fail")
        k1, m1, agg1 = c.correlate(src, sim, "a")
        k2, m2, agg2 = c.correlate(src, sim, "a")
        assert k1 == k2 and not agg1 and not agg2  # exact dedup identity
        k3, _, agg3 = c.correlate(src, sim, "b")
        assert k3 != k1 and not agg3               # distinct message
        k4, m4, agg4 = c.correlate(src, sim, "c")
        assert agg4 and k4 == sim                  # storm -> aggregate
        assert m4.startswith("(combined from similar events)")


# --- pipeline spans + SLIs through a live control plane ----------------------

class TestPipelineObservability:
    @pytest.fixture()
    def cluster(self, server, client):
        from kubernetes_tpu.kubelet.kubelet import Kubelet
        from kubernetes_tpu.scheduler.factory import ConfigFactory

        trace.clear_recent()
        client.create("nodes", mk_node("n1"))
        kubelet = Kubelet(RESTClient.for_server(server), "n1",
                          sync_period=0.2, heartbeat_period=1.0)
        kubelet.start(register=False)
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(batch_size=64).run()
        yield client, factory, sched
        sched.stop()
        factory.stop()
        kubelet.stop()

    def test_spans_and_slis_end_to_end(self, server, cluster):
        client, factory, sched = cluster
        client.create("pods", mk_pod("traced"))
        wait_for(lambda: (client.get("pods", "traced", "default").spec
                          .node_name), msg="pod bound")
        wait_for(lambda: (client.get("pods", "traced", "default").status
                          and client.get("pods", "traced",
                                         "default").status.phase == "Running"),
                 msg="pod running")

        # -- span propagation: pod root -> queue_wait + bind children ------
        root = wait_for(
            lambda: next((s for s in trace.recent_spans("schedule_pod")
                          if s.attrs.get("pod") == "default/traced"), None),
            msg="pod root span")
        names = {c.name for c in root.children}
        assert "queue_wait" in names and "bind" in names
        for c in root.children:
            assert c.parent_id == root.span_id
            assert c.trace_id == root.trace_id
            assert c.end is not None
        # the batch that solved it links back via the batch trace id
        batch_trace = root.attrs.get("batch_trace")
        assert batch_trace
        batch_roots = trace.recent_spans("schedule_batch",
                                         trace_id=batch_trace)
        assert batch_roots
        stage_names = {c.name for c in batch_roots[0].children}
        assert "tensorize" in stage_names and "upload" in stage_names
        assert stage_names & {"compile", "solve"}

        # -- SLI histograms non-empty on the registry ----------------------
        assert METRICS.hist_total(
            "scheduler_e2e_scheduling_latency_seconds") >= 1
        assert METRICS.hist_total("scheduler_pod_queue_wait_seconds") >= 1
        assert METRICS.hist_total("scheduler_informer_delivery_seconds") >= 1
        wait_for(lambda: METRICS.hist_total(
            "kubelet_pod_startup_latency_seconds") >= 1,
            msg="pod startup latency observed")
        assert METRICS.hist_total("scheduler_stage_seconds") >= 3

        # -- /metrics exposition (the per-component debug mux) -------------
        from kubernetes_tpu.utils.debugserver import DebugServer
        import http.client as hc
        dbg = DebugServer(port=0).start()
        try:
            conn = hc.HTTPConnection("127.0.0.1", dbg.port, timeout=5)
            conn.request("GET", "/metrics")
            body = conn.getresponse().read().decode()
            conn.close()
        finally:
            dbg.stop()
        for series in ("scheduler_e2e_scheduling_latency_seconds_bucket",
                       "kubelet_pod_startup_latency_seconds_bucket",
                       "scheduler_pod_queue_wait_seconds_bucket",
                       "scheduler_stage_seconds_bucket",
                       "informer_watch_lag_seconds"):
            assert series in body, f"{series} missing from /metrics"

        # -- events visible through kubectl --------------------------------
        from kubernetes_tpu.kubectl import cmd as kubectl
        out = io.StringIO()
        with redirect_stdout(out):
            rc = kubectl.main(["-s", f"127.0.0.1:{server.port}",
                               "get", "events"])
        assert rc == 0
        wait_for(lambda: "Scheduled" in _kubectl_out(server, "get", "events"),
                 msg="Scheduled event via kubectl")
        desc = _kubectl_out(server, "describe", "pod", "traced")
        assert "Events:" in desc and "Scheduled" in desc

    def test_workqueue_slis(self, server, client):
        """A named controller workqueue exports depth + latency series."""
        from kubernetes_tpu.controllers.replication_controller import (
            ReplicationManager,
        )
        mgr = ReplicationManager(client, workers=1)
        mgr.start()
        try:
            rc = api.ReplicationController(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ReplicationControllerSpec(
                    replicas=2, selector={"app": "web"},
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels={"app": "web"}),
                        spec=api.PodSpec(containers=[
                            api.Container(name="c", image="pause")]))))
            client.create("replicationcontrollers", rc)
            wait_for(lambda: len(client.list("pods", "default")[0]) == 2,
                     msg="RC created pods")
            wait_for(lambda: METRICS.hist_total(
                "workqueue_queue_latency_seconds") >= 1,
                msg="workqueue latency observed")
            wait_for(lambda: METRICS.hist_total(
                "workqueue_work_duration_seconds") >= 1,
                msg="workqueue work duration observed")
            assert "replication" in {
                dict(lk).get("queue") for lk in METRICS.hist_stats(
                    "workqueue_queue_latency_seconds")}
            # the controller's creations surfaced as events on the RC
            wait_for(lambda: any(
                e.reason == "SuccessfulCreate"
                for e in client.list("events", "default")[0]),
                msg="SuccessfulCreate event")
        finally:
            mgr.stop()


def _kubectl_out(server, *argv) -> str:
    from kubernetes_tpu.kubectl import cmd as kubectl
    out = io.StringIO()
    with redirect_stdout(out):
        kubectl.main(["-s", f"127.0.0.1:{server.port}", *argv])
    return out.getvalue()


# --- stage watchdogs ---------------------------------------------------------

class TestStageWatchdog:
    def test_hang_converts_to_stage_timeout(self):
        from kubernetes_tpu.ops.watchdog import StageTimeout, run_stages
        before = METRICS.counter_value("scheduler_stage_timeout_total",
                                       stage="upload")
        t0 = time.monotonic()
        with pytest.raises(StageTimeout) as ei:
            run_stages(lambda stage: stage("upload",
                                           lambda: time.sleep(30)),
                       deadlines={"upload": 0.3})
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0  # structured error within the deadline, no wedge
        assert ei.value.stage == "upload"
        assert "upload" in str(ei.value) and "deadline" in str(ei.value)
        assert METRICS.counter_value("scheduler_stage_timeout_total",
                                     stage="upload") == before + 1

    def test_stage_timeout_is_transient_for_classifier(self):
        from kubernetes_tpu.ops.watchdog import StageTimeout
        from kubernetes_tpu.scheduler.tpu import _is_device_error
        assert _is_device_error(StageTimeout("solve", 1.0))

    def test_abandoned_stage_leaves_mirror_lock_free(self):
        """A timed-out (abandoned) device stage must not strand the
        incremental mirror's lock: cache listeners block on it under the
        SchedulerCache lock, so a stranded lock would deadlock the whole
        informer pipeline — worse than the hang being converted."""
        from kubernetes_tpu.ops import watchdog
        from kubernetes_tpu.ops.incremental import IncrementalTensorizer
        inc = IncrementalTensorizer()
        inc._upload_staged = lambda plan, device=None: time.sleep(60)
        with pytest.raises(watchdog.StageTimeout):
            watchdog.run_stages(lambda stage: inc.schedule([], stage=stage),
                                deadlines={"upload": 0.3})
        assert inc._lock.acquire(timeout=2.0), \
            "mirror lock stranded by the abandoned upload worker"
        inc._lock.release()

    def test_injected_kernel_hang_falls_back(self, server, client):
        """A hang inside a kernel stage must not wedge the batch: the
        watchdog converts it to a StageTimeout, the timeout metric ticks,
        and the drained batch completes via the sequential fallback."""
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        client.create("nodes", mk_node("n1"))
        factory = ConfigFactory(client)
        factory.run()
        sched = factory.create_batch_from_provider(
            batch_size=16, stage_deadlines={"tensorize": 0.3})
        before = METRICS.counter_value("scheduler_stage_timeout_total",
                                       stage="tensorize")

        def hang_schedule(pending, weights=None, device=None, stage=None,
                          **kw):
            return stage("tensorize", lambda: time.sleep(60))
        sched._inc.schedule = hang_schedule
        try:
            client.create("pods", mk_pod("survivor"))
            wait_for(lambda: len(factory.pending) >= 1, msg="pod queued")
            t0 = time.monotonic()
            n = sched.schedule_batch_once(timeout=5)
            assert n == 1
            assert time.monotonic() - t0 < 5.0
            assert METRICS.counter_value(
                "scheduler_stage_timeout_total",
                stage="tensorize") == before + 1
            assert sched.kernel_failures == 1
            # fell back sequentially: the pod still lands
            wait_for(lambda: client.get("pods", "survivor",
                                        "default").spec.node_name == "n1",
                     msg="fallback bound the pod")
        finally:
            sched.stop()
            factory.stop()


# --- compile-cache fingerprinting --------------------------------------------

class TestCompileCacheVisibility:
    def test_fingerprinted_dir_and_hit_miss_events(self, tmp_path):
        import jax

        from kubernetes_tpu.utils import platform as plat
        root = str(tmp_path / "xla")
        os.makedirs(root)
        # a legacy (pre-fingerprint) artifact in the root is rejected
        with open(os.path.join(root, "stale-aot-entry"), "w") as f:
            f.write("x")
        saved_dir = dict(plat._CACHE_STATE)
        try:
            d = plat.enable_persistent_compilation_cache(root)
            fp = plat.machine_fingerprint()
            assert os.path.basename(d) == fp
            assert os.path.exists(os.path.join(d, "MACHINE_FEATURES"))
            rejected = METRICS.counter_series("compile_cache_events_total")
            assert any(dict(lk).get("event") == "rejected" and v >= 1
                       for lk, v in rejected.items())

            # empty cache (marker only): nothing to hit -> "uncached"
            before = plat.compile_cache_snapshot()
            assert plat.record_compile_cache_event(before) == "uncached"
            # unchanged NON-EMPTY dir between snapshot and record -> hit
            with open(os.path.join(d, "seeded-entry"), "w") as f:
                f.write("x")
            before = plat.compile_cache_snapshot()
            assert plat.record_compile_cache_event(before) == "hit"
            # a new entry appeared -> miss
            before = plat.compile_cache_snapshot()
            with open(os.path.join(d, "new-entry"), "w") as f:
                f.write("x")
            assert plat.record_compile_cache_event(before) == "miss"
            series = METRICS.counter_series("compile_cache_events_total")
            labels = [dict(lk) for lk in series]
            assert all("fingerprint" in d2 for d2 in labels)
            assert {"hit", "miss"} <= {d2["event"] for d2 in labels}
        finally:
            plat._CACHE_STATE.update(saved_dir)
            jax.config.update("jax_compilation_cache_dir", None)

    def test_disabled_cache_is_visible(self):
        from kubernetes_tpu.utils import platform as plat
        saved = dict(plat._CACHE_STATE)
        plat._CACHE_STATE.update({"dir": "", "fingerprint": ""})
        try:
            assert plat.record_compile_cache_event(None) == "disabled"
        finally:
            plat._CACHE_STATE.update(saved)


# --- round-5 hardening satellites --------------------------------------------

class TestHardeningSatellites:
    def test_federation_probe_not_self_sustaining(self):
        """Status-only cluster updates (our own probe writes) must NOT
        re-enqueue; spec changes must."""
        from kubernetes_tpu.apis import federation as fedapi
        from kubernetes_tpu.federation.controller import (
            ClusterHealthController,
        )
        ctl = ClusterHealthController(RESTClient(), probe_period=5.0)
        try:
            def cluster(addr, ready):
                return fedapi.Cluster(
                    metadata=api.ObjectMeta(name="c1"),
                    spec=fedapi.ClusterSpec(server_address=addr),
                    status=fedapi.ClusterStatus(conditions=[
                        fedapi.ClusterCondition(
                            type=fedapi.CLUSTER_READY,
                            status="True" if ready else "False")]))
            # status flip only: no enqueue (the old self-sustaining loop)
            ctl._cluster_changed(cluster("127.0.0.1:1", True),
                                 cluster("127.0.0.1:1", False))
            assert len(ctl.queue) == 0
            # spec change: enqueue
            ctl._cluster_changed(cluster("127.0.0.1:1", True),
                                 cluster("127.0.0.1:2", True))
            wait_for(lambda: len(ctl.queue) == 1, timeout=2,
                     msg="spec change enqueued")
        finally:
            ctl.queue.shutdown()

    def test_route_controller_reclaims_cidr_on_patch_failure(self):
        from kubernetes_tpu.controllers.route_controller import (
            RouteController,
        )

        class FailingClient:
            def __init__(self):
                self.fail_code = 422

            def patch(self, *a, **kw):
                if self.fail_code:
                    raise ApiError(self.fail_code, "Boom", "injected")

        class FakeCloud:
            def __init__(self):
                self.routes = {}

            def list_routes(self):
                return dict(self.routes)

            def create_route(self, name, cidr):
                self.routes[name] = cidr

            def delete_route(self, name):
                self.routes.pop(name, None)

        fc = FailingClient()
        ctl = RouteController.__new__(RouteController)
        ctl.client = fc
        ctl.cloud = FakeCloud()
        import ipaddress
        ctl.net = ipaddress.ip_network("10.244.0.0/16")
        ctl.node_mask = 24
        ctl._cidr_lock = threading.Lock()
        ctl._issued = {}

        class Store:
            def __init__(self):
                self.nodes = {}

            def get(self, key):
                return self.nodes.get(key)

            def list(self):
                return list(self.nodes.values())

        class Inf:
            store = Store()
        ctl.node_informer = Inf()
        Inf.store.nodes["n1"] = api.Node(
            metadata=api.ObjectMeta(name="n1"), spec=api.NodeSpec())

        with pytest.raises(ApiError):
            ctl.sync("n1")
        assert ctl._issued == {}, \
            "definite 4xx rejection must reclaim the CIDR"
        # ambiguous failure (5xx: the write may have landed server-side)
        # keeps the guard entry, and the retry reuses the SAME subnet
        # instead of leaking one per attempt
        fc.fail_code = 500
        with pytest.raises(ApiError):
            ctl.sync("n1")
        assert ctl._issued == {"10.244.0.0/24": "n1"}
        fc.fail_code = 0
        ctl.sync("n1")
        # the guarded first subnet was handed out again, not leaked
        assert list(ctl._issued) == ["10.244.0.0/24"]
        # node deletion prunes its issued entries
        del Inf.store.nodes["n1"]
        ctl.sync("n1")
        assert ctl._issued == {}

    def test_volume_manager_resolves_pvc_outside_lock(self, tmp_path):
        from kubernetes_tpu.volume import VolumeManager
        vm = VolumeManager(str(tmp_path / "kubelet"))

        class Resolver:
            def __init__(self, vm):
                self.vm = vm
                self.lock_was_free = None

            def get(self, resource, name, ns=""):
                free = self.vm._lock.acquire(blocking=False)
                if free:
                    self.vm._lock.release()
                self.lock_was_free = free
                if resource == "persistentvolumeclaims":
                    return api.PersistentVolumeClaim(
                        metadata=api.ObjectMeta(name=name, namespace=ns),
                        spec=api.PersistentVolumeClaimSpec(
                            volume_name="pv1"))
                return api.PersistentVolume(
                    metadata=api.ObjectMeta(name=name),
                    spec=api.PersistentVolumeSpec(
                        host_path=api.HostPathVolumeSource(
                            path=str(tmp_path / "pv-data"))))

        vm.resolver = Resolver(vm)
        pod = api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="default"),
            spec=api.PodSpec(
                volumes=[api.Volume(
                    name="data",
                    persistent_volume_claim=api.
                    PersistentVolumeClaimVolumeSource(claim_name="cl"))],
                containers=[api.Container(
                    name="c", image="pause",
                    volume_mounts=[api.VolumeMount(name="data",
                                                   mount_path="/data")])]))
        views = vm.setup_pod(pod)
        assert vm.resolver.lock_was_free is True, \
            "PVC resolution must not run under the manager-wide lock"
        assert views["c"]["/data"] == str(tmp_path / "pv-data")
        assert vm.mounted("default/p")

    def test_tls_skip_verify_is_explicit_and_counted(self):
        class SecureStub:
            secure = True
            port = 1

        c = RESTClient.for_server(SecureStub())
        assert c.tls and not c.insecure_skip_verify, \
            "secure server must no longer imply skip-verify"
        before = METRICS.counter_value("tls_insecure_connections")
        insecure = RESTClient(tls=True, insecure_skip_verify=True)
        insecure._new_conn(1.0)
        assert METRICS.counter_value("tls_insecure_connections") == before + 1


# --- ISSUE 10: exposition round trip + scraper delta math --------------------

class TestScraper:
    def test_render_parse_round_trip(self):
        """render() output — escaped labels, # HELP, canonical le bounds —
        parses back losslessly."""
        from kubernetes_tpu.observability.scrape import parse_prometheus_text
        from kubernetes_tpu.utils.metrics import HELP, MetricsRegistry

        r = MetricsRegistry()
        nasty = 'a\\b"c\nd'
        r.inc("rt_total", 3, path=nasty, verb="GET")
        r.set_gauge("rt_gauge", 2.5)
        for v in (0.003, 0.003, 0.05, 1.7):
            r.observe("rt_seconds", v, stage="solve")
        HELP["rt_total"] = 'round "trip" help\nwith newline'
        try:
            text = r.render()
            fams = parse_prometheus_text(text)
        finally:
            HELP.pop("rt_total", None)

        assert fams["rt_total"].type == "counter"
        assert fams["rt_total"].help == 'round "trip" help\nwith newline'
        assert fams["rt_total"].value(path=nasty, verb="GET") == 3.0
        assert fams["rt_gauge"].value() == 2.5

        h = fams["rt_seconds"].histogram(stage="solve")
        assert h is not None and h.count == 4 and abs(h.sum - 1.756) < 1e-9
        # cumulative bucket counts survive, and the parsed-side quantile
        # agrees with the registry-side estimator
        assert h.buckets[0.004] == 2 and h.buckets[float("inf")] == 4
        reg_q = r.histogram("rt_seconds").quantile(0.5, stage="solve")
        assert h.quantile(0.5) == reg_q

    def test_brace_in_label_value_round_trips(self):
        """'}' is legal (unescaped) inside a quoted label value — the
        parser must track quote state, not stop at the first brace."""
        from kubernetes_tpu.observability.scrape import parse_prometheus_text
        from kubernetes_tpu.utils.metrics import MetricsRegistry
        r = MetricsRegistry()
        r.inc("brace_total", 2, err="KeyError('{oops}')")
        fams = parse_prometheus_text(r.render())
        assert fams["brace_total"].value(err="KeyError('{oops}')") == 2.0

    def test_nan_sample_does_not_crash_render(self):
        """A NaN gauge/observation must render as 'NaN' (and parse back),
        never crash every subsequent /metrics scrape."""
        import math

        from kubernetes_tpu.observability.scrape import parse_prometheus_text
        from kubernetes_tpu.utils.metrics import MetricsRegistry
        r = MetricsRegistry()
        r.set_gauge("bad_gauge", float("nan"))
        r.observe("bad_seconds", float("nan"))
        text = r.render()  # must not raise
        fams = parse_prometheus_text(text)
        assert math.isnan(fams["bad_gauge"].value())

    def test_le_bounds_are_canonical(self):
        """Every le value in the exposition must re-parse to exactly the
        bucket bound it was rendered from (no 0.016000000000000001 drift)."""
        import re

        from kubernetes_tpu.utils.metrics import (
            SCHEDULER_BUCKETS, MetricsRegistry,
        )
        r = MetricsRegistry()
        r.observe("le_seconds", 0.01)
        les = re.findall(r'le="([^"]+)"', r.render())
        parsed = [float(x) for x in les if x != "+Inf"]
        assert parsed == sorted(SCHEDULER_BUCKETS)

    def test_empty_histogram_quantile_is_nan(self):
        """No samples != zero latency: empty series quantiles are NaN, and
        bench's JSON formatter turns them into null."""
        import math

        from bench import _finite, _max_finite
        from kubernetes_tpu.observability.scrape import HistogramSnapshot
        from kubernetes_tpu.utils.metrics import Histogram, MetricsRegistry

        assert math.isnan(Histogram("empty").quantile(0.99))
        assert math.isnan(HistogramSnapshot().quantile(0.5))
        r = MetricsRegistry()
        snap = r.hist_snapshot("never_observed")
        assert math.isnan(r.delta_quantile("never_observed", snap, 0.99))
        assert _finite(float("nan")) is None
        # max over per-verb quantiles must skip empty series, not poison
        assert _max_finite([float("nan"), 0.25, 0.5]) == 0.5
        assert _finite(_max_finite([float("nan")])) is None

    @staticmethod
    def _text(**counters):
        lines = []
        for name, v in counters.items():
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {v}")
        return "\n".join(lines) + "\n"

    def test_counter_delta_windows_and_reset(self):
        from kubernetes_tpu.observability.scrape import Scraper
        s = Scraper()
        s.add_target("t", "127.0.0.1", 1)  # never fetched: ingest directly
        s.ingest("t", self._text(c_total=0), ts=0.0)
        s.ingest("t", self._text(c_total=50), ts=10.0)
        # adjacent-round delta and windowed rate
        assert s.counter_delta("t", "c_total") == 50
        assert s.counter_rate("t", "c_total", 10.0) == pytest.approx(5.0)
        # a counter that went BACKWARDS is an exporter restart: the delta
        # restarts from the new value instead of going negative
        s.ingest("t", self._text(c_total=7), ts=20.0)
        assert s.counter_delta("t", "c_total") == 7
        # unknown family: explicit NaN, not zero
        import math
        assert math.isnan(s.counter_delta("t", "nope_total"))

    def test_window_covers_at_least_the_period(self):
        """A round landing epsilon past the cutoff (scrape jitter) must not
        shrink a one-period window to nothing."""
        from kubernetes_tpu.observability.scrape import Scraper
        s = Scraper()
        s.add_target("t", "127.0.0.1", 1)
        s.ingest("t", self._text(c_total=0), ts=0.0)
        s.ingest("t", self._text(c_total=10), ts=1.01)  # 1s period + jitter
        s.ingest("t", self._text(c_total=30), ts=2.02)
        # the 1s window reaches back to the round AT-or-before the cutoff
        assert s.counter_delta("t", "c_total", 1.0) == 20
        assert s.counter_rate("t", "c_total", 1.0) == pytest.approx(
            20 / 1.01, rel=1e-6)

    def test_histogram_window_delta(self):
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.utils.metrics import MetricsRegistry
        r = MetricsRegistry()
        s = Scraper()
        s.add_target("t", "127.0.0.1", 1)
        r.observe("h_seconds", 0.002)
        s.ingest("t", r.render(), ts=0.0)
        for v in (0.01, 0.01, 0.3):
            r.observe("h_seconds", v)
        s.ingest("t", r.render(), ts=5.0)
        d = s.hist_delta("t", "h_seconds")
        assert d.count == 3  # the pre-window observation is excluded
        assert d.quantile(0.5) == 0.016  # 2 of 3 at 0.01 -> bucket 0.016
        assert s.hist_rate("t", "h_seconds", 5.0) == pytest.approx(0.6)

    def test_http_scrape_against_debugserver(self):
        """The live path: DebugServer /metrics -> Scraper -> deltas, with a
        scrape failure visible as an error round, not an exception."""
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.utils.debugserver import DebugServer
        dbg = DebugServer(port=0).start()
        try:
            s = Scraper()
            s.add_target("comp", "127.0.0.1", dbg.port)
            METRICS.inc("scrape_live_total", origin="test")
            assert s.scrape()["comp"].error is None
            METRICS.inc("scrape_live_total", 4, origin="test")
            s.scrape()
            assert s.counter_delta("comp", "scrape_live_total",
                                   origin="test") == 4
        finally:
            dbg.stop()
        before = METRICS.counter_value("observability_scrape_total",
                                       target="comp", outcome="error")
        rnd = s.scrape()["comp"]  # server is gone now
        assert rnd.error is not None
        assert METRICS.counter_value("observability_scrape_total",
                                     target="comp",
                                     outcome="error") == before + 1


# --- ISSUE 10: SLO burn-rate engine ------------------------------------------

class _Recorder:
    def __init__(self):
        self.events = []

    def event(self, obj, etype, reason, message):
        self.events.append((obj.metadata.name, etype, reason, message))


class TestSLOEngine:
    E2E = "scheduler_e2e_scheduling_latency_seconds"

    def _scraper_with_rates(self, points):
        """points: [(ts, counter value)] ingested as rounds."""
        from kubernetes_tpu.observability.scrape import Scraper
        s = Scraper()
        s.add_target("sched", "127.0.0.1", 1)
        for ts, v in points:
            s.ingest("sched",
                     f"# TYPE work_total counter\nwork_total {v}\n", ts=ts)
        return s

    def test_burn_rate_arithmetic(self):
        from kubernetes_tpu.observability.slo import SLOEngine
        import math
        # max bound (latency): burn = sli / objective
        assert SLOEngine.burn_rate(2.0, 1.0, "max") == 2.0
        assert SLOEngine.burn_rate(0.5, 1.0, "max") == 0.5
        # min bound (throughput): burn = objective / sli; zero burns forever
        assert SLOEngine.burn_rate(50.0, 100.0, "min") == 2.0
        assert SLOEngine.burn_rate(0.0, 100.0, "min") == float("inf")
        # no data propagates as NaN, never as 0-burn
        assert math.isnan(SLOEngine.burn_rate(float("nan"), 1.0, "max"))
        # but an INFINITE latency SLI (beyond the top bucket) is the worst
        # violation, not missing data: it burns infinitely
        assert SLOEngine.burn_rate(float("inf"), 1.0, "max") == float("inf")
        assert SLOEngine.burn_rate(float("inf"), 100.0, "min") == 0.0

    def test_beyond_bucket_latency_is_burning_not_no_data(self):
        """p99 past the last histogram bucket -> inf SLI -> burning."""
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        from kubernetes_tpu.utils.metrics import MetricsRegistry
        r = MetricsRegistry()
        s = Scraper()
        s.add_target("sched", "127.0.0.1", 1)
        s.ingest("sched", r.render(), ts=0.0)
        for _ in range(5):
            r.observe("slow_seconds", 1000.0)  # past every bucket
        s.ingest("sched", r.render(), ts=10.0)
        spec = SLOSpec(name="lat", target="sched", sli="quantile",
                       metric="slow_seconds", quantile=0.99, objective=1.0,
                       windows=(Window(10.0, 1.0),))
        res = SLOEngine(s, [spec]).evaluate()
        assert res[0].verdict == "burning"

    def test_short_spike_does_not_fire(self):
        """Multi-window gating: the LONG window must also be out of budget
        before the verdict is burning."""
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        # 1/s for 10s, then 10/s for the last 2s: the short window is fine
        # while the long window average (2.5/s) violates a >=5/s objective
        s = self._scraper_with_rates([(0, 0), (10, 10), (12, 30)])
        spec = SLOSpec(name="tput", target="sched", sli="rate",
                       metric="work_total", objective=5.0, bound="min",
                       windows=(Window(12.0, 1.0), Window(2.0, 1.0)))
        res = SLOEngine(s, [spec]).evaluate_one(spec)
        # long window burning (2.5/s < 5/s) but short window healthy
        assert res.windows[0].burn > 1.0 and res.windows[1].burn <= 1.0
        assert res.verdict == "ok"

    def test_sustained_burn_fires_and_recovers_with_events(self):
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        rec = _Recorder()
        s = self._scraper_with_rates([(0, 0), (10, 10), (12, 12)])  # 1/s
        spec = SLOSpec(name="tput", target="sched", sli="rate",
                       metric="work_total", objective=5.0, bound="min",
                       windows=(Window(12.0, 1.0), Window(2.0, 1.0)))
        engine = SLOEngine(s, [spec], recorder=rec)
        res = engine.evaluate()
        assert res[0].verdict == "burning"
        assert METRICS.counter_value("slo_violations_total", slo="tput") >= 1
        assert rec.events and rec.events[-1][2] == "SLOViolation"
        # recovery: rate jumps to 20/s in both windows
        s.ingest("sched", "# TYPE work_total counter\nwork_total 252\n",
                 ts=24.0)
        s.ingest("sched", "# TYPE work_total counter\nwork_total 292\n",
                 ts=26.0)
        res = engine.evaluate()
        assert res[0].verdict == "ok"
        assert rec.events[-1][2] == "SLORecovered"

    def test_recovery_survives_no_data_gap(self):
        """burning -> (scrape outage: no_data) -> ok must still post
        SLORecovered — a dangling SLOViolation never closes otherwise."""
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        rec = _Recorder()
        s = Scraper()
        s.add_target("sched", "127.0.0.1", 1)
        spec = SLOSpec(name="tput", target="sched", sli="rate",
                       metric="work_total", objective=5.0, bound="min",
                       windows=(Window(10.0, 1.0),))
        engine = SLOEngine(s, [spec], recorder=rec)
        s.ingest("sched", "# TYPE work_total counter\nwork_total 0\n", ts=0)
        s.ingest("sched", "# TYPE work_total counter\nwork_total 10\n",
                 ts=10)
        assert engine.evaluate()[0].verdict == "burning"
        # outage round: family missing entirely -> no_data
        s.ingest("sched", "", ts=12)
        s.ingest("sched", "", ts=14)
        assert engine.evaluate()[0].verdict == "no_data"
        # recovered at 20/s
        s.ingest("sched", "# TYPE work_total counter\nwork_total 210\n",
                 ts=20)
        s.ingest("sched", "# TYPE work_total counter\nwork_total 260\n",
                 ts=22)
        assert engine.evaluate()[0].verdict == "ok"
        assert rec.events[-1][2] == "SLORecovered"

    def test_empty_windows_is_no_data_not_burning(self):
        from kubernetes_tpu.observability.slo import SLOEngine, SLOSpec
        s = self._scraper_with_rates([(0, 0), (10, 10)])
        spec = SLOSpec(name="cfg", target="sched", sli="rate",
                       metric="work_total", objective=5.0, bound="min",
                       windows=())
        assert SLOEngine(s, [spec]).evaluate()[0].verdict == "no_data"

    def test_no_data_is_explicit(self):
        """An SLI over a never-observed series is no_data — not ok (a dead
        exporter must not read as a met objective) and not burning."""
        from kubernetes_tpu.observability.slo import (
            SLOEngine, SLOSpec, Window,
        )
        s = self._scraper_with_rates([(0, 0), (10, 10)])
        spec = SLOSpec(name="lat", target="sched", sli="quantile",
                       metric=self.E2E, quantile=0.99, objective=1.0,
                       windows=(Window(10.0, 1.0),))
        res = SLOEngine(s, [spec]).evaluate()
        assert res[0].verdict == "no_data"
        assert res[0].windows[0].as_dict()["sli"] is None


# --- ISSUE 10: churn soak harness --------------------------------------------

class TestSoakHarness:
    def test_soak_smoke_steady_state_from_scrape(self):
        """Tier-1 smoke: sustained create/bind/delete against hollow nodes;
        steady-state pods/s and p50/p99 computed from SCRAPED deltas; SLOs
        evaluated; kernel device/host split exported; not wedged."""
        from kubernetes_tpu.observability.scrape import Scraper
        from kubernetes_tpu.observability.soak import SoakConfig, run_soak

        scraper = Scraper()
        cfg = SoakConfig(num_nodes=6, create_rate=30, duration_seconds=2.5,
                         scrape_period=0.8, batch_size=32,
                         heartbeat_period=2.0, drain_timeout=20,
                         slo_e2e_p99_seconds=30.0, slo_watch_lag_seconds=30.0)
        report = run_soak(cfg, scraper=scraper)
        assert report.get("error") is None, report
        assert report["wedged"] is False
        assert report["pods_created"] > 0
        assert report["pods_bound"] > 0
        steady = report["steady_state"]
        assert steady["pods_per_sec"] is not None and steady["pods_per_sec"] > 0
        assert steady["e2e_p50_seconds"] is not None
        assert report["rounds"], "no scrape rounds recorded"
        verdicts = {s["name"]: s["verdict"] for s in report["slos"]}
        assert set(verdicts) == {"pods-per-sec", "schedule-e2e-p99",
                                 "informer-watch-lag"}
        # the SLIs came from the exported surface, and the device profiling
        # split rode along on the same scrape
        last = scraper.last("scheduler")
        assert last is not None and not last.error
        fam = last.families.get("scheduler_kernel_device_seconds")
        assert fam is not None, "host/device split missing from /metrics"
        comps = {dict(lk).get("component") for lk in fam.histograms}
        assert {"host", "device"} <= comps
        # kubemark exported its fleet gauge on the same surface
        assert last.families.get("kubemark_hollow_nodes") is not None

    def test_seeded_stage_hang_ends_wedged_not_hung(self):
        """The BENCH_r05 regression proof: a kernel stage that hangs every
        batch must end the soak with wedged=true + the stage named (binding
        still completing via the sequential fallback), never a 600s wedge
        and never success-shaped output."""
        from kubernetes_tpu.observability.soak import SoakConfig, run_soak

        cfg = SoakConfig(num_nodes=4, create_rate=20, duration_seconds=2.0,
                         scrape_period=0.8, batch_size=16,
                         heartbeat_period=2.0, drain_timeout=20,
                         hang_stage="tensorize")
        t0 = time.monotonic()
        report = run_soak(cfg)
        assert time.monotonic() - t0 < 90, "soak failed to bound the hang"
        assert report["wedged"] is True
        assert "tensorize" in report.get("stage_timeouts", {})
        # the fallback kept scheduling: a wedge is visible, not fatal
        assert report["pods_bound"] > 0
        assert report["kernel"]["failures"] >= 1


# --- ISSUE 10: /profilez + device profiling ----------------------------------

class TestProfilez:
    def _get(self, port, path):
        import http.client as hc
        import json as _json
        # generous: the FIRST /profilez/start pays the jax.profiler import
        # inside the handler thread
        conn = hc.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, _json.loads(resp.read().decode())
        finally:
            conn.close()

    def test_profilez_trace_window_round_trip(self, tmp_path):
        """status -> start -> (device work) -> stop over the live debug mux;
        double-start and stop-while-idle are 409s, not crashes."""
        import jax.profiler  # noqa: F401 — warm the import off the handler

        from kubernetes_tpu.utils.debugserver import DebugServer
        dbg = DebugServer(port=0).start()
        try:
            code, body = self._get(dbg.port, "/profilez")
            assert code == 200 and body == {"active": False}
            code, body = self._get(
                dbg.port, f"/profilez/start?dir={tmp_path / 'trace'}")
            assert code == 200 and body["active"] is True
            code, _ = self._get(dbg.port, "/profilez/start")
            assert code == 409  # one window at a time
            import jax.numpy as jnp
            jnp.asarray([1.0, 2.0]).sum().block_until_ready()
            code, body = self._get(dbg.port, "/profilez/stop")
            assert code == 200 and body["active"] is False
            assert body["dir"] == str(tmp_path / "trace")
            code, _ = self._get(dbg.port, "/profilez/stop")
            assert code == 409
        finally:
            dbg.stop()
            from kubernetes_tpu.observability import profiling
            if profiling.profile_status().get("active"):
                profiling.stop_profile()

    def test_stage_annotation_is_noop_safe(self):
        from kubernetes_tpu.observability.profiling import annotate
        with annotate("ktpu:test-stage"):
            pass  # must never raise, profiler or not
