"""API group coverage: extensions/batch/autoscaling/apps/policy/rbac types,
group routing under /apis/<group>/<version>, and the scale/rollback
subresources (reference pkg/apis/* + extensions Scale registry)."""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict
from kubernetes_tpu.apis import apps, autoscaling, batch, extensions as ext, policy, rbac
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.registry.generic import Registry, RegistryError


def _tpl(labels):
    return api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels=dict(labels)),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")],
                         restart_policy="Never"))


def _deployment(name="web", replicas=3):
    return ext.Deployment(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=ext.DeploymentSpec(
            replicas=replicas,
            selector=api.LabelSelector(match_labels={"app": name}),
            template=_tpl({"app": name}),
            strategy=ext.DeploymentStrategy(
                type=ext.ROLLING_UPDATE,
                rolling_update=ext.RollingUpdateDeployment(
                    max_unavailable=1, max_surge="25%"))))


class TestSchemeRoundTrip:
    @pytest.mark.parametrize("obj,gv,kind", [
        (_deployment(), "extensions/v1beta1", "Deployment"),
        (ext.DaemonSet(metadata=api.ObjectMeta(name="d"),
                       spec=ext.DaemonSetSpec(template=_tpl({"a": "b"}))),
         "extensions/v1beta1", "DaemonSet"),
        (ext.Ingress(metadata=api.ObjectMeta(name="i"),
                     spec=ext.IngressSpec(rules=[ext.IngressRule(
                         host="x.test", http=ext.HTTPIngressRuleValue(paths=[
                             ext.HTTPIngressPath(path="/", backend=ext.IngressBackend(
                                 service_name="s", service_port=80))]))])),
         "extensions/v1beta1", "Ingress"),
        (batch.Job(metadata=api.ObjectMeta(name="j"),
                   spec=batch.JobSpec(completions=2, parallelism=2,
                                      template=_tpl({"job": "j"}))),
         "batch/v1", "Job"),
        (batch.ScheduledJob(metadata=api.ObjectMeta(name="sj"),
                            spec=batch.ScheduledJobSpec(
                                schedule="*/5 * * * *",
                                job_template=batch.JobTemplateSpec(
                                    spec=batch.JobSpec(template=_tpl({"x": "y"}))))),
         "batch/v2alpha1", "ScheduledJob"),
        (autoscaling.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name="h"),
            spec=autoscaling.HorizontalPodAutoscalerSpec(
                scale_target_ref=autoscaling.CrossVersionObjectReference(
                    kind="ReplicationController", name="rc"),
                min_replicas=1, max_replicas=10,
                target_cpu_utilization_percentage=80)),
         "autoscaling/v1", "HorizontalPodAutoscaler"),
        (apps.PetSet(metadata=api.ObjectMeta(name="p"),
                     spec=apps.PetSetSpec(replicas=2, service_name="svc",
                                          template=_tpl({"p": "s"}))),
         "apps/v1alpha1", "PetSet"),
        (policy.PodDisruptionBudget(
            metadata=api.ObjectMeta(name="b"),
            spec=policy.PodDisruptionBudgetSpec(min_available="50%")),
         "policy/v1alpha1", "PodDisruptionBudget"),
        (rbac.ClusterRole(metadata=api.ObjectMeta(name="admin"),
                          rules=[rbac.PolicyRule(verbs=["*"], api_groups=["*"],
                                                 resources=["*"])]),
         "rbac.authorization.k8s.io/v1alpha1", "ClusterRole"),
    ])
    def test_round_trip(self, obj, gv, kind):
        d = scheme.encode(obj)
        assert d["apiVersion"] == gv and d["kind"] == kind
        back = scheme.decode(d)
        assert to_dict(back) == to_dict(obj)

    def test_camel_case_wire_names(self):
        d = to_dict(_deployment())
        assert "rollingUpdate" in d["spec"]["strategy"]
        assert d["spec"]["strategy"]["rollingUpdate"]["maxSurge"] == "25%"

    def test_core_additions_round_trip(self):
        s = api.Secret(metadata=api.ObjectMeta(name="tok", namespace="default"),
                       data={"token": "YWJj"},
                       type=api.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN)
        assert scheme.encode(s)["apiVersion"] == "v1"
        rq = api.ResourceQuota(
            metadata=api.ObjectMeta(name="q"),
            spec=api.ResourceQuotaSpec(hard={"cpu": "10", "pods": "20"}))
        back = from_dict(api.ResourceQuota, to_dict(rq))
        assert back.spec.hard == {"cpu": "10", "pods": "20"}


class TestGroupRegistry:
    def test_crud_each_group_resource(self):
        reg = Registry()
        reg.create("deployments", _deployment(), namespace="default")
        got = reg.get("deployments", "web", "default")
        assert got.spec.replicas == 3
        items, _ = reg.list("deployments", "default")
        assert len(items) == 1

        reg.create("clusterroles", rbac.ClusterRole(
            metadata=api.ObjectMeta(name="view"),
            rules=[rbac.PolicyRule(verbs=["get", "list"], resources=["pods"])]))
        assert reg.get("clusterroles", "view").rules[0].verbs == ["get", "list"]

    def test_validation_rejects_bad_objects(self):
        reg = Registry()
        with pytest.raises(RegistryError) as e:
            reg.create("jobs", batch.Job(
                metadata=api.ObjectMeta(name="j", namespace="default"),
                spec=batch.JobSpec(parallelism=-1, template=_tpl({}))))
        assert e.value.code == 422
        with pytest.raises(RegistryError):
            reg.create("horizontalpodautoscalers", autoscaling.HorizontalPodAutoscaler(
                metadata=api.ObjectMeta(name="h", namespace="default"),
                spec=autoscaling.HorizontalPodAutoscalerSpec(max_replicas=0)))
        with pytest.raises(RegistryError):
            reg.create("scheduledjobs", batch.ScheduledJob(
                metadata=api.ObjectMeta(name="s", namespace="default"),
                spec=batch.ScheduledJobSpec(schedule="bogus",
                                            job_template=batch.JobTemplateSpec())))

    def test_scale_subresource(self):
        reg = Registry()
        reg.create("deployments", _deployment(), namespace="default")
        sc = reg.get_scale("deployments", "web", "default")
        assert sc.spec.replicas == 3
        assert sc.status.selector == {"app": "web"}
        sc.spec.replicas = 7
        out = reg.update_scale("deployments", "web", "default", sc)
        assert out.spec.replicas == 7
        assert reg.get("deployments", "web", "default").spec.replicas == 7

    def test_rollback_subresource(self):
        reg = Registry()
        reg.create("deployments", _deployment(), namespace="default")
        reg.rollback_deployment("web", "default", ext.DeploymentRollback(
            name="web", rollback_to=ext.RollbackConfig(revision=2)))
        assert reg.get("deployments", "web", "default").spec.rollback_to.revision == 2


class TestGroupHTTP:
    @pytest.fixture()
    def server(self):
        s = APIServer()
        s.start()
        yield s
        s.stop()

    def test_group_paths_end_to_end(self, server):
        c = RESTClient.for_server(server)
        c.create("deployments", _deployment(), namespace="default")
        got = c.get("deployments", "web", "default")
        assert got.spec.replicas == 3

        # scale through HTTP
        sc = c.get_scale("deployments", "web", "default")
        sc.spec.replicas = 5
        assert c.update_scale("deployments", "web", "default", sc).spec.replicas == 5

        # group resources 404 under the core prefix
        with pytest.raises(ApiError) as e:
            c.request("GET", "/api/v1/namespaces/default/deployments/web")
        assert e.value.code == 404

        # non-namespaced group resource
        c.create("clusterroles", rbac.ClusterRole(
            metadata=api.ObjectMeta(name="edit"),
            rules=[rbac.PolicyRule(verbs=["*"], resources=["pods"])]))
        assert c.get("clusterroles", "edit").metadata.name == "edit"

    def test_discovery_endpoints(self, server):
        c = RESTClient.for_server(server)
        assert "v1" in c.request("GET", "/api")["versions"]
        groups = {g["name"] for g in c.request("GET", "/apis")["groups"]}
        assert {"extensions", "batch", "autoscaling", "apps", "policy"} <= groups

    def test_watch_group_resource(self, server):
        c = RESTClient.for_server(server)
        _, rv = c.list("jobs", "default")
        w = c.watch("jobs", "default", resource_version=rv)
        c.create("jobs", batch.Job(
            metadata=api.ObjectMeta(name="j1", namespace="default"),
            spec=batch.JobSpec(template=_tpl({"job": "j1"}))), namespace="default")
        ev_type, obj = next(iter(w))
        assert ev_type == "ADDED" and obj.metadata.name == "j1"
        w.stop()
