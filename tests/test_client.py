"""Client runtime: RESTClient, caches, Reflector, Informer, listers, events
against a live in-process API server (reference pkg/client/cache tests +
framework controller tests)."""

import threading
import time

import pytest

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.fields import parse_field_selector
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import (
    FIFO, ApiError, DeltaFIFO, Informer, ListWatch, Reflector, RESTClient,
    ThreadSafeStore, meta_namespace_key,
)
from kubernetes_tpu.client.cache import node_name_indexer
from kubernetes_tpu.client.listers import (
    NodeLister, PodLister, ServiceLister, node_is_ready,
)
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.reflector import StoreSink
from kubernetes_tpu.utils.flowcontrol import Backoff, TokenBucket


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=500, burst=500)


def mk_pod(name, ns="default", labels=None, node=""):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=api.PodSpec(node_name=node, containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(requests={"cpu": "100m"}))]))


def mk_node(name, ready=True):
    return api.Node(
        metadata=api.ObjectMeta(name=name),
        status=api.NodeStatus(
            capacity={"cpu": "4", "memory": "8Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready",
                                          status="True" if ready else "False")]))


class TestRESTClient:
    def test_typed_crud(self, client):
        created = client.create("pods", mk_pod("a", labels={"app": "x"}))
        assert isinstance(created, api.Pod) and created.metadata.uid
        got = client.get("pods", "a", "default")
        assert got.metadata.name == "a"
        items, rv = client.list("pods", "default")
        assert len(items) == 1 and rv > 0
        got.metadata.labels = {"app": "y"}
        updated = client.update("pods", got)
        assert updated.metadata.labels == {"app": "y"}
        client.delete("pods", "a", "default")
        with pytest.raises(ApiError) as ei:
            client.get("pods", "a", "default")
        assert ei.value.is_not_found

    def test_selectors(self, client):
        client.create("pods", mk_pod("w", labels={"app": "web"}))
        client.create("pods", mk_pod("d", labels={"app": "db"}))
        items, _ = client.list("pods", "default",
                               label_selector=labelsel.parse_selector("app=web"))
        assert [p.metadata.name for p in items] == ["w"]
        items, _ = client.list("pods", field_selector=parse_field_selector("spec.nodeName="))
        assert len(items) == 2

    def test_bind(self, client):
        client.create("pods", mk_pod("p"))
        client.bind(api.Binding(metadata=api.ObjectMeta(name="p", namespace="default"),
                                target=api.ObjectReference(kind="Node", name="n1")),
                    "default")
        assert client.get("pods", "p", "default").spec.node_name == "n1"

    def test_watch_stream(self, client):
        _, rv = client.list("pods")
        stream = client.watch("pods", resource_version=rv)
        got = []
        t = threading.Thread(target=lambda: [got.append(x) for x in stream])
        t.start()
        client.create("pods", mk_pod("w1"))
        time.sleep(0.3)
        stream.stop()
        t.join(timeout=2)
        assert got and got[0][0] == "ADDED" and got[0][1].metadata.name == "w1"


class TestFlowControl:
    def test_token_bucket_blocks(self):
        # fake clock so the test is deterministic
        now = [0.0]
        tb = TokenBucket(qps=10, burst=2, clock=lambda: now[0])
        assert tb.try_accept() and tb.try_accept()
        assert not tb.try_accept()
        now[0] += 0.1  # one token refilled
        assert tb.try_accept()
        assert not tb.try_accept()

    def test_backoff_doubles_to_cap(self):
        now = [0.0]
        b = Backoff(initial=1.0, maximum=8.0, clock=lambda: now[0])
        assert [b.next("k") for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
        b.reset("k")
        assert b.next("k") == 1.0
        # idle reset
        b.next("k")
        now[0] += 100.0
        assert b.next("k") == 1.0


class TestCaches:
    def test_fifo_blocking_pop(self):
        f = FIFO()
        out = []
        t = threading.Thread(target=lambda: out.append(f.pop(timeout=5)))
        t.start()
        time.sleep(0.05)
        f.add(mk_pod("a"))
        t.join(timeout=2)
        assert out[0].metadata.name == "a"

    def test_fifo_readd_replaces(self):
        f = FIFO()
        f.add(mk_pod("a", labels={"v": "1"}))
        f.add(mk_pod("a", labels={"v": "2"}))
        f.add(mk_pod("b"))
        assert len(f) == 2
        assert f.pop().metadata.labels == {"v": "2"}

    def test_fifo_add_if_not_present(self):
        f = FIFO()
        f.add(mk_pod("a", labels={"v": "1"}))
        f.add_if_not_present(mk_pod("a", labels={"v": "2"}))
        assert f.pop().metadata.labels == {"v": "1"}

    def test_delta_fifo_sequences(self):
        d = DeltaFIFO()
        p = mk_pod("a")
        d.add(p)
        d.update(p)
        d.delete(p)
        key, deltas = d.pop()
        assert key == "default/a"
        assert [t for t, _ in deltas] == ["Added", "Updated", "Deleted"]

    def test_delta_fifo_replace_emits_deletes(self):
        d = DeltaFIFO()
        d.add(mk_pod("a"))
        d.pop()
        d.replace([mk_pod("b")])
        seen = {}
        while len(d):
            key, deltas = d.pop()
            seen[key] = [t for t, _ in deltas]
        assert seen["default/b"] == ["Sync"]
        assert seen["default/a"] == ["Deleted"]

    def test_indexer(self):
        s = ThreadSafeStore(indexers={"node": node_name_indexer})
        s.add("default/a", mk_pod("a", node="n1"))
        s.add("default/b", mk_pod("b", node="n1"))
        s.add("default/c", mk_pod("c", node="n2"))
        assert {p.metadata.name for p in s.by_index("node", "n1")} == {"a", "b"}
        s.delete("default/a")
        assert {p.metadata.name for p in s.by_index("node", "n1")} == {"b"}


class TestReflector:
    def test_list_then_watch(self, server, client):
        client.create("pods", mk_pod("pre"))
        store = ThreadSafeStore()
        refl = Reflector(ListWatch(client, "pods"),
                         StoreSink(store, meta_namespace_key)).run()
        assert refl.wait_for_sync(5)
        assert store.get("default/pre") is not None
        client.create("pods", mk_pod("live"))
        _wait(lambda: store.get("default/live") is not None)
        client.delete("pods", "live", "default")
        _wait(lambda: store.get("default/live") is None)
        refl.stop()

    def test_relist_after_compaction(self, server, client):
        store = ThreadSafeStore()
        refl = Reflector(ListWatch(client, "pods"),
                         StoreSink(store, meta_namespace_key)).run()
        assert refl.wait_for_sync(5)
        # advance rv past the window start, compact, then ask for the old rv:
        # the server must answer 410 Gone (what drives a reflector re-list)
        for i in range(3):
            client.create("pods", mk_pod(f"x{i}"))
        _wait(lambda: store.get("default/x2") is not None)
        server.registry.store.compact()
        with pytest.raises(ApiError) as ei:
            client.watch("pods", resource_version=1)
        assert ei.value.is_gone
        refl.stop()

    def test_unassigned_pod_selector_feed(self, server, client):
        """The scheduler's FIFO feed: spec.nodeName== selector."""
        fifo = FIFO()

        class FIFOSink:
            def replace(self, items):
                for o in items:
                    fifo.add(o)

            def add(self, obj):
                fifo.add(obj)

            update = add

            def delete(self, obj):
                fifo.delete(obj)

        refl = Reflector(ListWatch(client, "pods",
                                   field_selector=parse_field_selector("spec.nodeName=")),
                         FIFOSink()).run()
        assert refl.wait_for_sync(5)
        client.create("pods", mk_pod("pending"))
        client.create("pods", mk_pod("assigned", node="n1"))
        popped = fifo.pop(timeout=5)
        assert popped.metadata.name == "pending"
        assert len(f := fifo) == 0 or fifo.pop(timeout=0.2) is None
        refl.stop()


class TestInformer:
    def test_handlers_and_store(self, server, client):
        client.create("nodes", mk_node("n1"))
        events = []
        inf = Informer(ListWatch(client, "nodes"))
        inf.add_event_handler(
            on_add=lambda o: events.append(("add", o.metadata.name)),
            on_update=lambda old, new: events.append(("update", new.metadata.name)),
            on_delete=lambda o: events.append(("delete", o.metadata.name)))
        inf.run()
        assert inf.wait_for_sync(5)
        client.create("nodes", mk_node("n2"))
        _wait(lambda: inf.store.get("n2") is not None)
        n2 = client.get("nodes", "n2")
        n2.metadata.labels = {"x": "y"}
        client.update("nodes", n2)
        client.delete("nodes", "n2")
        _wait(lambda: inf.store.get("n2") is None)
        _wait(lambda: ("delete", "n2") in events)
        assert ("add", "n1") in events and ("add", "n2") in events
        assert ("update", "n2") in events
        inf.stop()


class TestListers:
    def test_node_readiness_filter(self):
        store = ThreadSafeStore()
        store.add("ready", mk_node("ready"))
        store.add("notready", mk_node("notready", ready=False))
        cordoned = mk_node("cordoned")
        cordoned.spec = api.NodeSpec(unschedulable=True)
        store.add("cordoned", cordoned)
        ool = mk_node("outofdisk")
        ool.status.conditions.append(api.NodeCondition(type="OutOfDisk", status="True"))
        store.add("outofdisk", ool)
        lister = NodeLister(store)
        assert [n.metadata.name for n in lister.list()] == ["ready"]
        assert len(lister.list_all()) == 4

    def test_get_pod_services(self):
        store = ThreadSafeStore()
        svc = api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                          spec=api.ServiceSpec(selector={"app": "web"},
                                               ports=[api.ServicePort(port=80)]))
        store.add("default/s", svc)
        lister = ServiceLister(store)
        assert lister.get_pod_services(mk_pod("p", labels={"app": "web"}))
        assert not lister.get_pod_services(mk_pod("p", labels={"app": "db"}))
        assert not lister.get_pod_services(mk_pod("p", ns="other", labels={"app": "web"}))


class TestEventRecorder:
    def test_dedup_aggregation(self, server, client):
        rec = EventRecorder(client, "scheduler")
        pod = client.create("pods", mk_pod("p"))
        for _ in range(3):
            rec.event(pod, "Warning", "FailedScheduling", "no nodes available")
        rec.flush()
        _wait(lambda: client.list("events", "default")[0])
        events, _ = client.list("events", "default")
        assert len(events) == 1
        _wait(lambda: client.list("events", "default")[0][0].count == 3)
        ev = client.list("events", "default")[0][0]
        assert ev.reason == "FailedScheduling"
        assert ev.involved_object.name == "p"
        rec.event(pod, "Normal", "Scheduled", "bound to n1")
        rec.flush()
        _wait(lambda: len(client.list("events", "default")[0]) == 2)


def _wait(cond, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")
