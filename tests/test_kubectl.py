"""kubectl CLI against a live in-process apiserver (reference
pkg/kubectl/cmd/*_test.go + hack/test-cmd.sh shapes)."""

import json

import pytest
import yaml

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.kubectl.cmd import main
from kubernetes_tpu.utils import jsonpath, strategicpatch


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server)


@pytest.fixture()
def kubectl(server, capsys):
    def run(*argv, expect=0):
        rc = main(["-s", f"127.0.0.1:{server.port}", *argv])
        captured = capsys.readouterr()
        assert rc == expect, f"rc={rc} stderr={captured.err}"
        return captured.out
    return run


def _mk_pod(client, name, labels=None, node="", phase="Running"):
    pod = api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels or {}),
        spec=api.PodSpec(
            node_name="",
            containers=[api.Container(name="c", image="pause")]))
    created = client.create("pods", pod, "default")
    if phase:
        created.status = api.PodStatus(phase=phase)
        client.update_status("pods", created)
    return created


class TestGet:
    def test_get_pods_table(self, kubectl, client):
        _mk_pod(client, "alpha")
        _mk_pod(client, "beta")
        out = kubectl("get", "pods")
        assert "NAME" in out and "STATUS" in out
        assert "alpha" in out and "beta" in out
        assert "Running" in out

    def test_get_single_json_and_jsonpath(self, kubectl, client):
        _mk_pod(client, "alpha")
        out = kubectl("get", "pods", "alpha", "-o", "json")
        d = json.loads(out)
        assert d["kind"] == "Pod" and d["metadata"]["name"] == "alpha"
        out = kubectl("get", "pods", "alpha", "-o",
                      "jsonpath={.metadata.name}")
        assert out.strip() == "alpha"

    def test_get_by_slash_and_shortname(self, kubectl, client):
        _mk_pod(client, "alpha")
        out = kubectl("get", "po/alpha")
        assert "alpha" in out

    def test_get_yaml_list(self, kubectl, client):
        _mk_pod(client, "a")
        _mk_pod(client, "b")
        out = kubectl("get", "pods", "-o", "yaml")
        d = yaml.safe_load(out)
        assert d["kind"] == "List" and len(d["items"]) == 2

    def test_get_selector(self, kubectl, client):
        _mk_pod(client, "a", labels={"app": "x"})
        _mk_pod(client, "b", labels={"app": "y"})
        out = kubectl("get", "pods", "-l", "app=x", "-o", "name")
        assert out.strip() == "pod/a"

    def test_jsonpath_items_idiom_over_list(self, kubectl, client):
        _mk_pod(client, "a")
        _mk_pod(client, "b")
        out = kubectl("get", "pods", "-o",
                      "jsonpath={.items[*].metadata.name}")
        assert out.split() == ["a", "b"]


class TestCreateApplyDelete:
    def test_create_from_yaml(self, kubectl, tmp_path):
        f = tmp_path / "pod.yaml"
        f.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "made", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img:1"}]}}))
        out = kubectl("create", "-f", str(f))
        assert 'pod "made" created' in out
        out = kubectl("get", "pods", "made", "-o",
                      "jsonpath={.spec.containers[0].image}")
        assert out.strip() == "img:1"

    def test_create_multidoc(self, kubectl, tmp_path):
        f = tmp_path / "multi.yaml"
        f.write_text(
            yaml.safe_dump({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "one"},
                "spec": {"containers": [{"name": "c", "image": "i"}]}})
            + "---\n" +
            yaml.safe_dump({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "two"},
                "spec": {"selector": {"a": "b"},
                         "ports": [{"port": 80}]}}))
        out = kubectl("create", "-f", str(f))
        assert "created" in out
        assert kubectl("get", "svc", "two", "-o",
                       "jsonpath={.metadata.name}").strip() == "two"

    def test_apply_create_then_update(self, kubectl, tmp_path, client):
        doc = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "app1", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img:v1"}]}}
        f = tmp_path / "p.yaml"
        f.write_text(yaml.safe_dump(doc))
        assert 'created' in kubectl("apply", "-f", str(f))
        # out-of-band change to an unrelated field survives apply
        live = client.get("pods", "app1", "default")
        live.metadata.labels = {"added-by": "other"}
        client.update("pods", live, "default")
        doc["spec"]["containers"][0]["image"] = "img:v2"
        f.write_text(yaml.safe_dump(doc))
        assert 'configured' in kubectl("apply", "-f", str(f))
        after = client.get("pods", "app1", "default")
        assert after.spec.containers[0].image == "img:v2"
        assert (after.metadata.labels or {}).get("added-by") == "other"

    def test_delete_by_name_selector_all(self, kubectl, client):
        _mk_pod(client, "a", labels={"app": "x"})
        _mk_pod(client, "b", labels={"app": "x"})
        _mk_pod(client, "keep")
        out = kubectl("delete", "pods", "-l", "app=x")
        assert out.count("deleted") == 2
        assert kubectl("get", "pods", "-o", "name").strip() == "pod/keep"


class TestScaleRollout:
    def test_scale_rc(self, kubectl, client):
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name="rc1", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=1, selector={"app": "rc1"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "rc1"}),
                    spec=api.PodSpec(containers=[
                        api.Container(name="c", image="i")]))))
        client.create("replicationcontrollers", rc, "default")
        kubectl("scale", "rc", "rc1", "--replicas=5")
        assert client.get("replicationcontrollers", "rc1",
                          "default").spec.replicas == 5


class TestNodeOps:
    def _mk_node(self, client, name):
        client.create("nodes", api.Node(
            metadata=api.ObjectMeta(name=name),
            status=api.NodeStatus(conditions=[api.NodeCondition(
                type="Ready", status="True")])))

    def test_cordon_uncordon(self, kubectl, client):
        self._mk_node(client, "n1")
        kubectl("cordon", "n1")
        assert client.get("nodes", "n1").spec.unschedulable is True
        out = kubectl("get", "nodes")
        assert "SchedulingDisabled" in out
        kubectl("uncordon", "n1")
        assert client.get("nodes", "n1").spec.unschedulable is False

    def test_drain_evicts_managed_pods(self, kubectl, client):
        self._mk_node(client, "n1")
        p = api.Pod(
            metadata=api.ObjectMeta(
                name="victim", namespace="default",
                owner_references=[api.OwnerReference(
                    kind="ReplicaSet", name="rs", uid="u1")]),
            spec=api.PodSpec(containers=[api.Container(name="c", image="i")]))
        created = client.create("pods", p, "default")
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name="victim", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n1")), "default")
        out = kubectl("drain", "n1")
        assert 'pod "victim" evicted' in out
        assert client.get("nodes", "n1").spec.unschedulable is True

    def test_drain_refuses_unmanaged_without_force(self, kubectl, client):
        self._mk_node(client, "n2")
        client.create("pods", api.Pod(
            metadata=api.ObjectMeta(name="bare", namespace="default"),
            spec=api.PodSpec(containers=[api.Container(name="c", image="i")])),
            "default")
        client.bind(api.Binding(
            metadata=api.ObjectMeta(name="bare", namespace="default"),
            target=api.ObjectReference(kind="Node", name="n2")), "default")
        kubectl("drain", "n2", expect=1)
        assert client.get("pods", "bare", "default")  # survived
        kubectl("drain", "n2", "--force")


class TestRunExposeLabel:
    def test_run_creates_rc(self, kubectl, client):
        kubectl("run", "web", "--image=nginx", "--replicas=2")
        rc = client.get("replicationcontrollers", "web", "default")
        assert rc.spec.replicas == 2
        assert rc.spec.template.spec.containers[0].image == "nginx"

    def test_run_restart_never_creates_pod(self, kubectl, client):
        kubectl("run", "onep", "--image=img", "--restart=Never")
        assert client.get("pods", "onep", "default")

    def test_expose_rc(self, kubectl, client):
        kubectl("run", "web", "--image=nginx")
        kubectl("expose", "rc", "web", "--port=80")
        svc = client.get("services", "web", "default")
        assert svc.spec.selector == {"run": "web"}
        assert svc.spec.ports[0].port == 80

    def test_label_and_annotate(self, kubectl, client):
        _mk_pod(client, "p1")
        kubectl("label", "pods", "p1", "tier=web")
        assert client.get("pods", "p1",
                          "default").metadata.labels["tier"] == "web"
        kubectl("label", "pods", "p1", "tier=db", expect=1)  # no overwrite
        kubectl("label", "pods", "p1", "tier=db", "--overwrite")
        assert client.get("pods", "p1",
                          "default").metadata.labels["tier"] == "db"
        kubectl("label", "pods", "p1", "tier-")
        assert "tier" not in (client.get("pods", "p1",
                                         "default").metadata.labels or {})
        kubectl("annotate", "pods", "p1", "note=hello")
        assert client.get("pods", "p1",
                          "default").metadata.annotations["note"] == "hello"

    def test_autoscale(self, kubectl, client):
        kubectl("run", "web", "--image=nginx")
        kubectl("autoscale", "rc", "web", "--max=8", "--cpu-percent=70")
        hpa = client.get("horizontalpodautoscalers", "web", "default")
        assert hpa.spec.max_replicas == 8
        assert hpa.spec.scale_target_ref.kind == "ReplicationController"


class TestMisc:
    def test_version_and_apiversions(self, kubectl):
        assert "Client Version" in kubectl("version")
        out = kubectl("api-versions")
        assert "v1" in out and "extensions/v1beta1" in out

    def test_describe_pod(self, kubectl, client):
        _mk_pod(client, "descme", labels={"a": "b"})
        out = kubectl("describe", "pods", "descme")
        assert "Name:\tdescme" in out
        assert "a=b" in out
        assert "Image:\tpause" in out


class TestJSONPathUnit:
    def test_basic_paths(self):
        data = {"metadata": {"name": "x"},
                "items": [{"v": 1}, {"v": 2}]}
        assert jsonpath.evaluate("{.metadata.name}", data) == "x"
        assert jsonpath.evaluate("{.items[*].v}", data) == "1 2"
        assert jsonpath.evaluate("{.items[0].v}/{.items[-1].v}", data) == "1/2"
        assert jsonpath.evaluate("name={.metadata.name}", data) == "name=x"

    def test_errors(self):
        with pytest.raises(jsonpath.JSONPathError):
            jsonpath.evaluate("{metadata}", {})
        with pytest.raises(jsonpath.JSONPathError):
            jsonpath.evaluate("{.a", {})


class TestStrategicPatchUnit:
    def test_three_way_preserves_cluster_fields(self):
        original = {"spec": {"replicas": 1, "template": {"x": 1}}}
        modified = {"spec": {"replicas": 3, "template": {"x": 1}}}
        current = {"spec": {"replicas": 1, "template": {"x": 1},
                            "clusterIP": "10.0.0.1"},
                   "status": {"observed": 1}}
        out = strategicpatch.three_way_merge(original, modified, current)
        assert out["spec"]["replicas"] == 3
        assert out["spec"]["clusterIP"] == "10.0.0.1"
        assert out["status"] == {"observed": 1}

    def test_deletion_directive(self):
        original = {"metadata": {"labels": {"a": "1", "b": "2"}}}
        modified = {"metadata": {"labels": {"a": "1"}}}
        current = {"metadata": {"labels": {"a": "1", "b": "2", "c": "3"}}}
        out = strategicpatch.three_way_merge(original, modified, current)
        assert out["metadata"]["labels"] == {"a": "1", "c": "3"}

    def test_container_list_merged_by_name(self):
        current = {"containers": [{"name": "a", "image": "a:1"},
                                  {"name": "b", "image": "b:1"}]}
        patch = {"containers": [{"name": "a", "image": "a:2"}]}
        out = strategicpatch.apply_patch(current, patch)
        assert out["containers"] == [{"name": "a", "image": "a:2"},
                                     {"name": "b", "image": "b:1"}]

    def test_keyless_ports_replace_not_append(self):
        # Service ports carry 'port', not the containers' merge key — apply
        # must replace the list, never append duplicates
        original = {"ports": [{"port": 80}]}
        modified = {"ports": [{"port": 80}]}
        current = {"ports": [{"port": 80, "protocol": "TCP"}]}
        out = strategicpatch.three_way_merge(original, modified, current)
        assert len(out["ports"]) == 1

    def test_removed_list_element_emits_delete_directive(self):
        original = {"env": [{"name": "A", "value": "1"},
                            {"name": "B", "value": "2"}]}
        modified = {"env": [{"name": "A", "value": "1"}]}
        current = {"env": [{"name": "A", "value": "1"},
                           {"name": "B", "value": "2"},
                           {"name": "C", "value": "3"}]}
        out = strategicpatch.three_way_merge(original, modified, current)
        names = [e["name"] for e in out["env"]]
        assert "B" not in names          # removed from manifest -> removed
        assert "C" in names              # cluster-added element survives


class TestCronDayBits:
    def test_star_step_dom_still_restricts(self):
        from kubernetes_tpu.utils import cron
        s = cron.parse("0 0 */2 * *")
        import time as _t
        nxt = s.next_after(0)  # epoch day 1 (Jan 1) matches */2 from day 1
        assert _t.gmtime(nxt).tm_mday in range(1, 32, 2)

    def test_restricted_dom_and_dow_or_combine(self):
        from kubernetes_tpu.utils import cron
        s = cron.parse("0 0 13 * 5")  # 13th OR Fridays
        import time as _t
        t = s.next_after(0)
        tm = _t.gmtime(t)
        assert tm.tm_mday == 13 or tm.tm_wday == 4


class TestDeleteFileNamespace:
    def test_delete_f_honors_manifest_namespace(self, kubectl, client,
                                                tmp_path):
        doc = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "nsd", "namespace": "team-z"},
               "spec": {"containers": [{"name": "c", "image": "i"}]}}
        f = tmp_path / "p.yaml"
        f.write_text(yaml.safe_dump(doc))
        kubectl("create", "-f", str(f))
        assert client.get("pods", "nsd", "team-z")
        kubectl("delete", "-f", str(f))
        import pytest as _pytest
        from kubernetes_tpu.client.rest import ApiError
        with _pytest.raises(ApiError):
            client.get("pods", "nsd", "team-z")
