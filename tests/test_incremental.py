"""Incremental tensorizer equivalence + device-residency tests.

The incremental mirror (ops/incremental.py) must produce the same bindings
as the per-batch full rebuild (ops/tensorize.py) and the sequential oracle,
across event histories — adds, removals, node flips — not just one-shot
builds. The full rebuild is itself oracle-differential-tested
(test_tpu_kernel.py / test_kernel_gaps.py), so agreement here chains all
three implementations together.
"""

import random

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.ops.incremental import IncrementalTensorizer
from kubernetes_tpu.scheduler.batch import (
    ListPodLister, ListServiceLister, make_plugin_args, oracle_batch,
    tpu_batch,
)
from kubernetes_tpu.scheduler.cache import SchedulerCache

from tests.test_kernel_gaps import (
    aff, anti, ebs_vol, gce_vol, mk_node, mk_pod, pref,
)


def mk_args(nodes, existing=(), services=()):
    return make_plugin_args(
        nodes, pod_lister=ListPodLister(list(existing)),
        service_lister=ListServiceLister(list(services)))


def mirrored(nodes, existing, args):
    """SchedulerCache with an attached incremental mirror, fed via the real
    cache delta events."""
    cache = SchedulerCache()
    inc = IncrementalTensorizer(args)
    cache.add_listener(inc)
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    return cache, inc


def check_all_three(nodes, existing, pending, services=()):
    """oracle == full tensorize == incremental, same inputs."""
    want = oracle_batch(nodes, existing, pending,
                        mk_args(nodes, existing, services))
    full = tpu_batch(nodes, existing, pending,
                     mk_args(nodes, existing, services))
    assert full == want, f"full path broke:\n  {want}\n  {full}"
    cache, inc = mirrored(nodes, existing,
                          mk_args(nodes, existing, services))
    got = inc.schedule(pending)
    assert got == want, (
        f"incremental disagrees:\n  oracle:      {want}\n  incremental: {got}")
    return cache, inc, got


def commit(cache, pending, got):
    """Feed the batch's bindings back as informer-confirmed adds."""
    placed = []
    for pod, host in zip(pending, got):
        if host is None:
            continue
        p = deep_copy(pod)
        p.spec.node_name = host
        cache.add_pod(p)
        placed.append(p)
    return placed


class TestOneShotEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_cluster(self, seed):
        rng = random.Random(seed)
        nodes = []
        for i in range(16):
            labels = {api.LABEL_ZONE: f"z{i % 3}"}
            if rng.random() < 0.3:
                labels["disk"] = "ssd"
            taints = ([api.Taint(key="ded", value="ml", effect="NoSchedule")]
                      if rng.random() < 0.2 else None)
            nodes.append(mk_node(f"n{i:02d}", cpu=rng.choice(["2", "4", "8"]),
                                 labels=labels, taints=taints))
        existing = [mk_pod(f"e{i}", cpu="250m",
                           labels={"app": rng.choice(["web", "db"])},
                           node=f"n{rng.randrange(16):02d}")
                    for i in range(12)]
        svc = api.Service(
            metadata=api.ObjectMeta(name="web", namespace="default"),
            spec=api.ServiceSpec(selector={"app": "web"},
                                 ports=[api.ServicePort(port=80)]))
        apps = ["web", "db", "cache"]
        pending = []
        for i in range(40):
            app = rng.choice(apps)
            affinity = volumes = None
            roll = rng.random()
            if roll < 0.15:
                affinity = anti({"app": app}, api.LABEL_ZONE)
            elif roll < 0.3:
                affinity = aff({"app": rng.choice(apps)}, api.LABEL_ZONE)
            elif roll < 0.45:
                affinity = pref({"app": rng.choice(apps)}, api.LABEL_ZONE,
                                weight=rng.choice([10, 50]),
                                anti_=rng.random() < 0.5)
            elif roll < 0.55:
                volumes = [ebs_vol(f"vol-{rng.randrange(4)}")]
            elif roll < 0.6:
                volumes = [gce_vol(f"pd-{rng.randrange(4)}",
                                   ro=rng.random() < 0.5)]
            pending.append(mk_pod(f"p{i:02d}", labels={"app": app},
                                  cpu=rng.choice(["100m", "500m"]),
                                  affinity=affinity, volumes=volumes))
        check_all_three(nodes, existing, pending, [svc])

    def test_existing_pods_with_own_terms(self):
        """Placed pods' anti-affinity (symmetry) and preferred terms flow
        through pod_added events into the sym/te tables."""
        nodes = [mk_node(f"n{i}", labels={api.LABEL_ZONE: f"z{i % 2}"})
                 for i in range(4)]
        existing = [
            mk_pod("guard", node="n0", labels={"app": "guard"},
                   affinity=anti({"app": "victim"}, api.LABEL_ZONE)),
            mk_pod("magnet", node="n1", labels={"app": "magnet"},
                   affinity=pref({"app": "friend"}, api.LABEL_ZONE,
                                 weight=80)),
        ]
        pending = [mk_pod("v", labels={"app": "victim"}),
                   mk_pod("f", labels={"app": "friend"})]
        check_all_three(nodes, existing, pending)


class TestEventHistoryEquivalence:
    def test_multi_round_commit(self):
        """Three rounds of schedule->bind->next batch: the mirror must track
        the full rebuild given the same cumulative history."""
        nodes = [mk_node(f"n{i}", cpu="2", pods="6",
                         labels={api.LABEL_ZONE: f"z{i % 2}"})
                 for i in range(6)]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        history = []
        for rnd in range(3):
            pending = [
                mk_pod(f"r{rnd}-p{i}", cpu="300m",
                       labels={"app": "web" if i % 2 else "db"},
                       affinity=(anti({"app": "db"}, api.LABEL_HOSTNAME)
                                 if i == 3 else None))
                for i in range(8)
            ]
            got = inc.schedule(pending)
            want = tpu_batch(nodes, list(history), pending,
                             mk_args(nodes, list(history)))
            assert got == want, f"round {rnd}: {got} != {want}"
            history.extend(commit(cache, pending, got))

    def test_removal_rolls_back_everything(self):
        """Remove every placed pod -> the mirror must behave as if from
        scratch (counts, hit tables, ports, volumes all reversed)."""
        nodes = [mk_node(f"n{i}", cpu="1", pods="3") for i in range(3)]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        pending = [mk_pod(f"p{i}", cpu="400m",
                          affinity=anti({"g": "x"}, api.LABEL_HOSTNAME),
                          labels={"g": "x"}, volumes=[ebs_vol("vol-1")])
                   for i in range(3)]
        got1 = inc.schedule(pending)
        placed = commit(cache, pending, got1)
        assert len({g for g in got1 if g}) == 3  # anti-affinity spread
        for p in placed:
            cache.remove_pod(p)
        fresh = [mk_pod(f"q{i}", cpu="400m",
                        affinity=anti({"g": "x"}, api.LABEL_HOSTNAME),
                        labels={"g": "x"}, volumes=[ebs_vol("vol-1")])
                 for i in range(3)]
        got2 = inc.schedule(fresh)
        want = tpu_batch(nodes, [], fresh, mk_args(nodes))
        assert got2 == want
        assert sorted(filter(None, got2)) == sorted(filter(None, got1))

    def test_node_lifecycle(self):
        """Nodes appearing, flipping NotReady, and being removed mid-stream."""
        n0, n1, n2 = (mk_node(f"n{i}", cpu="2") for i in range(3))
        args = mk_args([n0, n1, n2])
        cache, inc = mirrored([n0, n1], [], args)

        got = inc.schedule([mk_pod("a", cpu="1500m"),
                            mk_pod("b", cpu="1500m"),
                            mk_pod("c", cpu="1500m")])
        assert got.count(None) == 1  # only two nodes exist

        cache.add_node(n2)          # third node appears
        got = inc.schedule([mk_pod("d", cpu="1500m")])
        assert got == ["n2"] or got[0] in {"n0", "n1", "n2"}

        flip = deep_copy(n2)
        flip.status.conditions = [api.NodeCondition(type="Ready",
                                                    status="False")]
        cache.update_node(flip)     # NotReady -> invalid for placement
        got = inc.schedule([mk_pod("e", cpu="100m")])
        assert got[0] in {"n0", "n1"}

        cache.remove_node(n0)
        got = inc.schedule([mk_pod("f", cpu="100m")])
        assert got == ["n1"]

    def test_node_label_change_reinits_domains(self):
        """Relabeling a node re-derives topology-domain hit tables."""
        a = mk_node("a", labels={api.LABEL_ZONE: "z1"})
        b = mk_node("b", labels={api.LABEL_ZONE: "z1"})
        args = mk_args([a, b])
        cache, inc = mirrored([a, b], [], args)
        cache.add_pod(mk_pod("guard", node="a", labels={"app": "g"},
                             affinity=anti({"app": "v"}, api.LABEL_ZONE)))
        # same zone everywhere: victim can't place
        got = inc.schedule([mk_pod("v1", labels={"app": "v"})])
        assert got == [None]
        # move b to its own zone: victim fits there now
        b2 = deep_copy(b)
        b2.metadata.labels = {api.LABEL_HOSTNAME: "b", api.LABEL_ZONE: "z2"}
        cache.update_node(b2)
        got = inc.schedule([mk_pod("v2", labels={"app": "v"})])
        assert got == ["b"]


class TestChurnHygiene:
    def test_slot_reclaimed_after_drain(self):
        """node removed with pods still on it: the slot frees once the last
        pod drains, so churn can't grow the node axis without bound."""
        nodes = [mk_node("a"), mk_node("b")]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        p = mk_pod("x", node="a", cpu="100m")
        cache.add_pod(p)
        cache.remove_node(nodes[0])
        assert "a" in inc._node_index          # still draining
        # a MODIFIED while draining (the normal pre-DELETE sequence) must
        # not launder the dead mark off the slot
        p2 = deep_copy(p)
        p2.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
        cache.update_pod(p2)
        assert "a" in inc._node_index
        cache.remove_pod(p2)
        assert "a" not in inc._node_index      # reclaimed
        free_before = len(inc._free)
        cache.add_node(mk_node("c"))
        assert len(inc._free) == free_before - 1   # slot reused

    def test_heartbeat_does_not_dirty_device_cache(self):
        """A status-only node update (same labels/taints/alloc) must not
        bump node-side versions — heartbeats are the common case."""
        nodes = [mk_node(f"n{i}") for i in range(4)]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        before = dict(inc._versions)
        cache.update_node(deep_copy(nodes[0]))   # identical heartbeat
        assert inc._versions == before


class TestDeviceResidency:
    def test_dirty_upload_shrinks(self):
        """Steady state re-uploads only what changed, not the world."""
        nodes = [mk_node(f"n{i:03d}") for i in range(200)]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        pending = [mk_pod(f"p{i}", cpu="100m") for i in range(32)]
        inc.schedule(pending)
        first = inc.last_upload_bytes
        commit(cache, pending, inc.schedule(pending))
        # second call with identical batch shape: node statics (labels,
        # taints, images, domains...) are device-resident, only pod-side
        # and touched aggregates move
        inc.schedule(pending)
        steady = inc.last_upload_bytes
        assert steady < first / 3, (first, steady)

    def test_jit_cache_stable_across_batches(self):
        import kubernetes_tpu.ops.kernel as K
        nodes = [mk_node(f"n{i}") for i in range(4)]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        inc.schedule([mk_pod("a", cpu="100m")])
        size = K._schedule_jit._cache_size()
        got = inc.schedule([mk_pod("b", cpu="200m")])
        assert K._schedule_jit._cache_size() == size
        assert got[0] is not None


class TestBrokenMirror:
    def test_listener_exception_marks_broken_and_cache_survives(self):
        """A throwing mirror never corrupts the cache, and refuses to serve
        stale tensors afterwards."""
        nodes = [mk_node("n0")]
        args = mk_args(nodes)
        cache, inc = mirrored(nodes, [], args)
        inc._apply_pod = lambda *a: (_ for _ in ()).throw(
            KeyError("poisoned"))
        p = mk_pod("victim", node="n0", cpu="100m")
        cache.add_pod(p)          # listener throws; cache must stay intact
        assert cache.pod_count() == 1
        info = cache.get_node_name_to_info_map()
        assert len(info["n0"].pods) == 1
        assert inc.broken and "poisoned" in inc.broken
        with pytest.raises(RuntimeError, match="mirror broken"):
            inc.schedule([mk_pod("q")])
        # the state is still removable (no phantom booking)
        cache.remove_pod(p)
        assert cache.pod_count() == 0

    def test_scheduler_resyncs_broken_mirror(self):
        """BatchScheduler classifies the broken-mirror error as a bug,
        falls back, resyncs a fresh mirror, and that one works."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        from tests.test_batch_scheduler import mk_node as bnode, \
            mk_pod as bpod, wait_scheduled

        server = APIServer().start()
        try:
            client = RESTClient.for_server(server, qps=1000, burst=1000)
            for i in range(3):
                client.create("nodes", bnode(f"n-{i}"))
            factory = ConfigFactory(client)
            factory.run(timeout=60)
            sched = factory.create_batch_from_provider(batch_size=16)
            old = sched._inc
            old.broken = "injected"
            client.create("pods", bpod("p-0"))
            n = 0
            while n == 0:
                n = sched.schedule_batch_once(timeout=2.0)
            assert sched._inc is not old          # resynced
            assert sched._inc.broken is None
            assert sched._inc._hi == 3            # re-mirrored from cache
            wait_scheduled(client, 1, timeout=15)
            # the fresh mirror schedules the next batch on the device path
            client.create("pods", bpod("p-1"))
            sched._retry_at = 0.0                 # skip the bug cooldown
            n = 0
            while n == 0:
                n = sched.schedule_batch_once(timeout=2.0)
            wait_scheduled(client, 2, timeout=15)
            assert sched.kernel_pods >= 1
            factory.stop()
        finally:
            server.stop()


class TestSchedulerWiring:
    def test_batch_scheduler_uses_mirror(self):
        """create_batch_from_provider attaches the mirror by default and the
        e2e path binds through it (full e2e in test_batch_scheduler.py)."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        from tests.test_batch_scheduler import mk_node as bnode, \
            mk_pod as bpod, wait_scheduled

        server = APIServer().start()
        try:
            client = RESTClient.for_server(server, qps=1000, burst=1000)
            for i in range(3):
                client.create("nodes", bnode(f"n-{i}"))
            factory = ConfigFactory(client)
            factory.run(timeout=60)
            sched = factory.create_batch_from_provider(batch_size=16)
            assert sched._inc is not None
            assert sched._inc._hi == 3  # nodes mirrored via listener replay
            for i in range(6):
                client.create("pods", bpod(f"p-{i}"))
            sched.run()
            try:
                wait_scheduled(client, 6, timeout=90)
            finally:
                sched.stop()
                factory.stop()
            assert sched.kernel_pods == 6 and sched.kernel_failures == 0, (
                f"health={sched.health} reason={sched.disabled_reason} "
                f"pods={sched.kernel_pods} failures={sched.kernel_failures}")
            assert sched._inc.builds >= 1
        finally:
            server.stop()
