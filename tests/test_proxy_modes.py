"""Proxy completeness: session affinity, NodePorts, userspace fallback
(round-3 verdict missing #9 — reference pkg/proxy/iptables/proxier.go
sessionAffinity + nodePorts rules; pkg/proxy/userspace proxysocket.go +
roundrobin.go)."""

import socket
import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.proxy import FakeIptables, LoadBalancerRR, Proxier
from kubernetes_tpu.proxy.userspace import UserspaceProxier


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=1000, burst=1000)


def mk_service(name, port=80, cluster_ip="10.96.0.10", node_port=0,
               svc_type="", affinity=""):
    return api.Service(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.ServiceSpec(
            cluster_ip=cluster_ip, type=svc_type, session_affinity=affinity,
            ports=[api.ServicePort(name="main", port=port,
                                   node_port=node_port)]))


def mk_endpoints(name, addrs, port=8080):
    return api.Endpoints(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        subsets=[api.EndpointSubset(
            addresses=[api.EndpointAddress(ip=ip) for ip in addrs],
            ports=[api.EndpointPort(name="main", port=port)])])


def wait_rules(ipt, pred, timeout=10):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred(ipt.current):
            return ipt.current
        time.sleep(0.05)
    raise AssertionError(f"ruleset never matched; last:\n{ipt.current}")


class TestIptablesModes:
    def test_nodeport_rules(self, client):
        ipt = FakeIptables()
        p = Proxier(client, ipt)
        p.start()
        try:
            client.create("services", mk_service(
                "np", node_port=30080, svc_type="NodePort"))
            client.create("endpoints", mk_endpoints("np", ["10.1.0.1"]))
            rules = wait_rules(ipt, lambda r: "--dport 30080" in r)
            assert "-A KUBE-NODEPORTS -p tcp --dport 30080 -j KUBE-SVC-" in rules
            # the chain is actually reachable: KUBE-SERVICES' terminal
            # local-traffic rule jumps to it (proxier.go writes this last)
            assert rules.splitlines()[-2] == (
                "-A KUBE-SERVICES -m addrtype --dst-type LOCAL "
                "-j KUBE-NODEPORTS")
        finally:
            p.stop()

    def test_clusterip_only_service_has_no_nodeport_rule(self, client):
        ipt = FakeIptables()
        p = Proxier(client, ipt)
        p.start()
        try:
            client.create("services", mk_service("plain"))
            client.create("endpoints", mk_endpoints("plain", ["10.1.0.1"]))
            rules = wait_rules(ipt, lambda r: "KUBE-SVC-" in r)
            assert "-A KUBE-NODEPORTS -p" not in rules
        finally:
            p.stop()

    def test_session_affinity_recent_rules(self, client):
        ipt = FakeIptables()
        p = Proxier(client, ipt)
        p.start()
        try:
            client.create("services", mk_service("sticky", affinity="ClientIP"))
            client.create("endpoints",
                          mk_endpoints("sticky", ["10.1.0.1", "10.1.0.2"]))
            rules = wait_rules(ipt, lambda r: "--rcheck" in r)
            # one rcheck (match existing stickiness) + one --set (record) per
            # endpoint, like the reference's recent-module pairs
            assert rules.count("--rcheck --seconds 10800 --reap") == 2
            assert rules.count("-m recent --name KUBE-SEP-") == 4
            assert rules.count("--set") == 2
        finally:
            p.stop()


class _EchoServer:
    """Answers every connection with its tag (distinguishable backend)."""

    def __init__(self, tag: bytes):
        self.tag = tag
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self._sock.settimeout(0.2)
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.sendall(self.tag)
                conn.shutdown(socket.SHUT_WR)
                conn.recv(1)
            except OSError:
                pass
            finally:
                conn.close()

    def stop(self):
        self._stop = True
        self._sock.close()


def _dial(port: int) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
        out = b""
        while True:
            b = s.recv(1024)
            if not b:
                return out
            out += b


def _wait_active(port: int, timeout: float = 10.0) -> None:
    """Wait until the relay has a live backend. The service port opens on
    the SERVICE event, endpoints are programmed by a separate event, and a
    dial in between is rightly dropped (b"") — reference userspace-proxy
    bootstrap behavior, which the fast (TCP_NODELAY) stack now actually
    exposes to tests."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _dial(port):
            return
        time.sleep(0.05)
    raise AssertionError(f"relay on :{port} never served a backend")


class TestLoadBalancerRR:
    def test_round_robin(self):
        lb = LoadBalancerRR()
        lb.set_endpoints("k", [("a", 1), ("b", 2), ("c", 3)])
        assert [lb.next_endpoint("k") for _ in range(6)] == [
            ("a", 1), ("b", 2), ("c", 3)] * 2

    def test_client_ip_affinity(self):
        lb = LoadBalancerRR()
        lb.set_endpoints("k", [("a", 1), ("b", 2)], session_affinity=True)
        first = lb.next_endpoint("k", client_ip="9.9.9.9")
        for _ in range(5):
            assert lb.next_endpoint("k", client_ip="9.9.9.9") == first
        # a different client still gets spread
        other = lb.next_endpoint("k", client_ip="8.8.8.8")
        assert other != first or lb.next_endpoint("k", "8.8.8.8") == other

    def test_dial_failure_voids_stickiness(self):
        """A sticky client whose pinned endpoint stops answering must fail
        over instead of being blackholed for the affinity TTL (reference
        sessionAffinityReset after a failed dial)."""
        lb = LoadBalancerRR()
        lb.set_endpoints("k", [("dead", 1), ("live", 2)],
                         session_affinity=True)
        pinned = lb.next_endpoint("k", client_ip="9.9.9.9")
        lb.endpoint_failed("k", "9.9.9.9", pinned)
        nxt = lb.next_endpoint("k", client_ip="9.9.9.9")
        assert nxt != pinned

    def test_sticky_entry_dropped_when_endpoint_vanishes(self):
        lb = LoadBalancerRR()
        lb.set_endpoints("k", [("a", 1), ("b", 2)], session_affinity=True)
        pinned = lb.next_endpoint("k", client_ip="9.9.9.9")
        remaining = [e for e in [("a", 1), ("b", 2)] if e != pinned]
        lb.set_endpoints("k", remaining, session_affinity=True)
        assert lb.next_endpoint("k", client_ip="9.9.9.9") == remaining[0]


class TestUserspaceProxier:
    def test_relays_and_round_robins_real_backends(self, client):
        b1, b2 = _EchoServer(b"one"), _EchoServer(b"two")
        p = UserspaceProxier(client)
        p.start()
        try:
            client.create("services", mk_service("web"))
            client.create("endpoints", api.Endpoints(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                subsets=[api.EndpointSubset(
                    addresses=[api.EndpointAddress(ip="127.0.0.1")],
                    ports=[api.EndpointPort(name="main", port=b1.port)]),
                    api.EndpointSubset(
                    addresses=[api.EndpointAddress(ip="127.0.0.1")],
                    ports=[api.EndpointPort(name="main", port=b2.port)])]))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    "default/web:main" not in p.port_map:
                time.sleep(0.05)
            lport = p.port_map["default/web:main"]
            _wait_active(lport)
            seen = {_dial(lport) for _ in range(6)}
            assert seen == {b"one", b"two"}, f"no spread: {seen}"
        finally:
            p.stop()
            b1.stop()
            b2.stop()

    def test_endpoint_update_repoints_relay(self, client):
        b1, b2 = _EchoServer(b"old"), _EchoServer(b"new")
        p = UserspaceProxier(client)
        p.start()
        try:
            client.create("services", mk_service("flip"))
            client.create("endpoints", mk_endpoints(
                "flip", ["127.0.0.1"], port=b1.port))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    "default/flip:main" not in p.port_map:
                time.sleep(0.05)
            lport = p.port_map["default/flip:main"]
            _wait_active(lport)
            assert _dial(lport) == b"old"
            ep = client.get("endpoints", "flip", "default")
            ep.subsets[0].ports[0].port = b2.port
            client.update("endpoints", ep)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if _dial(lport) == b"new":
                    return
                time.sleep(0.1)
            raise AssertionError("relay never repointed to new endpoint")
        finally:
            p.stop()
            b1.stop()
            b2.stop()

    def test_sticky_service_pins_backend(self, client):
        b1, b2 = _EchoServer(b"A"), _EchoServer(b"B")
        p = UserspaceProxier(client)
        p.start()
        try:
            client.create("services", mk_service("pin", affinity="ClientIP"))
            client.create("endpoints", api.Endpoints(
                metadata=api.ObjectMeta(name="pin", namespace="default"),
                subsets=[api.EndpointSubset(
                    addresses=[api.EndpointAddress(ip="127.0.0.1")],
                    ports=[api.EndpointPort(name="main", port=b1.port)]),
                    api.EndpointSubset(
                    addresses=[api.EndpointAddress(ip="127.0.0.1")],
                    ports=[api.EndpointPort(name="main", port=b2.port)])]))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    "default/pin:main" not in p.port_map:
                time.sleep(0.05)
            lport = p.port_map["default/pin:main"]
            _wait_active(lport)
            # all connections come from 127.0.0.1 -> one sticky backend
            seen = {_dial(lport) for _ in range(6)}
            assert len(seen) == 1, f"affinity did not pin: {seen}"
        finally:
            p.stop()
            b1.stop()
            b2.stop()
