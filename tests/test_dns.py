"""Cluster DNS (kube-dns analog) over real UDP sockets.

Parity target: reference cmd/kube-dns/dns.go — A records for
{svc}.{ns}.svc.cluster.local off the service watch, headless services
answering per-endpoint, SRV for named ports, PTR for allocated cluster
IPs. Driven end-to-end here: API server -> informers -> DNS server ->
UDP query/response on a real datagram socket (round-4 verdict #7).
"""

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.dns.server import (
    DNSServer, RCODE_NXDOMAIN, RCODE_OK, RCODE_REFUSED, TYPE_A, TYPE_AAAA,
    TYPE_PTR, TYPE_SRV, resolve_udp,
)


def mk_service(name, ns="default", cluster_ip="", ports=None, selector=None):
    return api.Service(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        spec=api.ServiceSpec(cluster_ip=cluster_ip, selector=selector,
                             ports=ports or [api.ServicePort(port=80)]))


def mk_endpoints(name, ns="default", addrs=(), port=80, port_name=""):
    return api.Endpoints(
        metadata=api.ObjectMeta(name=name, namespace=ns),
        subsets=[api.EndpointSubset(
            addresses=[api.EndpointAddress(
                ip=ip,
                target_ref=(api.ObjectReference(kind="Pod", name=pod)
                            if pod else None))
                for pod, ip in addrs],
            ports=[api.EndpointPort(name=port_name, port=port)])])


class TestStaticResolution:
    """Unit level: record math without informers."""

    def setup_method(self):
        self.dns = DNSServer()
        self.dns.set_static(
            [mk_service("web", cluster_ip="10.0.0.7",
                        ports=[api.ServicePort(port=80, name="http")]),
             mk_service("db", cluster_ip="None",
                        ports=[api.ServicePort(port=5432, name="pg")])],
            [mk_endpoints("web", addrs=[("web-1", "10.4.0.1")], port=80,
                          port_name="http"),
             mk_endpoints("db", addrs=[("db-0", "10.4.1.1"),
                                       ("", "10.4.1.2")], port=5432,
                          port_name="pg")])

    def test_cluster_ip_a_record(self):
        rcode, ans = self.dns.resolve("web.default.svc.cluster.local", TYPE_A)
        assert rcode == RCODE_OK and len(ans) == 1

    def test_headless_returns_endpoint_ips(self):
        rcode, ans = self.dns.resolve("db.default.svc.cluster.local", TYPE_A)
        assert rcode == RCODE_OK and len(ans) == 2

    def test_headless_per_pod_record(self):
        rcode, ans = self.dns.resolve("db-0.db.default.svc.cluster.local",
                                      TYPE_A)
        assert rcode == RCODE_OK and len(ans) == 1
        # unnamed address resolvable by dashed ip
        rcode, ans = self.dns.resolve(
            "10-4-1-2.db.default.svc.cluster.local", TYPE_A)
        assert rcode == RCODE_OK and len(ans) == 1

    def test_srv_named_port(self):
        rcode, ans = self.dns.resolve(
            "_http._tcp.web.default.svc.cluster.local", TYPE_SRV)
        assert rcode == RCODE_OK and len(ans) == 1

    def test_srv_headless_per_endpoint(self):
        rcode, ans = self.dns.resolve(
            "_pg._tcp.db.default.svc.cluster.local", TYPE_SRV)
        assert rcode == RCODE_OK and len(ans) == 2

    def test_nxdomain_inside_domain(self):
        rcode, _ = self.dns.resolve("ghost.default.svc.cluster.local", TYPE_A)
        assert rcode == RCODE_NXDOMAIN

    def test_refused_outside_domain(self):
        rcode, _ = self.dns.resolve("example.com", TYPE_A)
        assert rcode == RCODE_REFUSED

    def test_aaaa_on_existing_name_empty_noerror(self):
        rcode, ans = self.dns.resolve("web.default.svc.cluster.local",
                                      TYPE_AAAA)
        assert rcode == RCODE_OK and ans == []

    def test_ptr_for_cluster_ip(self):
        rcode, ans = self.dns.resolve("7.0.0.10.in-addr.arpa", TYPE_PTR)
        assert rcode == RCODE_OK and len(ans) == 1


class TestLiveUDP:
    """The full path: apiserver -> informers -> UDP socket."""

    @pytest.fixture()
    def stack(self):
        server = APIServer().start()
        client = RESTClient.for_server(server)
        dns = None
        try:
            yield server, client, lambda: DNSServer(
                RESTClient.for_server(server))
        finally:
            server.stop()

    def test_service_resolves_over_udp(self, stack):
        server, client, make_dns = stack
        created = client.create("services", mk_service(
            "api", selector={"app": "api"},
            ports=[api.ServicePort(port=443, name="https")]))
        # the registry allocated a cluster IP (no IP was requested)
        assert created.spec.cluster_ip not in ("", "None")
        dns = make_dns().start()
        try:
            r = resolve_udp(dns.port, "api.default.svc.cluster.local")
            assert r["rcode"] == RCODE_OK
            assert [a[2] for a in r["answers"]] == [created.spec.cluster_ip]
            # PTR back
            rev = ".".join(reversed(created.spec.cluster_ip.split(".")))
            r = resolve_udp(dns.port, f"{rev}.in-addr.arpa", TYPE_PTR)
            assert r["answers"][0][2] == "api.default.svc.cluster.local"
            # SRV
            r = resolve_udp(dns.port,
                            "_https._tcp.api.default.svc.cluster.local",
                            TYPE_SRV)
            assert r["answers"][0][2][2] == 443
        finally:
            dns.stop()

    def test_headless_follows_endpoints_watch(self, stack):
        server, client, make_dns = stack
        client.create("services", mk_service("hl", cluster_ip="None"))
        dns = make_dns().start()
        try:
            r = resolve_udp(dns.port, "hl.default.svc.cluster.local")
            assert r["rcode"] == RCODE_OK and r["answers"] == []
            # endpoints appear -> records appear via the watch, no restart
            client.create("endpoints", mk_endpoints(
                "hl", addrs=[("hl-0", "10.9.0.1"), ("hl-1", "10.9.0.2")]))
            import time
            deadline = time.monotonic() + 10
            ips = []
            while time.monotonic() < deadline:
                r = resolve_udp(dns.port, "hl.default.svc.cluster.local")
                ips = sorted(a[2] for a in r["answers"])
                if ips:
                    break
                time.sleep(0.05)
            assert ips == ["10.9.0.1", "10.9.0.2"]
            r = resolve_udp(dns.port, "hl-1.hl.default.svc.cluster.local")
            assert [a[2] for a in r["answers"]] == ["10.9.0.2"]
        finally:
            dns.stop()

    def test_nxdomain_and_refused_over_udp(self, stack):
        server, client, make_dns = stack
        dns = make_dns().start()
        try:
            assert resolve_udp(dns.port,
                               "nope.default.svc.cluster.local")["rcode"] \
                == RCODE_NXDOMAIN
            assert resolve_udp(dns.port, "example.com")["rcode"] \
                == RCODE_REFUSED
        finally:
            dns.stop()


class TestClusterIPAllocation:
    def test_allocation_claim_conflict_release(self):
        server = APIServer().start()
        try:
            client = RESTClient.for_server(server)
            a = client.create("services", mk_service("a"))
            b = client.create("services", mk_service("b"))
            assert a.spec.cluster_ip != b.spec.cluster_ip
            # explicit claim of a taken IP is rejected
            from kubernetes_tpu.client.rest import ApiError
            with pytest.raises(ApiError) as ei:
                client.create("services", mk_service(
                    "c", cluster_ip=a.spec.cluster_ip))
            assert ei.value.code == 422
            # delete releases; the IP becomes claimable
            client.delete("services", "a", "default")
            c = client.create("services", mk_service(
                "c", cluster_ip=a.spec.cluster_ip))
            assert c.spec.cluster_ip == a.spec.cluster_ip
            # immutability on update
            c.spec.cluster_ip = "10.0.0.250"
            with pytest.raises(ApiError) as ei:
                client.update("services", c)
            assert ei.value.code == 422
        finally:
            server.stop()

    def test_failed_create_releases_claimed_ip(self):
        """A 422 on a manifest with an explicit clusterIP must put the IP
        back — else the corrected retry fails 'already allocated' forever."""
        server = APIServer().start()
        try:
            client = RESTClient.for_server(server)
            from kubernetes_tpu.client.rest import ApiError
            bad = mk_service("svc", cluster_ip="10.96.0.77")
            bad.spec.ports = None  # invalid: no ports
            with pytest.raises(ApiError):
                client.create("services", bad)
            good = client.create("services",
                                 mk_service("svc", cluster_ip="10.96.0.77"))
            assert good.spec.cluster_ip == "10.96.0.77"
            # network/broadcast addresses of the CIDR are not claimable
            with pytest.raises(ApiError):
                client.create("services",
                              mk_service("net0", cluster_ip="10.96.0.0"))
        finally:
            server.stop()
