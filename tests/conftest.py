"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benching happens via bench.py).

force_cpu() does the full dance — env vars alone are NOT enough because the
axon sitecustomize force-registers the TPU platform at interpreter startup
and its jax.config.update beats JAX_PLATFORMS; without the config update +
clear_backends the suite hangs trying to grab the chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(device_count=8)
