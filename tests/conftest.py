"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benching happens via bench.py). Must run before jax import.

Note: the environment's axon sitecustomize force-registers the TPU platform
when PALLAS_AXON_POOL_IPS is set, overriding JAX_PLATFORMS — drop it so
pytest genuinely runs on the CPU mesh and never monopolizes the chip.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
