"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip benching happens via bench.py).

force_cpu() does the full dance — env vars alone are NOT enough because the
axon sitecustomize force-registers the TPU platform at interpreter startup
and its jax.config.update beats JAX_PLATFORMS; without the config update +
clear_backends the suite hangs trying to grab the chip.

The whole suite also runs under the kube-verify runtime race detectors
(kubernetes_tpu/analysis/runtime.py — our `go test -race` stand-in):

- every lock created by kubernetes_tpu code is order-tracked; an A→B/B→A
  acquisition inversion anywhere in the run is recorded;
- every informer ThreadSafeStore fingerprints objects on write and
  verifies on read — in-place mutation of a shared cache object is
  recorded.

Recorded violations fail the test that triggered them (teardown hook
below). Tests that deliberately seed violations drain_violations()
themselves. Set KTPU_NO_RACE_DETECT=1 to switch both off (e.g. when
bisecting whether the instrumentation itself perturbs a timing test).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# install BEFORE any kubernetes_tpu module mints its locks — analysis.runtime
# itself only touches stdlib at import time
from kubernetes_tpu.analysis import runtime as _race  # noqa: E402

_RACE_DETECT = os.environ.get("KTPU_NO_RACE_DETECT", "") != "1"
if _RACE_DETECT:
    _race.install_lock_order_tracker()
    _race.enable_checked_store()

from kubernetes_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu(device_count=8)


def pytest_runtest_teardown(item, nextitem):
    """Fail the responsible test on any recorded race violation — raising
    inside a victim thread would vanish into a log; failing the test makes
    the inversion/mutation a red X with the full report attached.

    Also drains the finished-span ring between tests: span assertions
    (recent_spans / spans_for_trace) must see only the test's own spans,
    never a previous test's leftovers."""
    from kubernetes_tpu.utils import trace as _trace

    _trace.clear_recent()
    if not _RACE_DETECT:
        return
    violations = _race.drain_violations()
    if violations:
        raise AssertionError(
            "kube-verify runtime race detector recorded violation(s) "
            f"during {item.nodeid}:\n  " + "\n  ".join(violations))
