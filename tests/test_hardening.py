"""Serving hardening: bounded watch queues + max-in-flight (verdict #6).

Reference seams: slow-watcher termination in the cacher
(pkg/storage/cacher.go:73) and the MaxInFlightLimit handler
(pkg/apiserver/handlers.go) with long-running (watch) requests exempt —
the two prerequisites for surviving the 1k-node control-plane load test.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.informer import Informer, ListWatch
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.registry.generic import Registry
from kubernetes_tpu.storage.store import ERROR, MemStore


def mk_pod(name, ns="default", fat: int = 0):
    """fat > 0 pads the object so a few events overflow kernel socket
    buffers — the only way a loopback watch consumer ever backs up."""
    ann = {"pad": "x" * fat} if fat else None
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, annotations=ann),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")]))


class TestSlowWatcherDrop:
    def test_store_drops_watcher_past_queue_bound(self):
        store = MemStore(watcher_queue=8)
        w = store.watch("/pods/")
        for i in range(30):
            store.create(f"/pods/default/p{i}", {"n": i})
        assert w.dropped and w.stopped
        assert w not in store._watchers
        # the queue holds the delivered prefix, then the terminal ERROR
        events = []
        while True:
            ev = w.next(timeout=0.1)
            if ev is None:
                break
            events.append(ev)
        assert events[-1].type == ERROR
        assert events[-1].obj["code"] == 410
        # the dropped watcher never blocked writers
        assert store.count("/pods/") == 30

    def test_fast_watcher_not_dropped(self):
        store = MemStore(watcher_queue=8)
        w = store.watch("/pods/")
        got = []
        for i in range(50):
            store.create(f"/pods/default/p{i}", {"n": i})
            ev = w.next(timeout=1.0)
            got.append(ev)
        assert not w.dropped
        assert len(got) == 50

    def test_http_watch_stream_ends_with_error_frame(self):
        registry = Registry(MemStore(watcher_queue=8))
        server = APIServer(registry).start()
        try:
            client = RESTClient.for_server(server, qps=10000, burst=10000)
            stream = client.watch("pods", "default")
            time.sleep(0.2)  # server-side watcher established
            # not reading the stream + fat events -> socket back-pressure ->
            # the serve loop stalls -> the store watcher overflows its bound
            for i in range(64):
                client.create("pods", mk_pod(f"p-{i:03d}", fat=200 * 1024))
            frames = []
            for etype, obj in stream:
                frames.append(etype)
                if etype == "ERROR":
                    break
            assert frames[-1] == "ERROR"
            stream.stop()
        finally:
            server.stop()

    def test_informer_recovers_from_drop_by_relisting(self):
        """The full client loop: watcher dropped server-side -> reflector
        re-lists -> informer converges anyway."""
        registry = Registry(MemStore(watcher_queue=4))
        server = APIServer(registry).start()
        try:
            client = RESTClient.for_server(server, qps=10000, burst=10000)
            slow = threading.Event()

            inf = Informer(ListWatch(client, "pods"))
            # make the informer's consumption slow so its server-side
            # watcher overflows the 4-event queue
            orig_add = inf.store.add

            def slow_add(obj):
                if not slow.is_set():
                    time.sleep(0.05)
                orig_add(obj)

            inf.store.add = slow_add
            inf.run()
            assert inf.wait_for_sync(5)
            for i in range(40):
                client.create("pods", mk_pod(f"q-{i:03d}", fat=200 * 1024))
            slow.set()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if len(inf.store.list()) == 40:
                    break
                time.sleep(0.1)
            assert len(inf.store.list()) == 40
            inf.stop()
        finally:
            server.stop()


class SleepyAdmission:
    """Admission plugin that stalls creates, to saturate the server."""

    handles = ("CREATE",)

    def __init__(self, delay):
        self.delay = delay

    def admit(self, attributes):
        time.sleep(self.delay)


class TestMaxInFlight:
    def _server(self, max_in_flight):
        from kubernetes_tpu.admission import AdmissionChain
        chain = AdmissionChain([SleepyAdmission(0.4)])
        return APIServer(admission_control=chain,
                         max_in_flight=max_in_flight).start()

    def test_saturation_sheds_with_429(self):
        server = self._server(max_in_flight=2)
        try:
            client = RESTClient.for_server(server, qps=10000, burst=10000)
            results = []

            def create(i):
                # raw single attempt: no client-side retry, see the shed
                try:
                    path = "/api/v1/namespaces/default/pods"
                    from kubernetes_tpu.api.serialization import scheme
                    results.append(client._request_once(
                        "POST", path, scheme.encode(mk_pod(f"s-{i}"))
                    ).get("code"))
                except ApiError as e:
                    results.append(e.code)

            threads = [threading.Thread(target=create, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert 429 in results, results
            assert any(c != 429 for c in results), results
        finally:
            server.stop()

    def test_watches_exempt_from_limit(self):
        server = self._server(max_in_flight=1)
        try:
            client = RESTClient.for_server(server, qps=10000, burst=10000)
            # hold the single slot with a slow create
            t = threading.Thread(
                target=lambda: client.create("pods", mk_pod("hold")))
            t.start()
            time.sleep(0.1)
            # a watch still opens while the server is saturated
            stream = client.watch("pods", "default")
            t.join()
            got = []
            deadline = time.monotonic() + 5
            for etype, obj in stream:
                got.append(obj.metadata.name)
                break
            stream.stop()
            assert got == ["hold"]
        finally:
            server.stop()

    def test_client_retries_429_to_success(self):
        server = self._server(max_in_flight=1)
        try:
            client = RESTClient.for_server(server, qps=10000, burst=10000)
            threads = [threading.Thread(
                target=lambda i=i: client.create("pods", mk_pod(f"r-{i}")))
                for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            pods, _ = client.list("pods", "default")
            assert len(pods) == 4  # every create eventually landed
        finally:
            server.stop()
