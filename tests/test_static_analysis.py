"""kube-verify: checker fixtures, suppressions, baseline, CLI, runtime
race detectors, and the self-hosting gate.

Every checker family gets a seeded-violation fixture (known-bad snippet is
caught) and a clean-pass fixture (known-good snippet is not). The
self-hosting gate at the bottom runs the full analyzer over kubernetes_tpu/
and asserts zero non-baselined findings — the tier-1 contract that keeps
the package at its own bar.
"""

import json
import os
import textwrap
import threading

import pytest

from kubernetes_tpu.analysis import (
    Baseline,
    analyze_paths,
    analyze_source,
    default_baseline_path,
)
from kubernetes_tpu.analysis import runtime as race
from kubernetes_tpu.analysis.__main__ import main as cli_main
from kubernetes_tpu.api import types as api


def findings_of(src: str, check: str = None):
    found = analyze_source(textwrap.dedent(src))
    if check is not None:
        found = [f for f in found if f.check == check]
    return found


# --- lock-held-across-io ------------------------------------------------------

class TestLockHeldAcrossIO:
    def test_rest_call_under_lock_caught(self):
        src = """
        class VolumeManager:
            def resolve(self, name):
                with self._lock:
                    claim = self.resolver.get("persistentvolumeclaims", name)
                return claim
        """
        hits = findings_of(src, "lock-held-across-io")
        assert len(hits) == 1
        assert "resolver.get" in hits[0].message

    def test_sleep_and_subprocess_under_lock_caught(self):
        src = """
        def wait(self):
            with self._state_lock:
                time.sleep(1.0)
                subprocess.run(["sync"])
        """
        checks = [f.message for f in findings_of(src, "lock-held-across-io")]
        assert len(checks) == 2

    def test_device_sync_under_lock_caught(self):
        src = """
        def solve(self, arrays):
            with self.mu:
                out = self._kernel(arrays).block_until_ready()
            return out
        """
        assert findings_of(src, "lock-held-across-io")

    def test_event_wait_under_foreign_lock_caught(self):
        src = """
        def run(self):
            with self._lock:
                self._stop.wait(5.0)
        """
        assert findings_of(src, "lock-held-across-io")

    def test_clean_patterns_pass(self):
        src = """
        def ok(self):
            with self._lock:
                self._items["k"] = 1                 # pure bookkeeping
                val = self._clients.get("k")         # dict of clients
                count = rp.restart_counts.get("c", 0)  # dict lookup
            claim = self.resolver.get("pvcs", "name")  # outside the lock
            with self._cond_lock:
                self._cond_lock.wait(0.5)            # Condition self-wait
        """
        assert not findings_of(src, "lock-held-across-io")

    def test_with_lock_acquire_call_caught(self):
        src = """
        def resolve(self, name):
            with self._lock.acquire():
                claim = self.resolver.get("pvcs", name)
        """
        assert findings_of(src, "lock-held-across-io")

    def test_nested_def_in_lock_body_not_flagged(self):
        src = """
        def arm(self):
            with self._lock:
                def later():
                    self.client.get("pods", "p")   # runs after release
                self._cb = later
        """
        assert not findings_of(src, "lock-held-across-io")


# --- replication-lock-io ------------------------------------------------------

class TestReplicationLockIO:
    def test_transport_send_under_lock_caught(self):
        src = """
        class Group:
            def commit(self, entry):
                with self._lock:
                    ok = self.transport.call(m, "append_entries", entry)
                return ok
        """
        hits = findings_of(src, "replication-lock-io")
        assert len(hits) == 1
        assert "transport" in hits[0].message

    def test_replication_rpc_under_lock_caught(self):
        src = """
        class Member:
            def ship(self, peer, entries):
                with self._lock:
                    peer.append_entries(self.term, entries)
        """
        hits = findings_of(src, "replication-lock-io")
        assert len(hits) == 1
        assert "append_entries" in hits[0].message

    def test_fsync_under_lock_caught(self):
        src = """
        import os
        class Member:
            def append(self, line):
                with self._lock:
                    self._wal.write(line)
                    os.fsync(self._wal.fileno())
        """
        hits = findings_of(src, "replication-lock-io")
        assert len(hits) == 1
        assert "fsync" in hits[0].message

    def test_structural_split_passes(self):
        # the shipped shape: stage under the lock, ship + sync outside it,
        # apply under the lock — and the writer batons (commit/ship gates)
        # MAY span the round-trip, that is their job
        src = """
        import os
        class Facade:
            def create(self, key, obj):
                with self._commit_gate:
                    with self._lock:
                        entry = self._stage(key, obj)
                    self.group.commit(entry)
                    os.fsync(self._dirfd)
                    with self._lock:
                        self._apply(entry)
        """
        assert not findings_of(src, "replication-lock-io")

    def test_nested_def_under_lock_not_flagged(self):
        src = """
        class Group:
            def plan(self):
                with self._lock:
                    def later():
                        self.transport.call(m, "append_entries")
                    return later
        """
        assert not findings_of(src, "replication-lock-io")


# --- informer-cache-mutation --------------------------------------------------

class TestCacheMutation:
    def test_store_get_then_mutate_caught(self):
        src = """
        def sync(self, key):
            node = self.node_informer.store.get(key)
            node.status = None
        """
        hits = findings_of(src, "informer-cache-mutation")
        assert len(hits) == 1
        assert "deep_copy" in hits[0].message

    def test_loop_over_lister_mutation_caught(self):
        src = """
        def relabel(self):
            for pod in self.pod_lister.list():
                pod.metadata.labels["x"] = "y"
        """
        assert findings_of(src, "informer-cache-mutation")

    def test_suboject_method_mutation_caught(self):
        src = """
        def append_condition(self, key):
            node = self.store.get(key)
            node.status.conditions.append(1)
        """
        assert findings_of(src, "informer-cache-mutation")

    def test_deep_copy_then_mutate_passes(self):
        src = """
        def sync(self, key):
            node = self.node_informer.store.get(key)
            fresh = deep_copy(node)
            fresh.status = None
            self.client.update_status("nodes", fresh)
        """
        assert not findings_of(src, "informer-cache-mutation")

    def test_fresh_client_object_mutation_passes(self):
        src = """
        def sync(self, key):
            pod = self.client.get("pods", key)   # fresh decode, not cached
            pod.status = None
        """
        assert not findings_of(src, "informer-cache-mutation")

    def test_rebound_name_is_untainted(self):
        src = """
        def sync(self, key):
            obj = self.store.get(key)
            obj = deep_copy(obj)
            obj.status = None
        """
        assert not findings_of(src, "informer-cache-mutation")


# --- host-sync-in-kernel ------------------------------------------------------

class TestHostSync:
    def test_item_and_asarray_in_jit_caught(self):
        src = """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            v = x.sum().item()
            host = np.asarray(x)
            return v, host
        """
        checks = {f.message.split()[0]
                  for f in findings_of(src, "host-sync-in-kernel")}
        assert len(findings_of(src, "host-sync-in-kernel")) == 2

    def test_traced_branch_caught_static_branch_passes(self):
        src = """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def kernel(x, mode):
            if mode == "fast":      # static: fine
                y = x * 2
            else:
                y = x
            if x > 0:               # traced: finding
                y = y + 1
            return y
        """
        hits = findings_of(src, "host-sync-in-kernel")
        assert len(hits) == 1
        assert "'x'" in hits[0].message

    def test_helper_reachable_from_jit_is_kernel_path(self):
        src = """
        import jax

        def helper(x):
            return float(x)         # sync inside the kernel call graph

        @jax.jit
        def kernel(x):
            return helper(x)
        """
        assert findings_of(src, "host-sync-in-kernel")

    def test_host_constants_and_metadata_pass(self):
        src = """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            table = np.asarray([1, 0, 1])    # literal: host constant
            chans = []
            chans.append(3)
            idx = np.asarray(chans)          # host-built list
            n = int(x.shape[0])              # static metadata
            if x.shape[0] > 4:               # static branch
                x = x[:4]
            return x, table, idx, n
        """
        assert not findings_of(src, "host-sync-in-kernel")

    def test_non_jax_module_ignored(self):
        src = """
        def plain(x):
            return float(x)
        """
        assert not findings_of(src, "host-sync-in-kernel")


# --- hygiene: swallowed-exception / monotonic-duration / nondaemon-thread -----

class TestHygiene:
    def test_silent_broad_except_caught(self):
        src = """
        def sync(self):
            try:
                self.reconcile()
            except Exception:
                pass
        """
        assert findings_of(src, "swallowed-exception")

    def test_bare_except_continue_caught(self):
        src = """
        def loop(self):
            for item in self.items:
                try:
                    self.step(item)
                except:
                    continue
        """
        assert findings_of(src, "swallowed-exception")

    def test_handled_excepts_pass(self):
        src = """
        def sync(self):
            try:
                self.reconcile()
            except ApiError:
                pass                      # typed: a decision, not a swallow
            try:
                self.reconcile()
            except Exception:
                log.exception("failed")   # logged
            try:
                self.reconcile()
            except Exception as e:
                ok = False                # fallback value is handling
            try:
                self.reconcile()
            except Exception:
                raise
        """
        assert not findings_of(src, "swallowed-exception")

    def test_wallclock_duration_and_deadline_caught(self):
        src = """
        def tick(self):
            elapsed = time.time() - self.started
            if time.time() > self.deadline:
                return True
        """
        assert len(findings_of(src, "monotonic-duration")) == 2

    def test_wallclock_clock_default_caught(self):
        src = """
        def __init__(self, clock=time.time):
            self._clock = clock
        """
        assert findings_of(src, "monotonic-duration")

    def test_monotonic_and_serialization_pass(self):
        src = """
        def tick(self):
            elapsed = time.monotonic() - self.started
            stamp = time.time()           # bare wall read: a timestamp
            meta.creation_timestamp = stamp
        """
        assert not findings_of(src, "monotonic-duration")

    def test_thread_without_daemon_caught(self):
        src = """
        def start(self):
            t = threading.Thread(target=self._loop)
            t.start()
        """
        assert findings_of(src, "nondaemon-thread")

    def test_thread_with_daemon_passes(self):
        src = """
        def start(self):
            t = threading.Thread(target=self._loop, daemon=True)
            u = threading.Thread(target=self._loop, daemon=False)
            u.start()
            u.join()
        """
        assert not findings_of(src, "nondaemon-thread")


# --- suppressions & baseline --------------------------------------------------

class TestLeakedSpan:
    def test_straight_line_finish_caught(self):
        src = """
        from kubernetes_tpu.utils.trace import Span
        def handle(self):
            sp = Span("work")
            self.do_things()
            sp.finish()
        """
        assert findings_of(src, "leaked-span")

    def test_never_finished_caught(self):
        src = """
        from kubernetes_tpu.utils.trace import Span
        def handle(self):
            sp = Span("work")
            self.do_things()
        """
        assert findings_of(src, "leaked-span")

    def test_bare_constructor_caught(self):
        src = """
        from kubernetes_tpu.utils.trace import Span
        def handle(self):
            Span("dropped")
        """
        assert findings_of(src, "leaked-span")

    def test_finally_finish_passes(self):
        src = """
        from kubernetes_tpu.utils.trace import Span
        def handle(self):
            sp = Span("work")
            try:
                self.do_things()
            finally:
                sp.finish()
        """
        assert not findings_of(src, "leaked-span")

    def test_ownership_handoff_passes(self):
        src = """
        from kubernetes_tpu.utils.trace import Span
        def returned(self):
            sp = Span("a")
            return sp
        def stored(self):
            sp = Span("b")
            self.span = sp
        def contained(self, key):
            sp = Span("c")
            self.live[key] = [sp, None]
        """
        assert not findings_of(src, "leaked-span")

    def test_attribute_read_is_not_a_handoff(self):
        # reading sp.trace_id must not launder the straight-line-finish
        # leak; handing the OBJECT somewhere still does
        src = """
        from kubernetes_tpu.utils.trace import Span
        def handle(self):
            sp = Span("work")
            tid = sp.trace_id
            self.do_things(tid)
            sp.finish()
        """
        assert findings_of(src, "leaked-span")

    def test_non_span_calls_ignored(self):
        src = """
        def handle(self):
            q = Queue("work")
            self.do_things(q)
        """
        assert not findings_of(src, "leaked-span")


class TestSuppressionsAndBaseline:
    BAD = """
    def sync(self):
        try:
            self.reconcile()
        except Exception:
            pass
    """

    def test_same_line_suppression(self):
        src = self.BAD.replace(
            "except Exception:",
            "except Exception:  # kube-verify: disable=swallowed-exception")
        assert not findings_of(src, "swallowed-exception")

    def test_next_line_suppression(self):
        src = self.BAD.replace(
            "    except Exception:",
            "    # kube-verify: disable-next-line=swallowed-exception\n"
            "    except Exception:")
        assert not findings_of(src, "swallowed-exception")

    def test_file_level_suppression(self):
        src = ("# kube-verify: disable-file=swallowed-exception\n"
               + textwrap.dedent(self.BAD))
        assert not analyze_source(src)

    def test_suppression_is_check_specific(self):
        src = self.BAD.replace(
            "except Exception:",
            "except Exception:  # kube-verify: disable=monotonic-duration")
        assert findings_of(src, "swallowed-exception")

    def test_baseline_roundtrip(self, tmp_path):
        bad = tmp_path / "mod.py"
        bad.write_text(textwrap.dedent(self.BAD))
        results = analyze_paths([str(bad)])
        assert results["new"] and not results["baselined"]

        bl_path = tmp_path / "baseline.json"
        Baseline.write(str(bl_path), results["new"])
        results2 = analyze_paths([str(bad)],
                                 baseline=Baseline.load(str(bl_path)))
        assert not results2["new"] and results2["baselined"]

    def test_baseline_survives_line_moves_not_code_changes(self, tmp_path):
        bad = tmp_path / "mod.py"
        bad.write_text(textwrap.dedent(self.BAD))
        results = analyze_paths([str(bad)])
        bl_path = tmp_path / "baseline.json"
        Baseline.write(str(bl_path), results["new"])
        # shift the code down: fingerprint (line-insensitive) still matches
        bad.write_text("\n\n\n" + textwrap.dedent(self.BAD))
        shifted = analyze_paths([str(bad)],
                                baseline=Baseline.load(str(bl_path)))
        assert not shifted["new"]


class TestCLI:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert cli_main([str(clean), "--no-baseline"]) == 0

        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent(TestSuppressionsAndBaseline.BAD))
        assert cli_main([str(bad), "--no-baseline"]) == 1
        capsys.readouterr()

        assert cli_main([str(bad), "--no-baseline", "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["summary"]["new"] == 1
        assert payload["findings"][0]["check"] == "swallowed-exception"

    def test_select_unknown_checker_is_usage_error(self, tmp_path):
        assert cli_main([str(tmp_path), "--select", "no-such-check"]) == 2

    def test_missing_path_is_io_error_exit(self, tmp_path):
        assert cli_main([str(tmp_path / "nope.py"), "--no-baseline"]) == 2

    def test_unreadable_file_is_io_error_finding(self, tmp_path, monkeypatch):
        # root ignores file modes, so simulate the open() failure instead
        import builtins
        p = tmp_path / "secret.py"
        p.write_text("x = 1\n")
        real_open = builtins.open

        def deny(path, *a, **kw):
            if str(path) == str(p):
                raise PermissionError(13, "Permission denied", str(path))
            return real_open(path, *a, **kw)

        monkeypatch.setattr(builtins, "open", deny)
        assert cli_main([str(p), "--no-baseline"]) == 2

    def test_fingerprints_distinguish_same_named_files(self, tmp_path):
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        body = textwrap.dedent(TestSuppressionsAndBaseline.BAD)
        (tmp_path / "a" / "__init__.py").write_text(body)
        (tmp_path / "b" / "__init__.py").write_text(body)
        results = analyze_paths([str(tmp_path)])
        fps = {f.fingerprint() for f in results["new"]}
        assert len(fps) == 2  # same code, different packages: no collision

    def test_list_checks(self, capsys):
        assert cli_main(["--list-checks"]) == 0
        out = capsys.readouterr().out
        for name in ("lock-held-across-io", "informer-cache-mutation",
                     "host-sync-in-kernel", "swallowed-exception",
                     "monotonic-duration", "nondaemon-thread"):
            assert name in out


# --- runtime race detectors ---------------------------------------------------

class TestLockOrderTracker:
    def test_inversion_detected(self):
        tr = race.LockOrderTracker()
        a = race.InstrumentedLock(threading.Lock(), "mod.py:10", tr)
        b = race.InstrumentedLock(threading.Lock(), "mod.py:20", tr)
        with a:
            with b:
                pass

        def invert():
            with b:
                with a:
                    pass

        t = threading.Thread(target=invert, daemon=True)
        t.start()
        t.join()
        assert tr.violations and "inversion" in tr.violations[0]
        assert "mod.py:10" in tr.violations[0]
        # seeded on purpose: consume before the conftest teardown hook
        assert any("inversion" in v for v in race.drain_violations())

    def test_consistent_order_is_clean(self):
        tr = race.LockOrderTracker()
        a = race.InstrumentedLock(threading.Lock(), "mod.py:10", tr)
        b = race.InstrumentedLock(threading.Lock(), "mod.py:20", tr)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert not tr.violations

    def test_three_lock_cycle_detected(self):
        tr = race.LockOrderTracker()
        locks = {s: race.InstrumentedLock(threading.Lock(), s, tr)
                 for s in ("s1", "s2", "s3")}

        def nest(first, second):
            with locks[first]:
                with locks[second]:
                    pass

        for first, second in (("s1", "s2"), ("s2", "s3"), ("s3", "s1")):
            t = threading.Thread(target=nest, args=(first, second),
                                 daemon=True)
            t.start()
            t.join()
        assert tr.violations
        race.drain_violations()

    def test_rlock_reentry_is_not_an_edge(self):
        tr = race.LockOrderTracker()
        a = race.InstrumentedLock(threading.RLock(), "mod.py:10", tr)
        with a:
            with a:   # re-entry, not ordering
                pass
        assert not tr.violations

    def test_same_site_locks_do_not_self_cycle(self):
        tr = race.LockOrderTracker()
        # two per-pod locks minted by the same line = one order class
        a = race.InstrumentedLock(threading.Lock(), "pod_lock.py:5", tr)
        b = race.InstrumentedLock(threading.Lock(), "pod_lock.py:5", tr)
        with a:
            with b:
                pass
        assert not tr.violations


class TestCheckedStore:
    def setup_method(self):
        self._was_enabled = race.checked_store_enabled()
        race.enable_checked_store()

    def teardown_method(self):
        # restore: under KTPU_NO_RACE_DETECT=1 the suite-wide mode is OFF
        # and must stay off after these tests
        if not self._was_enabled:
            race.disable_checked_store()
        race.drain_violations()

    def test_seeded_mutation_detected(self):
        from kubernetes_tpu.client.cache import ThreadSafeStore
        store = ThreadSafeStore(name="pods")
        pod = api.Pod(metadata=api.ObjectMeta(name="p", namespace="default",
                                              labels={"app": "web"}))
        store.add("default/p", pod)
        cached = store.get("default/p")
        cached.metadata.labels["app"] = "mutated"   # the seeded bug
        store.get("default/p")
        violations = race.drain_violations()
        assert violations and "default/p" in violations[0]

    def test_mutation_seen_via_list_too(self):
        from kubernetes_tpu.client.cache import ThreadSafeStore
        store = ThreadSafeStore(name="nodes")
        node = api.Node(metadata=api.ObjectMeta(name="n1"))
        store.add("n1", node)
        store.list()[0].metadata.labels = {"oops": "1"}
        store.list()
        assert race.drain_violations()

    def test_clean_readers_pass(self):
        from kubernetes_tpu.api.serialization import deep_copy
        from kubernetes_tpu.client.cache import ThreadSafeStore
        store = ThreadSafeStore(name="pods")
        pod = api.Pod(metadata=api.ObjectMeta(name="p", namespace="default"))
        store.add("default/p", pod)
        fresh = deep_copy(store.get("default/p"))
        fresh.metadata.labels = {"fine": "yes"}     # copy, not the cache
        store.get("default/p")
        store.list()
        assert not race.peek_violations()

    def test_rewrite_refreshes_fingerprint(self):
        from kubernetes_tpu.client.cache import ThreadSafeStore
        store = ThreadSafeStore(name="pods")
        store.add("k", api.Pod(metadata=api.ObjectMeta(name="p")))
        updated = api.Pod(metadata=api.ObjectMeta(
            name="p", labels={"v": "2"}))
        store.update("k", updated)                  # write path, not a race
        store.get("k")
        assert not race.peek_violations()


# --- listers deep-copy on read ------------------------------------------------

class TestListerCopyOnRead:
    def _store_with_pod(self):
        from kubernetes_tpu.client.cache import ThreadSafeStore
        store = ThreadSafeStore(name="pods")
        store.add("default/p", api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="default",
                                    labels={"app": "web"})))
        return store

    def test_lister_hands_out_copies(self):
        from kubernetes_tpu.client.listers import PodLister
        store = self._store_with_pod()
        lister = PodLister(store)
        pod = lister.list()[0]
        pod.metadata.labels["app"] = "scribbled"    # consumer owns the copy
        store.get("default/p")
        assert not race.peek_violations()
        assert store.get("default/p").metadata.labels["app"] == "web"

    def test_hot_path_opt_out_shares(self):
        from kubernetes_tpu.client.listers import PodLister
        store = self._store_with_pod()
        lister = PodLister(store, copy_on_read=False)
        assert lister.list()[0] is store.get("default/p")


# --- the self-hosting gate ----------------------------------------------------

class TestSelfHosting:
    def test_package_is_clean_under_its_own_analyzer(self):
        import kubernetes_tpu
        pkg_dir = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))
        results = analyze_paths(
            [pkg_dir], baseline=Baseline.load(default_baseline_path()))
        new = results["new"]
        assert not new, (
            "kube-verify found non-baselined violations in kubernetes_tpu/ "
            "— fix them or suppress with a justification:\n" + "\n".join(
                f"{f.path}:{f.line}: [{f.check}] {f.message}" for f in new))

    def test_volume_manager_regression_snippet_still_caught(self):
        """The round-5 bug this PR exists to make unshippable: PVC
        resolution (apiserver HTTP) under the manager-wide lock."""
        src = """
        def setup_pod(self, pod):
            with self._lock:
                claim = self.resolver.get(
                    "persistentvolumeclaims", "data", "default")
                pv = self.resolver.get("persistentvolumes", claim)
        """
        assert len(findings_of(src, "lock-held-across-io")) == 2
