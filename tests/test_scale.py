"""Control-plane scale proof: 1k nodes / 30k pods through the LIVE stack.

The in-tree analogue of the reference's TestSchedule1000Node30KPods
(test/component/scheduler/perf/scheduler_test.go:31, util.go:85-131): an
in-process apiserver, the full informer/FIFO/binder machinery, and the
batch scheduler — not just the kernel. SLOs asserted per the density
suite's contract (test/e2e/framework/metrics_util.go:44-49):

- saturation throughput >= 8 pods/s (the reference floor; the batch
  scheduler clears it by orders of magnitude),
- API request p99 <= 1 s (the >500-node cluster bound),
- zero unscheduled pods, zero node overcommit, kernel health ok.

Runs CPU-only on the virtual device mesh. SCALE_NODES / SCALE_PODS shrink
it for quick local iterations; defaults are the reference shape.
"""

import os
import time
from concurrent.futures import ThreadPoolExecutor

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.scheduler.factory import ConfigFactory
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

N_NODES = int(os.environ.get("SCALE_NODES", 1000))
N_PODS = int(os.environ.get("SCALE_PODS", 30000))


def mk_node(i):
    # reference shape: 4 CPU / 32Gi / 110-pod cap (util.go:85-111)
    return api.Node(
        metadata=api.ObjectMeta(
            name=f"node-{i:04d}",
            labels={api.LABEL_HOSTNAME: f"node-{i:04d}",
                    api.LABEL_ZONE: f"z{i % 4}"}),
        spec=api.NodeSpec(),
        status=api.NodeStatus(
            allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


def mk_pod(i):
    # pause pods requesting 100m / 500Mi (util.go:113-131)
    return api.Pod(
        metadata=api.ObjectMeta(name=f"pod-{i:05d}", namespace="default",
                                labels={"app": "pause"}),
        spec=api.PodSpec(containers=[api.Container(
            name="pause", image="pause",
            resources=api.ResourceRequirements(
                requests={"cpu": "100m", "memory": "500Mi"}))]))


class TestSchedule30KPods1KNodes:
    def test_live_control_plane_at_scale(self):
        server = APIServer().start()
        factory = sched = None
        try:
            client = RESTClient.for_server(server, qps=100000, burst=100000)
            with ThreadPoolExecutor(max_workers=32) as pool:
                list(pool.map(lambda i: client.create("nodes", mk_node(i)),
                              range(N_NODES)))
                list(pool.map(lambda i: client.create("pods", mk_pod(i)),
                              range(N_PODS)))

            factory = ConfigFactory(client)
            factory.run(timeout=120)
            deadline = time.monotonic() + 180
            while (len(factory.pending) < N_PODS
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert len(factory.pending) == N_PODS, (
                f"only {len(factory.pending)} pods queued")
            assert len(factory.node_lister.list()) == N_NODES

            sched = factory.create_batch_from_provider(batch_size=4096)
            E2E = "scheduler_e2e_scheduling_latency_seconds"
            API = "apiserver_request_seconds"
            base = METRICS.hist_total(E2E)
            api_snap = METRICS.hist_snapshot(API)
            t0 = time.perf_counter()
            sched.run()
            deadline = time.monotonic() + 300
            bound = 0
            while time.monotonic() < deadline:
                bound = METRICS.hist_total(E2E) - base
                if bound >= N_PODS:
                    break
                time.sleep(0.05)
            elapsed = time.perf_counter() - t0

            assert bound == N_PODS, (
                f"{bound}/{N_PODS} bound in {elapsed:.0f}s; "
                f"health={sched.health} failures={sched.kernel_failures}")
            rate = N_PODS / elapsed
            # density-suite saturation SLO floor (density.go:46-47)
            assert rate >= 8.0, f"{rate:.1f} pods/s under the 8 pods/s SLO"
            # API p99 <= 1s for >500-node clusters (metrics_util.go:46-49);
            # labeled per verb over the scheduling window, worst verb
            # counts; a verb with no requests in the window is NaN
            # ("no samples", not zero) and is skipped
            import math
            qs = [METRICS.delta_quantile(API, api_snap, 0.99, verb=v)
                  for v in ("GET", "POST", "PUT", "DELETE")]
            finite = [q for q in qs if math.isfinite(q)]
            assert finite, "no API requests observed in the window"
            p99 = max(finite)
            assert 0 < p99 <= 1.0, f"API p99 {p99:.3f}s busts the 1s SLO"
            assert sched.kernel_failures == 0 and sched.health == "ok", (
                sched.disabled_reason)

            # no overcommit: authoritative state via one LIST
            pods, _ = client.list("pods", "default")
            per_node = {}
            for p in pods:
                assert p.spec.node_name, f"{p.metadata.name} unbound"
                per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
            assert max(per_node.values()) <= 110
            assert max(per_node.values()) * 100 <= 4000  # 100m each, 4 CPU

            print(f"\nscale proof: {N_PODS} pods / {N_NODES} nodes bound in "
                  f"{elapsed:.1f}s = {rate:.0f} pods/s; API p99 {p99 * 1e3:.0f}ms; "
                  f"batches={sched.kernel_batches}")
        finally:
            if sched is not None:
                sched.stop()
            if factory is not None:
                factory.stop()
            server.stop()
