"""Multi-version API: v2 wire version with conversion + defaulting.

Parity targets: pkg/runtime/scheme.go:43 (one internal form, many wire
versions), pkg/conversion/converter.go (registered + reflective conversion),
pkg/api/v1/defaults.go (versioned defaulting on decode). Round-trip coverage
mirrors the reference's api/serialization roundtrip tests.
"""

import http.client
import json

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.conversion import ConversionError, converter
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict
from kubernetes_tpu.apis import v2
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient

from tests.test_scheduler_e2e import mk_node, mk_pod


def rich_pod():
    return api.Pod(
        metadata=api.ObjectMeta(name="rich", namespace="default",
                                labels={"app": "web"}),
        spec=api.PodSpec(
            node_name="n1",
            scheduler_name="custom-sched",
            node_selector={"disk": "ssd"},
            restart_policy="OnFailure",
            service_account_name="svc",
            tolerations=[api.Toleration(key="k", operator="Exists",
                                        effect="NoSchedule")],
            containers=[api.Container(
                name="c", image="img",
                ports=[api.ContainerPort(container_port=80)],
                resources=api.ResourceRequirements(
                    requests={"cpu": "100m"}))]),
        status=api.PodStatus(phase="Running", pod_ip="10.0.0.1"))


class TestConversion:
    def test_pod_round_trips_through_v2(self):
        p = rich_pod()
        p2 = converter.convert(p, v2.Pod)
        # the v2 restructuring actually happened
        assert p2.spec.node_ref.kind == "Node"
        assert p2.spec.node_ref.name == "n1"
        assert p2.spec.scheduling.scheduler_name == "custom-sched"
        assert p2.spec.scheduling.node_selector == {"disk": "ssd"}
        assert not hasattr(p2.spec, "node_name")
        back = converter.convert(p2, api.Pod)
        assert to_dict(back) == to_dict(p)

    def test_unscheduled_pod_has_no_node_ref(self):
        p = mk_pod("pending")
        p2 = converter.convert(p, v2.Pod)
        assert p2.spec.node_ref is None
        back = converter.convert(p2, api.Pod)
        assert back.spec.node_name == ""

    def test_node_round_trips_via_reflective_default(self):
        n = mk_node("worker", labels={"zone": "z1"})
        n.spec = api.NodeSpec(pod_cidr="10.1.0.0/24", unschedulable=True)
        n2 = converter.convert(n, v2.Node)
        assert isinstance(n2, v2.Node)
        assert n2.spec.pod_cidr == "10.1.0.0/24"
        back = converter.convert(n2, api.Node)
        assert to_dict(back) == to_dict(n)

    def test_non_dataclass_target_raises(self):
        # the reflective default covers any dataclass pair (like the
        # reference's DefaultConvert); only non-struct targets are an error
        with pytest.raises(ConversionError):
            converter.convert(api.Pod(), str)

    def test_v2_wire_shape(self):
        """The encoded v2 JSON really differs from v1: nodeRef object,
        scheduling struct, no nodeName/schedulerName keys."""
        d = scheme.encode(converter.convert(rich_pod(), v2.Pod))
        assert d["apiVersion"] == "v2"
        assert d["spec"]["nodeRef"] == {"kind": "Node", "name": "n1"}
        assert d["spec"]["scheduling"]["schedulerName"] == "custom-sched"
        assert "nodeName" not in d["spec"]
        assert "schedulerName" not in d["spec"]
        # v1 for contrast
        d1 = scheme.encode(rich_pod())
        assert d1["spec"]["nodeName"] == "n1"
        assert "scheduling" not in d1["spec"]


class TestDefaulting:
    def test_restart_policy_and_protocol_defaulted_on_v2_decode(self):
        body = {"apiVersion": "v2", "kind": "Pod",
                "metadata": {"name": "d", "namespace": "default"},
                "spec": {"containers": [
                    {"name": "c", "image": "img",
                     "ports": [{"containerPort": 80}]}]}}
        obj2 = from_dict(v2.Pod, body)
        from kubernetes_tpu.api.conversion import defaulter
        defaulter.default(obj2)
        assert obj2.spec.restart_policy == "Always"
        assert obj2.spec.containers[0].ports[0].protocol == "TCP"

    def test_explicit_values_not_overwritten(self):
        obj2 = from_dict(v2.Pod, {
            "spec": {"restartPolicy": "Never",
                     "containers": [{"name": "c", "ports": [
                         {"containerPort": 1, "protocol": "UDP"}]}]}})
        from kubernetes_tpu.api.conversion import defaulter
        defaulter.default(obj2)
        assert obj2.spec.restart_policy == "Never"
        assert obj2.spec.containers[0].ports[0].protocol == "UDP"


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=1000, burst=1000)


def _raw(server, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    payload = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"} if payload else {}
    conn.request(method, path, body=payload, headers=headers)
    resp = conn.getresponse()
    data = json.loads(resp.read() or b"{}")
    conn.close()
    return resp.status, data


class TestServedV2:
    def test_discovery_lists_both_versions(self, server):
        code, d = _raw(server, "GET", "/api")
        assert code == 200 and d["versions"] == ["v1", "v2"]

    def test_create_v1_read_v2(self, server, client):
        client.create("pods", rich_pod())
        code, d = _raw(server, "GET", "/api/v2/namespaces/default/pods/rich")
        assert code == 200
        assert d["apiVersion"] == "v2"
        assert d["spec"]["nodeRef"]["name"] == "n1"
        assert d["spec"]["scheduling"]["schedulerName"] == "custom-sched"
        assert "nodeName" not in d["spec"]

    def test_create_v2_read_v1_with_defaults(self, server, client):
        body = {"apiVersion": "v2", "kind": "Pod",
                "metadata": {"name": "viatwo", "namespace": "default"},
                "spec": {"scheduling": {"nodeSelector": {"disk": "ssd"}},
                         "containers": [{"name": "c", "image": "img"}]}}
        code, d = _raw(server, "POST", "/api/v2/namespaces/default/pods", body)
        assert code == 201, d
        assert d["apiVersion"] == "v2"  # response in the request's version
        p = client.get("pods", "viatwo", "default")
        assert p.spec.node_selector == {"disk": "ssd"}
        assert p.spec.restart_policy == "Always"  # v2 defaulting applied

    def test_update_v2_visible_v1(self, server, client):
        client.create("pods", mk_pod("edit"))
        code, d = _raw(server, "GET", "/api/v2/namespaces/default/pods/edit")
        d["metadata"]["labels"] = {"touched": "yes"}
        code, out = _raw(server, "PUT",
                         "/api/v2/namespaces/default/pods/edit", d)
        assert code == 200, out
        assert client.get("pods", "edit", "default").metadata.labels == \
            {"touched": "yes"}

    def test_list_v2(self, server, client):
        client.create("pods", rich_pod())
        client.create("pods", mk_pod("plain"))
        code, d = _raw(server, "GET", "/api/v2/namespaces/default/pods")
        assert code == 200
        assert d["apiVersion"] == "v2" and d["kind"] == "PodList"
        by_name = {i["metadata"]["name"]: i for i in d["items"]}
        assert by_name["rich"]["spec"]["nodeRef"]["name"] == "n1"
        assert "nodeName" not in by_name["rich"]["spec"]

    def test_nodes_served_at_v2(self, server, client):
        client.create("nodes", mk_node("n9"))
        code, d = _raw(server, "GET", "/api/v2/nodes/n9")
        assert code == 200 and d["apiVersion"] == "v2"
        assert d["status"]["allocatable"]["cpu"] == "4"

    def test_unserved_resource_404s_at_v2(self, server, client):
        code, d = _raw(server, "GET", "/api/v2/namespaces/default/services")
        assert code == 404
        code, _ = _raw(server, "GET", "/api/v3/namespaces/default/pods")
        assert code == 404

    def test_watch_v2_frames(self, server, client):
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
        conn.request("GET", "/api/v2/namespaces/default/pods?watch=true")
        resp = conn.getresponse()
        client.create("pods", rich_pod())
        line = resp.readline().strip()
        while not line:
            line = resp.readline().strip()
        frame = json.loads(line)
        assert frame["type"] == "ADDED"
        assert frame["object"]["apiVersion"] == "v2"
        assert frame["object"]["spec"]["nodeRef"]["name"] == "n1"
        conn.close()

    def test_scheduler_sees_v2_created_pod(self, server, client):
        """Storage is version-independent: a pod created through v2 is
        scheduled by the v1-speaking scheduler."""
        from kubernetes_tpu.scheduler.factory import ConfigFactory
        import time
        client.create("nodes", mk_node("n1"))
        f = ConfigFactory(client)
        f.run()
        s = f.create_from_provider().run()
        try:
            body = {"apiVersion": "v2", "kind": "Pod",
                    "metadata": {"name": "sched2", "namespace": "default"},
                    "spec": {"containers": [{"name": "c", "image": "img"}]}}
            code, _ = _raw(server, "POST",
                           "/api/v2/namespaces/default/pods", body)
            assert code == 201
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                p = client.get("pods", "sched2", "default")
                if p.spec.node_name:
                    break
                time.sleep(0.05)
            assert p.spec.node_name == "n1"
            # and the binding is visible in v2 shape
            code, d = _raw(server, "GET",
                           "/api/v2/namespaces/default/pods/sched2")
            assert d["spec"]["nodeRef"] == {"kind": "Node", "name": "n1"}
        finally:
            s.stop()
            f.stop()
