"""Federation: one control plane propagating into member clusters.

Parity target: reference federation/ (round-4 verdict missing #7) —
cluster registry with health-probed Ready conditions, federated objects
created/updated/deleted across every ready member, and member status
aggregated back to the federated object.
"""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apis import federation as fedapi
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.federation import (
    ClusterHealthController, FederationSyncController,
)


def wait_for(cond, timeout=20.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def mk_cluster(name, server, ready=True):
    c = fedapi.Cluster(
        metadata=api.ObjectMeta(name=name),
        spec=fedapi.ClusterSpec(server_address=f"127.0.0.1:{server.port}"))
    if ready:
        c.status = fedapi.ClusterStatus(conditions=[
            fedapi.ClusterCondition(type=fedapi.CLUSTER_READY,
                                    status=api.CONDITION_TRUE)])
    return c


def mk_rc(name="app", replicas=3):
    return api.ReplicationController(
        metadata=api.ObjectMeta(name=name, namespace="default"),
        spec=api.ReplicationControllerSpec(
            replicas=replicas, selector={"app": name},
            template=api.PodTemplateSpec(
                metadata=api.ObjectMeta(labels={"app": name}),
                spec=api.PodSpec(containers=[
                    api.Container(name="c", image="img:1")]))))


@pytest.fixture()
def planes():
    fed = APIServer().start()
    m1 = APIServer().start()
    m2 = APIServer().start()
    try:
        yield fed, m1, m2
    finally:
        for s in (fed, m1, m2):
            s.stop()


class TestClusterHealth:
    def test_ready_condition_probed(self, planes):
        fed, m1, _ = planes
        fed_client = RESTClient.for_server(fed)
        fed_client.create("clusters", mk_cluster("c1", m1, ready=False))
        dead = fedapi.Cluster(metadata=api.ObjectMeta(name="dead"),
                              spec=fedapi.ClusterSpec(
                                  server_address="127.0.0.1:1"))
        fed_client.create("clusters", dead)
        ctrl = ClusterHealthController(fed_client, probe_period=0.5)
        ctrl.start()
        try:
            def cond(name):
                c = fed_client.get("clusters", name)
                for cc in (c.status.conditions or []) if c.status else []:
                    if cc.type == fedapi.CLUSTER_READY:
                        return cc.status
                return None
            wait_for(lambda: cond("c1") == api.CONDITION_TRUE,
                     msg="live member Ready=True")
            wait_for(lambda: cond("dead") == api.CONDITION_FALSE,
                     msg="dead member Ready=False")
        finally:
            ctrl.stop()


class TestFederatedSync:
    def test_create_update_delete_propagate(self, planes):
        fed, m1, m2 = planes
        fed_client = RESTClient.for_server(fed)
        c1, c2 = RESTClient.for_server(m1), RESTClient.for_server(m2)
        fed_client.create("clusters", mk_cluster("c1", m1))
        fed_client.create("clusters", mk_cluster("c2", m2))
        ctrl = FederationSyncController(fed_client)
        ctrl.start()
        try:
            fed_client.create("replicationcontrollers", mk_rc(replicas=3))

            def in_member(client):
                try:
                    return client.get("replicationcontrollers", "app",
                                      "default")
                except ApiError:
                    return None
            r1 = wait_for(lambda: in_member(c1), msg="rc in member 1")
            r2 = wait_for(lambda: in_member(c2), msg="rc in member 2")
            assert r1.spec.replicas == 3 and r2.spec.replicas == 3
            assert (r1.metadata.annotations or {}).get(
                "federation.kubernetes.io/managed-by")

            # update propagates
            fed_client.patch("replicationcontrollers", "app",
                             {"spec": {"replicas": 5}}, "default")
            wait_for(lambda: in_member(c1).spec.replicas == 5
                     and in_member(c2).spec.replicas == 5,
                     msg="scale propagated")

            # member status aggregates back up
            for member in (c1, c2):
                rc = in_member(member)
                rc.status = api.ReplicationControllerStatus(replicas=5)
                member.update_status("replicationcontrollers", rc)
            wait_for(lambda: (lambda f: f.status is not None
                              and f.status.replicas == 10)(
                fed_client.get("replicationcontrollers", "app", "default")),
                msg="aggregated status 2x5")

            # deletion cascades
            fed_client.delete("replicationcontrollers", "app", "default")
            wait_for(lambda: in_member(c1) is None and in_member(c2) is None,
                     msg="cascading delete")
        finally:
            ctrl.stop()

    def test_unready_member_skipped_then_caught_up(self, planes):
        fed, m1, m2 = planes
        fed_client = RESTClient.for_server(fed)
        c2 = RESTClient.for_server(m2)
        fed_client.create("clusters", mk_cluster("c1", m1))
        fed_client.create("clusters", mk_cluster("c2", m2, ready=False))
        ctrl = FederationSyncController(fed_client)
        ctrl.start()
        try:
            fed_client.create("secrets", api.Secret(
                metadata=api.ObjectMeta(name="creds", namespace="default"),
                data={"k": "dg=="}))
            wait_for(lambda: _get(RESTClient.for_server(m1), "secrets",
                                  "creds"), msg="secret in ready member")
            time.sleep(0.3)
            assert _get(c2, "secrets", "creds") is None  # unready: skipped
            # member becomes ready -> catch-up
            cl = fed_client.get("clusters", "c2")
            cl.status = fedapi.ClusterStatus(conditions=[
                fedapi.ClusterCondition(type=fedapi.CLUSTER_READY,
                                        status=api.CONDITION_TRUE)])
            fed_client.update_status("clusters", cl)
            wait_for(lambda: _get(c2, "secrets", "creds"),
                     msg="catch-up after Ready")
        finally:
            ctrl.stop()

    def test_member_drift_reconciled(self, planes):
        fed, m1, _ = planes
        fed_client = RESTClient.for_server(fed)
        c1 = RESTClient.for_server(m1)
        fed_client.create("clusters", mk_cluster("c1", m1))
        ctrl = FederationSyncController(fed_client)
        ctrl.start()
        try:
            fed_client.create("replicationcontrollers", mk_rc(replicas=2))
            wait_for(lambda: _get(c1, "replicationcontrollers", "app"),
                     msg="propagated")
            # someone edits the member copy directly: drift
            rc = c1.get("replicationcontrollers", "app", "default")
            rc.spec.replicas = 9
            c1.update("replicationcontrollers", rc)
            # any federation-side touch reconciles it back
            fed_client.patch("replicationcontrollers", "app",
                             {"metadata": {"labels": {"touch": "1"}}},
                             "default")
            wait_for(lambda: _get(c1, "replicationcontrollers",
                                  "app").spec.replicas == 2,
                     msg="drift reconciled to federated spec")
        finally:
            ctrl.stop()


def _get(client, resource, name, ns="default"):
    try:
        return client.get(resource, name, ns)
    except ApiError:
        return None


def test_entrypoint_runs():
    import subprocess
    import sys
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.federation", "--port", "0"],
        stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert "federation apiserver listening on" in line, line
        port = int(line.strip().rsplit(":", 1)[1])
        member = APIServer().start()
        try:
            fed_client = RESTClient(port=port)
            fed_client.create("clusters", mk_cluster("m", member,
                                                     ready=False))
            fed_client.create("configmaps", api.ConfigMap(
                metadata=api.ObjectMeta(name="cfg", namespace="default"),
                data={"a": "b"}))
            mc = RESTClient.for_server(member)
            wait_for(lambda: _get(mc, "configmaps", "cfg"),
                     msg="configmap propagated via the entrypoint plane")
        finally:
            member.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
