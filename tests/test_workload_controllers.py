"""ReplicaSet / Deployment / DaemonSet / Job controllers against a live
in-process cluster (reference pkg/controller/{replicaset,deployment,daemon,job}
unit+integration shapes)."""

import time

import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apis import batch, extensions as ext
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.controllers.daemonset_controller import DaemonSetController
from kubernetes_tpu.controllers.deployment_controller import (
    DeploymentController, resolve_fenceposts,
)
from kubernetes_tpu.controllers.job_controller import JobController
from kubernetes_tpu.controllers.replicaset_controller import ReplicaSetController

HASH_LABEL = "pod-template-hash"


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RESTClient.for_server(server, qps=2000, burst=2000)


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.03)
    raise AssertionError("condition not met")


def _template(labels):
    return api.PodTemplateSpec(
        metadata=api.ObjectMeta(labels=dict(labels)),
        spec=api.PodSpec(containers=[api.Container(name="c", image="pause")]))


def _pods(client, selector=None):
    return client.list("pods", "default", label_selector=selector)[0]


def _retry_update(client, resource, name, ns, mutate, attempts=10):
    """Read-modify-write with conflict retry (controllers bump rv under us)."""
    from kubernetes_tpu.client.rest import ApiError
    for _ in range(attempts):
        obj = client.get(resource, name, ns)
        mutate(obj)
        try:
            return client.update(resource, obj, ns)
        except ApiError as e:
            if not e.is_conflict:
                raise
            time.sleep(0.02)
    raise AssertionError("update kept conflicting")


def _mark_running_ready(client, pod):
    pod.status = api.PodStatus(
        phase=api.POD_RUNNING,
        conditions=[api.PodCondition(type=api.POD_READY,
                                     status=api.CONDITION_TRUE)])
    client.update_status("pods", pod)


class TestReplicaSetController:
    def test_scale_up_down_and_status(self, client):
        ctrl = ReplicaSetController(client)
        ctrl.start()
        try:
            rs = api.ReplicaSet(
                metadata=api.ObjectMeta(name="web", namespace="default"),
                spec=api.ReplicaSetSpec(
                    replicas=3,
                    selector=api.LabelSelector(match_labels={"app": "web"}),
                    template=_template({"app": "web"})))
            client.create("replicasets", rs, "default")
            _wait(lambda: len(_pods(client, "app=web")) == 3)

            _retry_update(client, "replicasets", "web", "default",
                          lambda rs: setattr(rs.spec, "replicas", 1))
            _wait(lambda: len(_pods(client, "app=web")) == 1)
            _wait(lambda: client.get("replicasets", "web", "default")
                  .status.replicas == 1)
        finally:
            ctrl.stop()

    def test_match_expressions_selector(self, client):
        ctrl = ReplicaSetController(client)
        ctrl.start()
        try:
            rs = api.ReplicaSet(
                metadata=api.ObjectMeta(name="exp", namespace="default"),
                spec=api.ReplicaSetSpec(
                    replicas=2,
                    selector=api.LabelSelector(match_expressions=[
                        api.LabelSelectorRequirement(
                            key="tier", operator="In",
                            values=["web", "api"])]),
                    template=_template({"tier": "web"})))
            client.create("replicasets", rs, "default")
            _wait(lambda: len(_pods(client, "tier in (web,api)")) == 2)
        finally:
            ctrl.stop()


class TestDeploymentController:
    def test_fenceposts(self):
        s = ext.DeploymentStrategy(rolling_update=ext.RollingUpdateDeployment(
            max_surge="25%", max_unavailable="25%"))
        assert resolve_fenceposts(s, 10) == (3, 2)   # surge up, unavail down
        assert resolve_fenceposts(None, 10) == (1, 1)
        z = ext.DeploymentStrategy(rolling_update=ext.RollingUpdateDeployment(
            max_surge=0, max_unavailable=0))
        assert resolve_fenceposts(z, 10) == (0, 1)   # both-zero fencepost

    def _deploy(self, client, name="dep", image="img:v1", replicas=2):
        tpl = _template({"app": name})
        tpl.spec.containers[0].image = image
        d = ext.Deployment(
            metadata=api.ObjectMeta(name=name, namespace="default"),
            spec=ext.DeploymentSpec(
                replicas=replicas,
                selector=api.LabelSelector(match_labels={"app": name}),
                template=tpl))
        return client.create("deployments", d, "default")

    def test_creates_replicaset_and_pods(self, client):
        dc = DeploymentController(client)
        rsc = ReplicaSetController(client)
        dc.start()
        rsc.start()
        try:
            self._deploy(client)
            _wait(lambda: len(client.list("replicasets", "default")[0]) == 1)
            rs = client.list("replicasets", "default")[0][0]
            assert rs.metadata.name.startswith("dep-")
            assert (rs.metadata.labels or {}).get(HASH_LABEL)
            _wait(lambda: len(_pods(client, "app=dep")) == 2)
        finally:
            dc.stop()
            rsc.stop()

    def test_rolling_update_rolls_all_pods(self, client):
        dc = DeploymentController(client)
        rsc = ReplicaSetController(client)
        dc.start()
        rsc.start()
        try:
            self._deploy(client, image="img:v1", replicas=2)
            _wait(lambda: len(_pods(client, "app=dep")) == 2)
            # pods become available -> kubelet-in-miniature
            for p in _pods(client, "app=dep"):
                _mark_running_ready(client, p)

            def set_v2(d):
                d.spec.template.spec.containers[0].image = "img:v2"
            _retry_update(client, "deployments", "dep", "default", set_v2)

            # eventually: 2 RSes, old at 0, new at 2, all pods on img:v2
            def rolled():
                rses = client.list("replicasets", "default")[0]
                if len(rses) != 2:
                    return False
                by_size = sorted(rses, key=lambda r: r.spec.replicas or 0)
                if (by_size[0].spec.replicas or 0) != 0 or \
                   (by_size[1].spec.replicas or 0) != 2:
                    return False
                pods = [p for p in _pods(client, "app=dep")
                        if p.metadata.deletion_timestamp is None]
                if len(pods) != 2:
                    return False
                for p in pods:
                    if p.spec.containers[0].image != "img:v2":
                        return False
                    _mark_running_ready(client, p)  # keep rollout moving
                return True

            # keep marking new pods ready so the rollout can progress
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                for p in _pods(client, "app=dep"):
                    st = p.status
                    if not (st and st.phase == api.POD_RUNNING):
                        try:
                            _mark_running_ready(client, p)
                        except Exception:
                            pass
                if rolled():
                    break
                time.sleep(0.05)
            assert rolled()
            # revision annotations moved forward
            revs = sorted(int((r.metadata.annotations or {}).get(
                ext.ANN_REVISION, "0"))
                for r in client.list("replicasets", "default")[0])
            assert revs == [1, 2]
        finally:
            dc.stop()
            rsc.stop()

    def test_scale_down_shrinks_new_replicaset(self, client):
        dc = DeploymentController(client)
        rsc = ReplicaSetController(client)
        dc.start()
        rsc.start()
        try:
            self._deploy(client, replicas=4)
            _wait(lambda: len(_pods(client, "app=dep")) == 4)
            _retry_update(client, "deployments", "dep", "default",
                          lambda d: setattr(d.spec, "replicas", 2))
            _wait(lambda: len([p for p in _pods(client, "app=dep")
                               if p.metadata.deletion_timestamp is None]) == 2)
            rs = client.list("replicasets", "default")[0][0]
            assert (rs.spec.replicas or 0) == 2
        finally:
            dc.stop()
            rsc.stop()

    def test_rollback_restores_old_template(self, client):
        dc = DeploymentController(client)
        dc.start()
        try:
            self._deploy(client, image="img:v1", replicas=1)
            _wait(lambda: len(client.list("replicasets", "default")[0]) == 1)
            def set_v2(d):
                d.spec.template.spec.containers[0].image = "img:v2"
            _retry_update(client, "deployments", "dep", "default", set_v2)
            _wait(lambda: len(client.list("replicasets", "default")[0]) == 2)

            client.rollback_deployment(
                "dep", "default",
                ext.DeploymentRollback(name="dep",
                                       rollback_to=ext.RollbackConfig(revision=0)))
            _wait(lambda: client.get("deployments", "dep", "default")
                  .spec.template.spec.containers[0].image == "img:v1")
            assert client.get("deployments", "dep", "default") \
                .spec.rollback_to is None
        finally:
            dc.stop()


class TestDaemonSetController:
    def _node(self, name, labels=None, ready=True, taints=None):
        return api.Node(
            metadata=api.ObjectMeta(name=name, labels=labels or {}),
            spec=api.NodeSpec(taints=taints),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "8Gi", "pods": "110"},
                conditions=[api.NodeCondition(
                    type=api.NODE_READY,
                    status=api.CONDITION_TRUE if ready
                    else api.CONDITION_FALSE)]))

    def test_one_pod_per_eligible_node(self, client):
        for i in range(3):
            client.create("nodes", self._node(f"n{i}"))
        client.create("nodes", self._node("n-notready", ready=False))
        ctrl = DaemonSetController(client)
        ctrl.start()
        try:
            ds = ext.DaemonSet(
                metadata=api.ObjectMeta(name="agent", namespace="default"),
                spec=ext.DaemonSetSpec(
                    selector=api.LabelSelector(match_labels={"ds": "agent"}),
                    template=_template({"ds": "agent"})))
            client.create("daemonsets", ds, "default")
            _wait(lambda: len(_pods(client, "ds=agent")) == 3)
            nodes_assigned = {p.spec.node_name for p in _pods(client, "ds=agent")}
            assert nodes_assigned == {"n0", "n1", "n2"}

            # new node joining gets a daemon pod
            client.create("nodes", self._node("n3"))
            _wait(lambda: len(_pods(client, "ds=agent")) == 4)

            # status reflects desired/current
            _wait(lambda: client.get("daemonsets", "agent", "default")
                  .status.desired_number_scheduled == 4)
        finally:
            ctrl.stop()

    def test_node_selector_and_taints(self, client):
        client.create("nodes", self._node("gpu1", labels={"accel": "tpu"}))
        client.create("nodes", self._node("cpu1"))
        client.create("nodes", self._node(
            "tainted", labels={"accel": "tpu"},
            taints=[api.Taint(key="dedicated", value="x",
                              effect=api.TAINT_NO_SCHEDULE)]))
        ctrl = DaemonSetController(client)
        ctrl.start()
        try:
            tpl = _template({"ds": "tpu-agent"})
            tpl.spec.node_selector = {"accel": "tpu"}
            ds = ext.DaemonSet(
                metadata=api.ObjectMeta(name="tpu-agent", namespace="default"),
                spec=ext.DaemonSetSpec(
                    selector=api.LabelSelector(match_labels={"ds": "tpu-agent"}),
                    template=tpl))
            client.create("daemonsets", ds, "default")
            _wait(lambda: {p.spec.node_name
                           for p in _pods(client, "ds=tpu-agent")} == {"gpu1"})
            time.sleep(0.3)  # no pod ever lands on cpu1/tainted
            assert {p.spec.node_name
                    for p in _pods(client, "ds=tpu-agent")} == {"gpu1"}
        finally:
            ctrl.stop()


class TestJobController:
    def _job(self, name="sum", parallelism=2, completions=4, **kw):
        return batch.Job(
            metadata=api.ObjectMeta(name=name, namespace="default"),
            spec=batch.JobSpec(
                parallelism=parallelism, completions=completions,
                selector=api.LabelSelector(match_labels={"job": name}),
                template=_template({"job": name}), **kw))

    def test_runs_to_completion(self, client):
        ctrl = JobController(client)
        ctrl.start()
        try:
            client.create("jobs", self._job(), "default")
            _wait(lambda: len(_pods(client, "job=sum")) == 2)

            # finish pods one by one; controller backfills until 4 completions
            seen_done = set()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                for p in _pods(client, "job=sum"):
                    if p.metadata.name not in seen_done and \
                            (p.status is None or
                             p.status.phase != api.POD_SUCCEEDED):
                        p.status = api.PodStatus(phase=api.POD_SUCCEEDED)
                        try:
                            client.update_status("pods", p)
                            seen_done.add(p.metadata.name)
                        except Exception:
                            pass
                job = client.get("jobs", "sum", "default")
                st = job.status
                if st and st.succeeded >= 4 and any(
                        c.type == batch.JOB_COMPLETE and
                        c.status == api.CONDITION_TRUE
                        for c in (st.conditions or [])):
                    break
                time.sleep(0.05)
            job = client.get("jobs", "sum", "default")
            assert job.status.succeeded >= 4
            assert any(c.type == batch.JOB_COMPLETE for c in
                       (job.status.conditions or []))
            assert job.status.completion_time
        finally:
            ctrl.stop()

    def test_parallelism_cap(self, client):
        ctrl = JobController(client)
        ctrl.start()
        try:
            client.create("jobs", self._job(name="cap", parallelism=3,
                                            completions=10), "default")
            _wait(lambda: len(_pods(client, "job=cap")) == 3)
            time.sleep(0.3)
            assert len(_pods(client, "job=cap")) == 3  # never exceeds parallelism
        finally:
            ctrl.stop()
