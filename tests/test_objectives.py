"""Scheduling objectives subsystem (ISSUE 13): bin-packing, priority
preemption, and gang scheduling as tensor solve modes.

The acceptance anchor is oracle equivalence: on randomized fixtures the
kernel's placements, victim sets, nominated nodes, gang verdicts, survivor
rows, and score decompositions must match the node-by-node Python replay
(scheduler/objectives/oracle.py) EXACTLY — and a disabled objective config
must trace the bit-identical default program.  Plus the delivery surfaces:
the provider-registry seam, incremental-mirror parity, live preemption
eviction with Preempted Events and counters, and the gang_churn soak
report blocks.
"""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.observability.explain import oracle_breakdown
from kubernetes_tpu.scheduler.batch import (
    ListPodLister, ListServiceLister, make_plugin_args, tpu_batch,
)
from kubernetes_tpu.scheduler.objectives.config import (
    GANG_LABEL, PRIORITY_ANNOTATION, ObjectiveConfig, gang_order,
    get_objective, pod_gang, pod_priority,
)
from kubernetes_tpu.scheduler.objectives.oracle import oracle_objective


def mk_node(name, cpu="4", mem="8Gi", pods="110", labels=None, taints=None):
    labels = dict(labels or {})
    labels.setdefault(api.LABEL_HOSTNAME, name)
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=labels),
        spec=api.NodeSpec(taints=taints),
        status=api.NodeStatus(
            allocatable={"cpu": cpu, "memory": mem, "pods": pods},
            conditions=[api.NodeCondition(type="Ready", status="True")]))


def mk_pod(name, ns="default", cpu=None, mem="256Mi", labels=None, node="",
           selector=None, priority=None, gang=None, host_ports=()):
    labels = dict(labels or {})
    ann = None
    if priority is not None:
        ann = {PRIORITY_ANNOTATION: str(priority)}
    if gang is not None:
        labels[GANG_LABEL] = gang
    requests = {"memory": mem}
    if cpu:
        requests["cpu"] = cpu
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns, labels=labels,
                                annotations=ann),
        spec=api.PodSpec(
            node_name=node, node_selector=selector,
            containers=[api.Container(
                name="c", image="pause",
                ports=[api.ContainerPort(host_port=p, container_port=p)
                       for p in host_ports],
                resources=api.ResourceRequirements(requests=requests))]))


def _records_equal(kr, orr):
    assert kr.pod == orr.pod
    assert kr.survivors == orr.survivors, (
        f"{kr.pod}: survivors {kr.survivors} != oracle {orr.survivors}")
    assert kr.node == orr.node, (kr.pod, kr.node, orr.node)
    assert kr.preemption == orr.preemption, kr.pod
    assert kr.gang == orr.gang, kr.pod
    if kr.node is None:
        return
    assert kr.score == pytest.approx(orr.score, abs=1e-4), kr.pod
    assert set(kr.components) == set(orr.components), kr.pod
    for name in orr.components:
        assert kr.components[name] == pytest.approx(
            orr.components[name], abs=1e-4), (kr.pod, name)
    assert kr.runner_up == orr.runner_up, kr.pod


def _outcomes_equal(kout, oout):
    assert [(p.pod, p.node, p.victims) for p in kout.preemptions] == \
        [(p.pod, p.node, p.victims) for p in oout.preemptions]
    assert [(g.name, g.placed, g.members) for g in kout.gangs] == \
        [(g.name, g.placed, g.members) for g in oout.gangs]


class TestGangOrder:
    def test_members_contiguous_at_first_arrival(self):
        pods = [mk_pod("a"), mk_pod("g1a", gang="g1"), mk_pod("b"),
                mk_pod("g2a", gang="g2"), mk_pod("g1b", gang="g1"),
                mk_pod("g2b", gang="g2"), mk_pod("c")]
        ordered, perm = gang_order(pods)
        names = [p.metadata.name for p in ordered]
        assert names == ["a", "g1a", "g1b", "b", "g2a", "g2b", "c"]
        # perm maps ordered[j] back to pods[perm[j]]
        for j, i in enumerate(perm):
            assert ordered[j] is pods[i]

    def test_no_gangs_identity(self):
        pods = [mk_pod(f"p{i}") for i in range(5)]
        ordered, perm = gang_order(pods)
        assert ordered == pods
        assert perm == list(range(5))


class TestObjectiveInputs:
    def test_priority_annotation(self):
        assert pod_priority(mk_pod("p", priority=7)) == 7.0
        assert pod_priority(mk_pod("p")) == 0.0
        bad = mk_pod("p")
        bad.metadata.annotations = {PRIORITY_ANNOTATION: "not-a-number"}
        assert pod_priority(bad) == 0.0  # malformed must not unschedule

    def test_gang_label(self):
        # namespace-qualified: two teams independently labelling their
        # jobs gang=train must not fuse into one all-or-nothing unit
        assert pod_gang(mk_pod("p", gang="j1")) == "default/j1"
        assert pod_gang(mk_pod("p", ns="teamB", gang="j1")) == "teamB/j1"
        assert pod_gang(mk_pod("p")) is None


class TestKernelOracleParity:
    """The acceptance anchor: kernel objective output == Python replay."""

    def _random_cluster(self, seed, n_nodes=16, small_nodes=True):
        rng = random.Random(seed)
        zones = ["us-a", "us-b", "us-c"]
        nodes = []
        for i in range(n_nodes):
            labels = {api.LABEL_HOSTNAME: f"n{i:02d}",
                      api.LABEL_ZONE: rng.choice(zones)}
            if rng.random() < 0.3:
                labels["disk"] = "ssd"
            cpu = rng.choice(["1", "2"]) if small_nodes else "4"
            nodes.append(mk_node(f"n{i:02d}", cpu=cpu,
                                 pods=str(rng.choice([4, 110])),
                                 labels=labels))
        existing = []
        for i in range(10):
            existing.append(mk_pod(
                f"e{i:02d}", cpu=f"{rng.choice([300, 500, 700])}m",
                mem="256Mi", labels={"app": rng.choice(["web", "db"])},
                priority=rng.choice([0, 1, 2]),
                node=rng.choice(nodes).metadata.name))
        return rng, zones, nodes, existing

    def _args(self, nodes, existing):
        def build():
            return make_plugin_args(
                nodes, pod_lister=ListPodLister(list(existing)))
        return build

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_binpack_parity(self, seed):
        rng, _zones, nodes, existing = self._random_cluster(
            seed, small_nodes=False)
        pending = [mk_pod(f"p{i:02d}", cpu=f"{rng.choice([100, 400, 900])}m")
                   for i in range(24)]
        pending.append(mk_pod("huge", cpu="64"))
        obj = get_objective("binpack")
        args = self._args(nodes, existing)
        names, recs, outcome = tpu_batch(nodes, existing, pending, args(),
                                         objective=obj, explain=True)
        res = oracle_objective(nodes, existing, pending, args(), obj)
        assert names == res.names
        _outcomes_equal(outcome, res.outcome)
        assert any("binpack" in r.components for r in recs if r.node)
        for kr, orr in zip(recs, res.records):
            _records_equal(kr, orr)

    @pytest.mark.parametrize("seed", [3, 4, 5])
    def test_preempt_parity(self, seed):
        rng, _zones, nodes, existing = self._random_cluster(seed)
        pending = []
        for i in range(16):
            prio = rng.choice([0, 0, 3, 5, 9])
            pending.append(mk_pod(
                f"p{i:02d}", cpu=f"{rng.choice([200, 600, 900, 1500])}m",
                priority=prio,
                selector={"disk": "ssd"} if rng.random() < 0.15 else None))
        obj = get_objective("preempt")
        args = self._args(nodes, existing)
        names, recs, outcome = tpu_batch(nodes, existing, pending, args(),
                                         objective=obj, explain=True)
        res = oracle_objective(nodes, existing, pending, args(), obj)
        assert names == res.names
        _outcomes_equal(outcome, res.outcome)
        for kr, orr in zip(recs, res.records):
            _records_equal(kr, orr)

    @pytest.mark.parametrize("seed", [6, 7, 8])
    def test_gang_parity(self, seed):
        rng, zones, nodes, existing = self._random_cluster(seed)
        pending = []
        for i in range(6):
            size = rng.choice([2, 3, 4])
            for j in range(size):
                pending.append(mk_pod(
                    f"g{i}m{j}", cpu=f"{rng.choice([400, 700, 900])}m",
                    gang=f"job{i}"))
        for i in range(6):
            pending.append(mk_pod(f"s{i}", cpu="300m"))
        rng.shuffle(pending)
        obj = get_objective("gang")
        args = self._args(nodes, existing)
        names, recs, outcome = tpu_batch(nodes, existing, pending, args(),
                                         objective=obj, explain=True)
        ordered, perm = gang_order(pending)
        res = oracle_objective(nodes, existing, ordered, args(), obj)
        from kubernetes_tpu.ops.kernel import unpermute_result
        assert names == unpermute_result(res.names, perm)
        _outcomes_equal(outcome, res.outcome)
        for kr, orr in zip(recs, res.records):
            _records_equal(kr, orr)
        # all-or-nothing + topology: every placed gang sits in ONE zone
        zone_of = {n.metadata.name: (n.metadata.labels or {})[api.LABEL_ZONE]
                   for n in nodes}
        by_name = dict(zip([f"{p.metadata.namespace}/{p.metadata.name}"
                            for p in pending], names))
        for gr in outcome.gangs:
            member_nodes = [by_name[m] for m in gr.members]
            if gr.placed:
                assert all(member_nodes)
                assert len({zone_of[n] for n in member_nodes}) == 1, gr.name
            else:
                assert member_nodes == [None] * len(gr.members), gr.name

    @pytest.mark.parametrize("seed", [9, 10])
    def test_gang_preempt_combined_parity(self, seed):
        rng, _zones, nodes, existing = self._random_cluster(seed)
        pending = []
        for i in range(4):
            for j in range(rng.choice([2, 3])):
                pending.append(mk_pod(f"g{i}m{j}", cpu="600m",
                                      gang=f"job{i}"))
        for i in range(5):
            pending.append(mk_pod(f"hi{i}", cpu="900m",
                                  priority=rng.choice([5, 9])))
        obj = get_objective("gang_preempt")
        args = self._args(nodes, existing)
        names, recs, outcome = tpu_batch(nodes, existing, pending, args(),
                                         objective=obj, explain=True)
        ordered, perm = gang_order(pending)
        res = oracle_objective(nodes, existing, ordered, args(), obj)
        from kubernetes_tpu.ops.kernel import unpermute_result
        assert names == unpermute_result(res.names, perm)
        _outcomes_equal(outcome, res.outcome)
        for kr, orr in zip(recs, res.records):
            _records_equal(kr, orr)

    def test_oracle_breakdown_delegates(self):
        """explain.oracle_breakdown(objective=...) is the documented entry
        to the objective replay (ROADMAP item 3's per-mode oracle)."""
        _rng, _zones, nodes, existing = self._random_cluster(11)
        pending = [mk_pod("p0", cpu="300m"), mk_pod("p1", cpu="64")]
        obj = get_objective("binpack")
        args = self._args(nodes, existing)
        names, recs, _outcome = tpu_batch(nodes, existing, pending, args(),
                                          objective=obj, explain=True)
        orecs = oracle_breakdown(nodes, existing, pending, args(), names,
                                 objective=obj)
        for kr, orr in zip(recs, orecs):
            _records_equal(kr, orr)

    def test_seeded_preemption_exact_victims(self):
        """Hand-checked nomination: lowest (victim priority, victim count,
        node order) wins, equal-or-higher priority never preempted."""
        nodes = [mk_node("n0", cpu="1", pods="8"),
                 mk_node("n1", cpu="1", pods="8"),
                 mk_node("n2", cpu="1", pods="8")]
        existing = [
            # n0: one high-priority victim candidate -> protected
            mk_pod("v-hi", cpu="900m", node="n0", priority=9),
            # n1: two low victims (300m each) -> needs BOTH for an 800m pod
            mk_pod("v-a", cpu="450m", node="n1", priority=1),
            mk_pod("v-b", cpu="450m", node="n1", priority=2),
            # n2: one mid victim frees enough alone -> fewer victims, but
            # its priority (3) is HIGHER than n1's top victim (2): the
            # lexicographic order prefers n1
            mk_pod("v-c", cpu="900m", node="n2", priority=3),
        ]
        pending = [mk_pod("hi", cpu="800m", priority=5)]
        obj = get_objective("preempt")
        args = make_plugin_args(nodes,
                                pod_lister=ListPodLister(list(existing)))
        names, outcome = tpu_batch(nodes, existing, pending, args,
                                   objective=obj)
        assert names == [None]
        assert len(outcome.preemptions) == 1
        dec = outcome.preemptions[0]
        assert dec.node == "n1"
        assert dec.victims == ["default/v-a", "default/v-b"]

    def test_never_preempts_equal_or_higher(self):
        nodes = [mk_node("n0", cpu="1", pods="8")]
        existing = [mk_pod("peer", cpu="900m", node="n0", priority=5)]
        pending = [mk_pod("hi", cpu="800m", priority=5)]
        args = make_plugin_args(nodes,
                                pod_lister=ListPodLister(list(existing)))
        names, outcome = tpu_batch(nodes, existing, pending, args,
                                   objective=get_objective("preempt"))
        assert names == [None]
        assert outcome.preemptions == []

    def test_disabled_objective_bit_identical(self):
        """A disabled config selects the EXACT default program: identical
        lowered HLO text, identical assignments, no extra arrays."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.fixtures import feature_batch
        from kubernetes_tpu.ops.kernel import (
            Weights, _schedule_jit, features_of,
        )
        from kubernetes_tpu.ops.tensorize import Tensorizer

        ct = feature_batch(n_nodes=48, n_pods=24, with_existing=True)
        feats, w = features_of(ct), Weights()
        arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
        disabled = ObjectiveConfig()
        low_none = _schedule_jit.lower(
            arrays, ct.n_zones, w, feats, False, None).as_text()
        low_off = _schedule_jit.lower(
            arrays, ct.n_zones, w, feats, False, disabled).as_text()
        assert low_none == low_off
        out_a = np.asarray(_schedule_jit(arrays, ct.n_zones, w, feats))
        out_b = np.asarray(_schedule_jit(arrays, ct.n_zones, w, feats,
                                         False, disabled))
        assert np.array_equal(out_a, out_b)
        assert Tensorizer(objective=disabled).objective is None

    def test_explain_surfaces_for_objectives(self):
        """Preemption reason string agrees with the FitError message; a
        rejected gang member's eliminations carry the GangTopology row."""
        from kubernetes_tpu.observability.explain import format_reason
        from kubernetes_tpu.scheduler.objectives.decode import (
            PreemptionFitError, preemption_message,
        )

        nodes = [mk_node("n0", cpu="1", pods="8",
                         labels={api.LABEL_ZONE: "za"}),
                 mk_node("n1", cpu="1", pods="8")]  # no zone label
        existing = [mk_pod("low", cpu="900m", node="n0", priority=0),
                    mk_pod("low1", cpu="900m", node="n1", priority=0)]
        pending = [mk_pod("hi", cpu="800m", priority=9),
                   mk_pod("gm0", cpu="100m", gang="j"),
                   mk_pod("gm1", cpu="2", gang="j")]
        args = make_plugin_args(nodes,
                                pod_lister=ListPodLister(list(existing)))
        names, recs, outcome = tpu_batch(
            nodes, existing, pending, args,
            objective=get_objective("gang_preempt"), explain=True)
        by_pod = {r.pod: r for r in recs}
        hi = by_pod["default/hi"]
        assert hi.preemption is not None
        assert format_reason(hi) == preemption_message(
            hi.preemption["node"], hi.preemption["victims"])
        err = PreemptionFitError(pending[0], outcome.preemptions[0])
        assert str(err) == format_reason(hi)
        # gm1 can never fit (2 cpu on 1-cpu nodes): the gang is rejected,
        # and gm0's decision shows the gang verdict; the n1 node (no zone
        # label) is eliminated on the GangTopology row for gang members
        gm0 = by_pod["default/gm0"]
        assert gm0.gang == {"name": "default/j", "outcome": "rejected"}
        assert gm0.node is None
        assert "GangTopology" in gm0.eliminations()


class TestIncrementalParity:
    """The incremental mirror must solve objectives identically to the
    full Tensorizer (same arrays contract, same decode)."""

    @pytest.mark.parametrize("objective", ["binpack", "gang_preempt"])
    def test_full_vs_incremental(self, objective):
        from kubernetes_tpu.ops.incremental import IncrementalTensorizer

        rng = random.Random(42)
        zones = ["za", "zb"]
        nodes = [mk_node(f"n{i}", cpu="2", pods="8",
                         labels={api.LABEL_ZONE: zones[i % 2]})
                 for i in range(8)]
        existing = [mk_pod(f"e{i}", cpu="700m", node=f"n{i % 8}",
                           priority=i % 3) for i in range(8)]
        pending = []
        for i in range(3):
            for j in range(2):
                pending.append(mk_pod(f"g{i}m{j}", cpu="600m",
                                      gang=f"job{i}"))
        pending += [mk_pod(f"hi{i}", cpu="1800m", priority=9)
                    for i in range(2)]
        rng.shuffle(pending)
        obj = get_objective(objective)

        def args():
            return make_plugin_args(
                nodes, pod_lister=ListPodLister(list(existing)))

        full = tpu_batch(nodes, existing, pending, args(), objective=obj,
                         explain=True)
        inc = IncrementalTensorizer(args(), objective=obj)
        for n in nodes:
            inc.node_added(n)
        for p in existing:
            inc.pod_added(p)
        incr = inc.schedule(pending, explain=True)
        assert full[0] == incr[0]
        _outcomes_equal(full[2], incr[2])
        for kr, ir in zip(full[1], incr[1]):
            _records_equal(kr, ir)

    def test_victim_delta_path_matches_full_rebuild(self):
        """vict_prio/vict_cum maintained through add/remove/terminating
        churn (the delta path, ROADMAP 3b) must nominate the exact victims
        a full Tensorizer rebuild of the final state nominates."""
        from kubernetes_tpu.ops.incremental import IncrementalTensorizer

        nodes = [mk_node(f"n{i}", cpu="4", pods="16") for i in range(4)]
        obj = get_objective("preempt")
        inc = IncrementalTensorizer(make_plugin_args(nodes), objective=obj)
        for n in nodes:
            inc.node_added(n)
        placed = [mk_pod(f"v{i:02d}", cpu="300m", node=f"n{i % 4}",
                         priority=i % 4) for i in range(16)]
        for p in placed:
            inc.pod_added(p)
        # churn: every third victim leaves; one pod goes terminating (an
        # update arrives as remove+add with a deletion timestamp)
        for p in placed[::3]:
            inc.pod_removed(p)
        live = [p for i, p in enumerate(placed) if i % 3 != 0]
        term = mk_pod("term", cpu="300m", node="n0", priority=0)
        term.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
        inc.pod_added(term)

        # pending pods so large only eviction can place them
        pending = [mk_pod(f"hi{i}", cpu="3500m", priority=9)
                   for i in range(3)]
        incr = inc.schedule(pending)
        final = live + [term]
        full = tpu_batch(
            nodes, final, pending,
            make_plugin_args(nodes, pod_lister=ListPodLister(final)),
            objective=obj)
        assert incr[0] == full[0]
        _outcomes_equal(full[1], incr[1])
        # the delta path really did preempt (victims named, not just equal)
        assert any(dec.victims for dec in incr[1].preemptions)


class TestLiveObjectivePipeline:
    """BatchScheduler under gang_preempt against a live apiserver: victim
    eviction through the API, Preempted Events, objective counters, and
    the nominated node on the preemptor's failure surfaces."""

    @pytest.fixture()
    def cluster(self):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.scheduler.factory import ConfigFactory

        server = APIServer().start()
        client = RESTClient.for_server(server, user_agent="objectives-test")
        for i in range(3):
            client.create("nodes", mk_node(
                f"n{i}", cpu="1", mem="4Gi", pods="8",
                labels={api.LABEL_HOSTNAME: f"n{i}",
                        api.LABEL_ZONE: f"z{i % 2}"}))
        factory = ConfigFactory(client)
        factory.run(timeout=30)
        sched = factory.create_batch_from_provider(
            batch_size=16, objective="gang_preempt", strict=True).run()
        try:
            yield client, sched
        finally:
            sched.stop()
            factory.stop()
            server.stop()

    def test_gang_then_preemption_live(self, cluster):
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

        client, sched = cluster
        base = dict(METRICS.counter_series("scheduler_preemptions_total"))

        for i in range(2):
            client.create("pods", mk_pod(f"tr{i}", cpu="300m", mem="64Mi",
                                         gang="job1"))
        for i in range(3):
            client.create("pods", mk_pod(f"low{i}", cpu="600m", mem="64Mi",
                                         priority=1))
        deadline = time.monotonic() + 30
        bound = {}
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            bound = {p.metadata.name: p.spec.node_name for p in pods
                     if p.spec and p.spec.node_name}
            if len(bound) >= 5:
                break
            time.sleep(0.05)
        assert bound.get("tr0") and bound.get("tr1"), bound

        client.create("pods", mk_pod("hi", cpu="600m", mem="64Mi",
                                     priority=10))
        deadline = time.monotonic() + 30
        hi_node, evicted, pre_ev, fs_ev = None, False, [], []
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", "default")
            by = {p.metadata.name: p for p in pods}
            hi_node = (by["hi"].spec.node_name
                       if "hi" in by and by["hi"].spec else None)
            evicted = any(n not in by for n in ("low0", "low1", "low2"))
            evs, _ = client.list("events", "default")
            pre_ev = [e for e in evs if e.reason == "Preempted"]
            fs_ev = [e for e in evs if e.reason == "FailedScheduling"
                     and "nominated node" in (e.message or "")]
            if hi_node and evicted and pre_ev and fs_ev:
                break
            time.sleep(0.05)
        assert hi_node and evicted, (hi_node, evicted)
        assert pre_ev, "no Preempted event on the victim"
        assert fs_ev, "no nominated-node FailedScheduling event"
        assert "Preempted by default/hi" in pre_ev[0].message

        after = METRICS.counter_series("scheduler_preemptions_total")
        key = (("reason", "evicted"),)
        assert after.get(key, 0.0) > base.get(key, 0.0)
        gangs = METRICS.counter_series("scheduler_gang_placements_total")
        assert gangs.get((("outcome", "placed"),), 0.0) >= 1.0


class TestGangChurnSoak:
    def test_gang_churn_report_blocks(self):
        """A tiny gang_churn soak emits the objective report blocks
        (preemptions / gangs_placed / gangs_rejected) per round and in the
        summary, and places at least one gang (check_soak.py's schema)."""
        from kubernetes_tpu.observability.soak import SoakConfig, run_soak

        # duration must outlast the gang_preempt program's cold compile
        # (a few seconds on a loaded CPU runner) or the steady-state
        # window legitimately sees zero binds and the schema check balks
        cfg = SoakConfig(num_nodes=6, create_rate=24, duration_seconds=8,
                         scrape_period=1, batch_size=32,
                         scenario="gang_churn", gang_size=3,
                         preempt_every=4, drain_timeout=15)
        report = run_soak(cfg)
        assert not report.get("wedged"), report.get("error")
        assert report["config"]["scenario"] == "gang_churn"
        assert report["config"]["objective"] == "gang_preempt"
        assert report["gangs_placed"] > 0
        assert "gangs_rejected" in report
        assert isinstance(report["preemptions"], dict)
        for rnd in report["rounds"]:
            for key in ("preemptions", "gangs_placed", "gangs_rejected"):
                assert key in rnd, (key, rnd)

        import json
        import sys

        sys.path.insert(0, "tools")
        try:
            import check_soak
        finally:
            sys.path.pop(0)
        doc = {"metric": "pods_scheduled_per_sec x", "value": 1.0,
               "unit": "pods/s", "vs_baseline": 1.0,
               "wedged": bool(report.get("wedged")), "detail": report}
        errs = check_soak.check(json.loads(json.dumps(doc)),
                                expect_wedged=False)
        assert not errs, errs


class TestGangBatchIntake:
    """Count-based batch draining must never split a co-pending gang: the
    intake pulls the queued tail of any gang the batch_size slice cut (the
    all-or-nothing contract is per solve, so two solves each seeing half a
    gang would commit or reject it independently)."""

    def test_fifo_drain_where(self):
        from kubernetes_tpu.client.cache import FIFO

        q = FIFO()
        for i in range(6):
            q.add(mk_pod(f"p{i}", gang="g" if i % 2 else None))
        got = q.drain_where(
            lambda p: (p.metadata.labels or {}).get(GANG_LABEL) == "g")
        assert [p.metadata.name for p in got] == ["p1", "p3", "p5"]
        assert len(q) == 3  # non-matching pods stay queued, order kept
        assert [p.metadata.name for p in q.drain(10)] == ["p0", "p2", "p4"]

    def test_gang_straddling_batch_boundary(self):
        """batch_size=2 with [solo, g0, g1, g2] pending: the drain slice
        ends inside the gang. The intake gives the whole gang back (it
        would overshoot the pod bucket behind the solo), then solves it
        intact — oversized, since a gang larger than batch_size can only
        ever run as the head of its own batch — in the NEXT call. It is
        never split across solves."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.scheduler.factory import ConfigFactory

        server = APIServer().start()
        client = RESTClient.for_server(server, user_agent="objectives-test")
        try:
            for i in range(4):
                client.create("nodes", mk_node(
                    f"n{i}", cpu="2", mem="4Gi", pods="8",
                    labels={api.LABEL_HOSTNAME: f"n{i}",
                            api.LABEL_ZONE: f"z{i % 2}"}))
            factory = ConfigFactory(client)
            factory.run(timeout=30)
            try:
                sched = factory.create_batch_from_provider(
                    batch_size=2, objective="gang", strict=True)
                client.create("pods", mk_pod("solo", cpu="100m", mem="64Mi"))
                for j in range(3):
                    client.create("pods", mk_pod(f"g{j}", cpu="300m",
                                                 mem="64Mi", gang="jobA"))
                deadline = time.monotonic() + 20
                while (len(factory.pending) < 4
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert len(factory.pending) == 4
                # solo alone (the gang went back whole), then the gang
                # intact as its own oversized batch
                assert sched.schedule_batch_once(timeout=5) == 1
                n = sched.schedule_batch_once(timeout=5)
                assert n == 3, f"gang split at the boundary: got {n} pods"
                deadline = time.monotonic() + 20
                bound = {}
                while time.monotonic() < deadline:
                    pods, _ = client.list("pods", "default")
                    bound = {p.metadata.name: p.spec.node_name for p in pods
                             if p.spec and p.spec.node_name}
                    if len(bound) == 4:
                        break
                    time.sleep(0.05)
                assert len(bound) == 4, bound
                zone = {f"n{i}": f"z{i % 2}" for i in range(4)}
                gz = {zone[bound[f"g{j}"]] for j in range(3)}
                assert len(gz) == 1, f"gang split across zones: {bound}"
            finally:
                factory.stop()
        finally:
            server.stop()

    def test_gang_tail_pull_keeps_bucket_shape(self):
        """Pulling a cut gang's tail must not overshoot batch_size (the
        incremental mirror's pod bucket): whole trailing units are given
        back to the queue, so the first solve handles gang A intact and
        gang B arrives whole in the next batch."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client import RESTClient
        from kubernetes_tpu.scheduler.factory import ConfigFactory

        server = APIServer().start()
        client = RESTClient.for_server(server, user_agent="objectives-test")
        try:
            for i in range(4):
                client.create("nodes", mk_node(
                    f"n{i}", cpu="4", mem="8Gi", pods="16",
                    labels={api.LABEL_HOSTNAME: f"n{i}",
                            api.LABEL_ZONE: f"z{i % 2}"}))
            factory = ConfigFactory(client)
            factory.run(timeout=30)
            try:
                sched = factory.create_batch_from_provider(
                    batch_size=4, objective="gang", strict=True)
                for j in range(3):
                    client.create("pods", mk_pod(f"a{j}", cpu="200m",
                                                 mem="64Mi", gang="jobA"))
                for j in range(3):
                    client.create("pods", mk_pod(f"b{j}", cpu="200m",
                                                 mem="64Mi", gang="jobB"))
                deadline = time.monotonic() + 20
                while (len(factory.pending) < 6
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                assert len(factory.pending) == 6
                # first solve: jobA complete (tail pulled), jobB given
                # back whole — P stays at the pod bucket
                assert sched.schedule_batch_once(timeout=5) == 3
                assert len(factory.pending) == 3
                # second solve: jobB arrives intact
                assert sched.schedule_batch_once(timeout=5) == 3
                deadline = time.monotonic() + 20
                bound = {}
                while time.monotonic() < deadline:
                    pods, _ = client.list("pods", "default")
                    bound = {p.metadata.name: p.spec.node_name for p in pods
                             if p.spec and p.spec.node_name}
                    if len(bound) == 6:
                        break
                    time.sleep(0.05)
                assert len(bound) == 6, bound
            finally:
                factory.stop()
        finally:
            server.stop()

    def test_rejected_gang_counted_once_across_retries(self):
        """A still-pending gang is re-solved on every backoff retry; the
        rejected counter must move once per gang, not once per solve, and
        count again after an intervening placement (name reuse)."""
        from kubernetes_tpu.scheduler.objectives.decode import (
            GangResult, ObjectiveOutcome,
        )
        from kubernetes_tpu.scheduler.tpu import BatchScheduler
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

        sched = BatchScheduler.__new__(BatchScheduler)
        sched._rejected_gangs_counted = set()
        key = (("outcome", "rejected"),)
        pkey = (("outcome", "placed"),)

        def series():
            s = METRICS.counter_series("scheduler_gang_placements_total")
            return s.get(key, 0.0), s.get(pkey, 0.0)

        rej0, pl0 = series()
        rejected = ObjectiveOutcome(objective="gang", gangs=[
            GangResult(name="jobR", members=["default/a"], placed=False)])
        for _ in range(3):  # three retry solves, one rejection
            sched._apply_outcome(rejected)
        assert series()[0] == rej0 + 1

        placed = ObjectiveOutcome(objective="gang", gangs=[
            GangResult(name="jobR", members=["default/a"], placed=True)])
        sched._apply_outcome(placed)
        assert series()[1] == pl0 + 1
        sched._apply_outcome(rejected)  # a NEW gang reusing the name
        assert series()[0] == rej0 + 2

    def test_fifo_requeue_front(self):
        from kubernetes_tpu.client.cache import FIFO

        q = FIFO()
        for i in range(3):
            q.add(mk_pod(f"p{i}"))
        taken = q.pop()  # p0
        q.requeue_front(taken)
        # a newer informer copy wins over the stale give-back, but the
        # position still moves to the head
        newer = mk_pod("p1", cpu="900m")
        p1 = [p for p in q.drain(10) if p.metadata.name == "p1"][0]
        for p in reversed([taken, newer]):
            q.add(p)
        q.add(mk_pod("p9"))
        q.requeue_front(mk_pod("p1"))  # stale copy of p1
        head = q.pop()
        assert head.metadata.name == "p1"
        req = head.spec.containers[0].resources.requests
        assert req.get("cpu") == "900m", "stale give-back clobbered newer copy"

    def test_cross_namespace_gangs_are_distinct_units(self):
        """gang=train in two namespaces: one team's infeasible member must
        not nullify the other team's placements (kernel and oracle agree)."""
        nodes = [mk_node(f"n{i}", cpu="2",
                         labels={api.LABEL_ZONE: f"z{i % 2}"})
                 for i in range(4)]
        pending = [
            mk_pod("w0", ns="teamA", cpu="300m", gang="train"),
            mk_pod("w1", ns="teamA", cpu="300m", gang="train"),
            # teamB's second member can never fit -> teamB rejected
            mk_pod("w0", ns="teamB", cpu="300m", gang="train"),
            mk_pod("w1", ns="teamB", cpu="64", gang="train"),
        ]
        obj = get_objective("gang")
        args = make_plugin_args(nodes, pod_lister=ListPodLister([]))
        names, _recs, outcome = tpu_batch(nodes, [], pending, args,
                                          objective=obj, explain=True)
        res = oracle_objective(nodes, [], gang_order(pending)[0], args, obj)
        _outcomes_equal(outcome, res.outcome)
        by_gang = {g.name: g for g in outcome.gangs}
        assert by_gang["teamA/train"].placed
        assert not by_gang["teamB/train"].placed
        by_name = dict(zip([f"{p.metadata.namespace}/{p.metadata.name}"
                            for p in pending], names))
        assert by_name["teamA/w0"] and by_name["teamA/w1"]
        assert by_name["teamB/w0"] is None and by_name["teamB/w1"] is None

    def test_preemption_eviction_suppressed_until_bind(self):
        """A still-unschedulable preemptor gets ONE eviction round per
        nomination — backoff retries must not kill a fresh victim set each
        solve — and the guard clears when the preemptor binds."""
        from kubernetes_tpu.scheduler.objectives.decode import (
            ObjectiveOutcome, PreemptionDecision,
        )
        from kubernetes_tpu.scheduler.tpu import BatchScheduler
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

        deletes = []

        class StubClient:
            def delete(self, kind, name, ns):
                deletes.append(f"{ns}/{name}")

        class StubRecorder:
            def event(self, *a, **k):
                pass

        class StubF:
            client = StubClient()

        sched = BatchScheduler.__new__(BatchScheduler)
        sched._nominated = {}
        sched._rejected_gangs_counted = set()
        sched.f = StubF()
        sched.recorder = StubRecorder()

        def outcome(victims):
            return ObjectiveOutcome(objective="preempt", preemptions=[
                PreemptionDecision(pod="default/hi", node="n0",
                                   victims=list(victims))])

        skey = (("reason", "suppressed"),)
        base = dict(METRICS.counter_series("scheduler_preemptions_total"))
        sched._apply_outcome(outcome(["default/low0"]))
        assert deletes == ["default/low0"]
        # retry solves nominate again (different victims even) — no kills,
        # and the surfaced decision repeats the ORIGINAL eviction record
        # (the fresh one names victims that will never be deleted)
        p2, _ = sched._apply_outcome(outcome(["default/low1"]))
        sched._apply_outcome(outcome(["default/low2"]))
        assert deletes == ["default/low0"]
        assert p2["default/hi"].victims == ["default/low0"]
        after = METRICS.counter_series("scheduler_preemptions_total")
        assert after.get(skey, 0.0) == base.get(skey, 0.0) + 2
        # bind clears the guard; a later repeat preemption evicts again
        sched._nominated.pop("default/hi", None)
        sched._apply_outcome(outcome(["default/low3"]))
        assert deletes == ["default/low0", "default/low3"]

    def test_gang_churner_never_reuses_names(self):
        """A mid-burst create failure must not shift the next burst onto
        already-created names (AlreadyExists would leave that gang short a
        member forever)."""
        from kubernetes_tpu.observability.soak import _GangChurner

        attempted = []

        class FlakyClient:
            def __init__(self):
                self.calls = 0

            def create(self, kind, obj):
                self.calls += 1
                attempted.append(obj.metadata.name)
                if self.calls == 2:  # second member of the first burst
                    raise RuntimeError("transient apiserver error")

            def delete(self, kind, name, ns):
                pass

        ch = _GangChurner(FlakyClient(), rate=1000.0, cap=10_000,
                          gang_size=3, preempt_every=100)
        t = 0.0
        ch.tick(t)
        for _ in range(3):
            t += 0.01
            ch.tick(t)
            if len(attempted) >= 9:
                break
        assert len(attempted) >= 9
        assert len(set(attempted)) == len(attempted), (
            f"reused pod names: {attempted}")
        assert ch.create_errors == 1

    def test_gang_churner_departs_whole_gangs(self):
        """The cap trim removes arrival units (whole gangs / whole preempt
        bursts), never a gang suffix — a 1-pod preempt burst must not put
        the pod-at-a-time trim out of gang alignment."""
        from kubernetes_tpu.observability.soak import _GangChurner

        created, deleted = [], []

        class StubClient:
            def create(self, kind, obj):
                created.append(obj.metadata.name)

            def delete(self, kind, name, ns):
                deleted.append(name)

        ch = _GangChurner(StubClient(), rate=1000.0, cap=5,
                          gang_size=3, preempt_every=3)
        t = 0.0
        ch.tick(t)
        for _ in range(6):
            t += 0.01
            ch.tick(t)
        assert len(created) >= 12 and deleted, (created, deleted)
        # every burst either departed completely or not at all
        gone = set(deleted)
        for g, members in _bursts_of(created, ch).items():
            departed = {m in gone for m in members}
            assert len(departed) == 1, (
                f"burst {g} partially departed: {members} vs {sorted(gone)}")
        assert len(ch._live) <= ch.cap + ch.gang_size


def _bursts_of(created, ch):
    """Reconstruct arrival units from the stub's create order: gang bursts
    are gang_size consecutive names, preempt bursts a single name (the
    churner's preempt_every cadence)."""
    units, i, burst_no = {}, 0, 0
    while i < len(created):
        burst_no += 1
        size = 1 if burst_no % ch.preempt_every == 0 else ch.gang_size
        units[burst_no] = created[i:i + size]
        i += size
    return units
