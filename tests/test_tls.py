"""TLS serving + x509 client-cert authentication.

Parity target: reference pkg/genericapiserver/genericapiserver.go:638
(secure port with --tls-cert-file/--client-ca-file) and
plugin/pkg/auth/authenticator/request/x509 (verified client cert subject
CN -> user, O -> groups), authorized through RBAC (round-4 verdict #10).
"""

import pytest

# utils/certs delegates to the optional `cryptography` package; without it
# these tests can't mint a CA — skip at collection instead of erroring
pytest.importorskip("cryptography")

from kubernetes_tpu.api import types as api
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apis import rbac
from kubernetes_tpu.auth import (
    RBACAuthorizer, TokenAuthenticator, UnionAuthenticator, X509Authenticator,
)
from kubernetes_tpu.auth.user import UserInfo
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.registry.generic import Registry
from kubernetes_tpu.utils.certs import CertAuthority


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("pki"))
    ca = CertAuthority()
    server = ca.write_bundle(d, "server", "kube-apiserver", server=True)
    alice = ca.write_bundle(d, "alice", "alice", organizations=["dev", "qa"])
    mallory_ca = CertAuthority("evil-ca")
    mallory = mallory_ca.write_bundle(d + "/evil", "mallory", "alice")
    return {"ca": ca, "server": server, "alice": alice, "mallory": mallory}


def tls_server(pki, authorizer=None, **kw):
    return APIServer(
        tls_cert_file=pki["server"]["cert"],
        tls_key_file=pki["server"]["key"],
        client_ca_file=pki["server"]["ca"],
        authenticator=UnionAuthenticator([
            X509Authenticator(),
            TokenAuthenticator({"sekrit": UserInfo(name="tokenuser",
                                                   uid="t1")}),
        ]),
        authorizer=authorizer, **kw).start()


def grant_rbac(registry: Registry, subject_kind: str, subject: str):
    """ClusterRole allowing pod ops + binding for the subject."""
    registry.create("clusterroles", rbac.ClusterRole(
        metadata=api.ObjectMeta(name="pod-admin"),
        rules=[rbac.PolicyRule(verbs=["*"], resources=["pods"],
                               api_groups=[""])]))
    registry.create("clusterrolebindings", rbac.ClusterRoleBinding(
        metadata=api.ObjectMeta(name="pod-admin-binding"),
        subjects=[rbac.Subject(kind=subject_kind, name=subject)],
        role_ref=api.ObjectReference(kind="ClusterRole",
                                     name="pod-admin")))


def mk_pod(name="p0"):
    return api.Pod(metadata=api.ObjectMeta(name=name, namespace="default"),
                   spec=api.PodSpec(containers=[
                       api.Container(name="c", image="img")]))


class TestTLSServing:
    def test_https_crud_with_verified_server_cert(self, pki):
        server = tls_server(pki)
        try:
            client = RESTClient(port=server.port, tls=True,
                                ca_file=pki["server"]["ca"],
                                cert_file=pki["alice"]["cert"],
                                key_file=pki["alice"]["key"])
            created = client.create("pods", mk_pod())
            assert created.metadata.name == "p0"
            assert client.get("pods", "p0", "default").metadata.name == "p0"
        finally:
            server.stop()

    def test_plain_http_to_tls_port_fails(self, pki):
        server = tls_server(pki)
        try:
            client = RESTClient(port=server.port)  # no TLS
            with pytest.raises(Exception):
                client.get("pods", "p0", "default")
        finally:
            server.stop()

    def test_wrong_ca_rejected_by_client(self, pki):
        server = tls_server(pki)
        try:
            evil = CertAuthority("other")
            import tempfile, os
            with tempfile.NamedTemporaryFile(delete=False) as f:
                f.write(evil.ca_pem())
                path = f.name
            client = RESTClient(port=server.port, tls=True, ca_file=path)
            with pytest.raises(Exception):
                client.get("pods", "p0", "default")
            os.unlink(path)
        finally:
            server.stop()


class TestX509Identity:
    def test_cert_cn_o_maps_to_user_groups_via_rbac(self, pki):
        """alice's cert (CN=alice, O=dev,qa) authorized by an RBAC binding
        to the 'dev' GROUP — proves both CN->user and O->groups land."""
        registry = Registry()
        grant_rbac(registry, "Group", "dev")
        server = tls_server(pki, authorizer=RBACAuthorizer(registry),
                            registry=registry)
        try:
            alice = RESTClient(port=server.port, tls=True,
                               ca_file=pki["server"]["ca"],
                               cert_file=pki["alice"]["cert"],
                               key_file=pki["alice"]["key"])
            assert alice.create("pods", mk_pod()).metadata.name == "p0"
            # token identity has no binding -> 403
            token = RESTClient(port=server.port, tls=True,
                               ca_file=pki["server"]["ca"],
                               bearer_token="sekrit")
            with pytest.raises(ApiError) as ei:
                token.get("pods", "p0", "default")
            assert ei.value.code == 403
            # no identity at all -> 401
            anon = RESTClient(port=server.port, tls=True,
                              ca_file=pki["server"]["ca"])
            with pytest.raises(ApiError) as ei:
                anon.get("pods", "p0", "default")
            assert ei.value.code == 401
        finally:
            server.stop()

    def test_cert_from_untrusted_ca_is_not_an_identity(self, pki):
        """mallory's cert says CN=alice but is signed by an untrusted CA:
        the TLS layer must refuse the chain — impersonation by unverified
        cert is the attack x509 authn exists to stop."""
        registry = Registry()
        grant_rbac(registry, "User", "alice")
        server = tls_server(pki, authorizer=RBACAuthorizer(registry),
                            registry=registry)
        try:
            mallory = RESTClient(port=server.port, tls=True,
                                 ca_file=pki["server"]["ca"],
                                 cert_file=pki["mallory"]["cert"],
                                 key_file=pki["mallory"]["key"])
            with pytest.raises(Exception) as ei:
                mallory.get("pods", "p0", "default")
            # either the handshake dies or the server treats it as
            # anonymous 401 — never a 200/403-as-alice
            assert not isinstance(ei.value, ApiError) or ei.value.code == 401
        finally:
            server.stop()

    def test_entrypoint_serves_https(self, pki, tmp_path):
        """python -m kubernetes_tpu.apiserver --tls-cert-file ... serves
        https and authenticates client certs (flag surface parity)."""
        import subprocess, sys, time
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.apiserver",
             "--port", "0",
             "--tls-cert-file", pki["server"]["cert"],
             "--tls-private-key-file", pki["server"]["key"],
             "--client-ca-file", pki["server"]["ca"]],
            stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert "listening on https://" in line, line
            port = int(line.strip().rsplit(":", 1)[1])
            client = RESTClient(port=port, tls=True,
                                ca_file=pki["server"]["ca"],
                                cert_file=pki["alice"]["cert"],
                                key_file=pki["alice"]["key"])
            deadline = time.monotonic() + 10
            while True:
                try:
                    client.create("pods", mk_pod("tls-e"))
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
            assert client.get("pods", "tls-e",
                              "default").metadata.name == "tls-e"
        finally:
            proc.terminate()
            proc.wait(timeout=10)
