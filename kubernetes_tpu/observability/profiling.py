"""Kernel device profiling: jax.profiler hooks + live trace windows.

Two layers:

- **Always-on device/host split.** ``record_dispatch`` feeds
  ``scheduler_kernel_device_seconds{stage,component}``: the kernel dispatch
  path times its host side (trace/lower/dispatch — the async
  ``_schedule_jit`` call returning) separately from its device side (the
  blocking materialization that cannot complete until the scan has run),
  so "2.3 s solve" decomposes into "40 ms host + 2.26 s device" without
  opening a profiler. Host-only stages (tensorize) report a host component
  only.
- **On-demand trace windows.** ``start_profile``/``stop_profile`` wrap
  ``jax.profiler.start_trace``/``stop_trace`` with state tracking, and
  every watchdog stage runs inside a ``jax.profiler.TraceAnnotation`` (via
  ``annotate``) so an open window shows tensorize/upload/compile/solve as
  named regions in the trace viewer. The debugserver exposes this as
  ``/profilez`` (``/profilez/start?dir=...``, ``/profilez/stop``) on every
  component, so a live scheduler can be profiled without a restart.

jax import is deferred and failure-tolerant throughout: profiling must
never be the reason a component can't run.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

DEVICE_METRIC = "scheduler_kernel_device_seconds"

_lock = threading.Lock()
_state = {"dir": None, "started_at": None}


def record_dispatch(stage: str, host_seconds: float,
                    device_seconds: Optional[float] = None,
                    registry=METRICS) -> None:
    """Export one stage's host/device time split."""
    registry.observe(DEVICE_METRIC, host_seconds,
                     stage=stage, component="host")
    if device_seconds is not None:
        registry.observe(DEVICE_METRIC, device_seconds,
                         stage=stage, component="device")


@contextmanager
def annotate(name: str):
    """jax.profiler.TraceAnnotation when a profiler is importable, no-op
    otherwise — the one wrapper every pipeline stage runs under, so an open
    /profilez window sees named kernel regions."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        yield
        return
    with TraceAnnotation(name):
        yield


# --- live trace windows (/profilez) -------------------------------------------


def profile_status() -> dict:
    with _lock:
        if _state["dir"] is None:
            return {"active": False}
        return {"active": True, "dir": _state["dir"],
                "seconds": round(time.monotonic() - _state["started_at"], 3)}


def start_profile(log_dir: str = "") -> dict:
    """Open a jax profiler trace window. One window at a time per process —
    a second start while one is open is an error, not a silent restart."""
    import jax.profiler

    log_dir = log_dir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"ktpu-profile-{os.getpid()}-{int(time.time())}")
    with _lock:
        if _state["dir"] is not None:
            raise RuntimeError(
                f"profile already active (dir={_state['dir']})")
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
        _state["dir"] = log_dir
        _state["started_at"] = time.monotonic()
    METRICS.inc("profiler_windows_total", event="start")
    return {"active": True, "dir": log_dir}


def stop_profile() -> dict:
    """Close the open trace window; returns where the trace landed and how
    many artifact files the profiler wrote."""
    import jax.profiler

    with _lock:
        if _state["dir"] is None:
            raise RuntimeError("no profile active")
        log_dir, t0 = _state["dir"], _state["started_at"]
        try:
            jax.profiler.stop_trace()
        finally:
            _state["dir"] = None
            _state["started_at"] = None
    files = 0
    for _root, _dirs, names in os.walk(log_dir):
        files += len(names)
    METRICS.inc("profiler_windows_total", event="stop")
    return {"active": False, "dir": log_dir, "files": files,
            "seconds": round(time.monotonic() - t0, 3)}
