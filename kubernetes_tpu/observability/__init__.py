"""Cluster observatory: scrape -> SLI -> SLO -> soak, plus kernel profiling.

- `scrape`: Prometheus text-exposition parser + multi-target scraper with
  counter/histogram delta math (SLIs from what components EXPORT).
- `slo`: declarative SLO specs evaluated as multi-window burn rates,
  surfaced as metrics + Events.
- `soak`: the kubemark churn soak harness (sustained create/bind/delete
  with scraped steady-state SLIs) behind `bench.py --mode soak`.
- `profiling`: jax.profiler hooks — the always-on host/device time split
  (`scheduler_kernel_device_seconds`) and the `/profilez` trace windows.
- `audit`: the apiserver's structured per-request audit log (ring +
  rotating disk sink), served at `/auditz`.
- `flightrecorder`: the black box — spans/Events/audit/metric-delta rings
  dumped as one forensic JSON bundle on stage timeouts, wedged soaks, and
  SLO burn transitions.
"""

from kubernetes_tpu.observability.audit import (  # noqa: F401
    AUDIT, AuditLog, AuditRecord,
)
from kubernetes_tpu.observability.flightrecorder import (  # noqa: F401
    RECORDER, FlightRecorder,
)
from kubernetes_tpu.observability.scrape import (  # noqa: F401
    Family, HistogramSnapshot, Scraper, parse_prometheus_text,
)
from kubernetes_tpu.observability.slo import (  # noqa: F401
    SLOEngine, SLOResult, SLOSpec, Window,
)
from kubernetes_tpu.observability.soak import (  # noqa: F401
    SoakConfig, default_slos, run_soak,
)
