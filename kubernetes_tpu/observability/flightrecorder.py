"""Black-box flight recorder: dump the last N seconds of everything.

The observatory (scrape -> SLO -> soak) DETECTS failure; this module makes
failure EXPLAINABLE from artifacts alone.  It continuously rides on the
bounded rings the rest of the system already maintains — finished spans
(`utils/trace.recent_spans`), locally emitted Events
(`utils/events.recent_events`), apiserver audit records
(`observability/audit.AUDIT`) — plus its own notes ring (soak rounds,
metric deltas), and on a trigger serializes all of them into ONE forensic
JSON bundle:

- a kernel stage watchdog fires (`ops/watchdog.run_stages`),
- a soak run goes ``wedged: true`` (`observability/soak.py`),
- an SLO transitions to burning (`observability/slo.SLOEngine`).

Bundles are bounded on disk (`keep` newest survive) and rate-limited per
reason (`min_interval`) so a hang that fires every batch produces a handful
of bundles, not thousands; the triggers that must attach a path to a report
pass ``force=True``.  `bench.py` embeds the bundle path in its JSON so a
wedged BENCH round is diagnosable without re-running anything.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from kubernetes_tpu.observability.audit import AUDIT
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso

log = logging.getLogger("flightrecorder")

BUNDLE_KIND = "ktpu-flight-recorder-bundle"
BUNDLE_VERSION = 1

# counter families whose per-label series are broken out in full (beyond the
# family totals) — the ones a wedge postmortem reads first
_FOCUS_COUNTERS = (
    "scheduler_stage_timeout_total",
    "scheduler_unschedulable_reasons_total",
    "scheduler_status_write_errors_total",
    "soak_phase_timeout_total",
    "slo_violations_total",
    "rest_client_chaos_interventions_total",
    "apiserver_dropped_requests",
    "flight_recorder_dumps_total",
)


def _span_dict(span) -> dict:
    return {
        "name": span.name,
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "parent_id": span.parent_id,
        "duration_seconds": round(span.duration, 6),
        "attrs": dict(span.attrs),
    }


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]


class FlightRecorder:
    def __init__(self, directory: str = "", keep: int = 8,
                 min_interval: float = 5.0, notes_capacity: int = 512):
        self._lock = threading.Lock()
        self._notes: "deque[dict]" = deque(maxlen=notes_capacity)
        self._last_counter_totals: Dict[str, float] = {}
        self._last_dump_by_reason: Dict[str, float] = {}
        self._seq = 0
        self.keep = keep
        self.min_interval = min_interval
        # per-pid default dir: concurrent processes (verify.sh soak smokes,
        # the bench restart probe) must not prune each other's bundles
        self.directory = (directory
                          or os.environ.get("KTPU_FLIGHT_DIR")
                          or os.path.join(tempfile.gettempdir(),
                                          f"ktpu-flight-{os.getpid()}"))

    # --- continuous inputs ---------------------------------------------------

    def note(self, kind: str, **payload) -> None:
        """Append one entry to the notes ring (soak rounds, SLO verdicts —
        anything a postmortem wants timestamped next to spans and audit)."""
        with self._lock:
            self._notes.append({"ts": _now_iso(), "kind": kind, **payload})

    def snapshot_metrics(self) -> dict:
        """Record the counter movement since the previous snapshot as a
        metric-delta note; returns the delta dict."""
        totals = METRICS.counter_totals()
        with self._lock:
            prev = self._last_counter_totals
            delta = {name: v - prev.get(name, 0.0)
                     for name, v in totals.items()
                     if v - prev.get(name, 0.0)}
            self._last_counter_totals = totals
            self._notes.append({"ts": _now_iso(), "kind": "metrics_delta",
                                "delta": delta})
        return delta

    # --- the dump ------------------------------------------------------------

    def dump(self, reason: str, trigger: Optional[dict] = None,
             force: bool = True) -> Optional[str]:
        """Write a forensic bundle; returns its path, or None when the
        same reason dumped within `min_interval` and force is False."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump_by_reason.get(reason)
            if (not force and last is not None
                    and now - last < self.min_interval):
                return None
            self._last_dump_by_reason[reason] = now
            self._seq += 1
            seq = self._seq
            notes = list(self._notes)
        from kubernetes_tpu.observability.explain import LEDGER
        from kubernetes_tpu.utils.events import recent_events
        counters = METRICS.counter_totals()
        # span selection: the newest 512, PLUS every timed-out stage span
        # still in the ring regardless of age — at realistic churn the
        # wedge cause fires early and thousands of later spans would push
        # it out of a plain tail, gutting the bundle's whole point. The
        # truncation is recorded, never silent.
        all_spans = trace.recent_spans()
        tail = all_spans[-512:]
        keep = {id(s) for s in tail}
        timed_out = [s for s in all_spans
                     if s.attrs.get("timeout") and id(s) not in keep]
        bundle = {
            "kind": BUNDLE_KIND,
            "version": BUNDLE_VERSION,
            "reason": reason,
            "trigger": trigger or {},
            "ts": _now_iso(),
            "pid": os.getpid(),
            "spans_total_in_ring": len(all_spans),
            "spans_truncated": len(all_spans) > len(tail),
            "spans": [_span_dict(s) for s in timed_out + tail],
            "events": recent_events(256),
            "audit": [r.to_dict() for r in AUDIT.tail(512)],
            # the decision-ledger tail: what the solve was DECIDING going
            # into the wedge, per-predicate — "which stage hung" plus "what
            # it was doing" in one artifact
            "decisions": [r.to_dict() for r in LEDGER.tail(128)],
            "notes": notes,
            "metrics": {
                "counters": counters,
                "series": {
                    name: [{**dict(lk), "value": v}
                           for lk, v in series.items()]
                    for name, series in
                    ((n, METRICS.counter_series(n)) for n in _FOCUS_COUNTERS)
                    if series
                },
            },
        }
        try:
            os.makedirs(self.directory, exist_ok=True)
            fname = f"flight-{int(time.time())}-{seq:04d}-{_slug(reason)}.json"
            path = os.path.join(self.directory, fname)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                # default=repr: span attrs may carry exceptions or other
                # non-JSON values; a bundle must never fail to serialize
                json.dump(bundle, fh, default=repr)
            os.replace(tmp, path)
            self._prune()
        except OSError:
            log.exception("flight recorder dump failed (reason=%s)", reason)
            return None
        METRICS.inc("flight_recorder_dumps_total", reason=_slug(reason))
        log.warning("flight recorder bundle written: %s (reason=%s)",
                    path, reason)
        return path

    def _prune(self) -> None:
        try:
            bundles = sorted(
                f for f in os.listdir(self.directory)
                if f.startswith("flight-") and f.endswith(".json"))
        except OSError:
            return
        for stale in bundles[:-self.keep] if self.keep > 0 else bundles:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                log.warning("could not prune stale bundle %s", stale)

    def bundles(self) -> List[str]:
        """Existing bundle paths, oldest first."""
        try:
            return [os.path.join(self.directory, f)
                    for f in sorted(os.listdir(self.directory))
                    if f.startswith("flight-") and f.endswith(".json")]
        except OSError:
            return []


RECORDER = FlightRecorder()
