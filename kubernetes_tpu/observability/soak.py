"""Kubemark churn soak: sustained create/bind/delete with scraped SLIs.

The flagship bench measures one-shot batch solves; BASELINE.json's headline
metric is *steady-state* "pods/sec + p99 schedule latency". This harness
closes that gap (ROADMAP item 2): it boots a `HollowCluster` behind a live
API server, sustains a configurable pod creation rate while deleting the
oldest pods to hold a bounded in-flight population (real churn, not a
draining queue), and — crucially — observes the run the way an operator
would: a `Scraper` polls the component debugserver's `/metrics` every
round, round SLIs (pods/s, e2e p50/p99, queue wait, watch lag) are computed
from *scraped* counter/histogram deltas, and an `SLOEngine` evaluates
multi-window burn rates against declarative objectives as it goes.

Self-observation is the point (the BENCH_r05 postmortem: a wedged run
reported 0.0 pods/s as if it were a measurement): every phase runs under a
watchdog deadline, a phase that hangs ends the soak with
``wedged: true`` + the phase name, and a nonzero scraped
``scheduler_stage_timeout_total`` delta — the scheduler's own watchdog
firing mid-churn — also marks the report wedged. ``bench.py --mode soak``
turns a wedged report into a nonzero exit.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.ops import watchdog
from kubernetes_tpu.observability.flightrecorder import RECORDER
from kubernetes_tpu.observability.scrape import Scraper
from kubernetes_tpu.observability.slo import SLOEngine, SLOSpec, Window
from kubernetes_tpu.utils.metrics import finite_round

log = logging.getLogger("soak")

E2E_HIST = "scheduler_e2e_scheduling_latency_seconds"
QUEUE_HIST = "scheduler_pod_queue_wait_seconds"
TIMEOUT_COUNTER = "scheduler_stage_timeout_total"
REASONS_COUNTER = "scheduler_unschedulable_reasons_total"
PREEMPT_COUNTER = "scheduler_preemptions_total"
GANG_COUNTER = "scheduler_gang_placements_total"

SOAK_PHASES = ("boot", "churn", "drain", "report")


@dataclass
class SoakConfig:
    num_nodes: int = 100
    create_rate: float = 100.0        # sustained pod creations per second
    duration_seconds: float = 30.0    # churn phase length
    scrape_period: float = 2.0        # one scrape round + SLO evaluation
    warmup_rounds: int = 1            # rounds excluded from steady state
    max_in_flight: int = 0            # live pod cap; 0 = 2s worth of rate
    batch_size: int = 256
    # micro-batch window: the scheduler solves every `microbatch_ms` (or a
    # full batch, whichever first) instead of per-burst — the report's
    # `microbatch` block carries rounds-per-second next to pods/s
    microbatch_ms: float = 0.0
    heartbeat_period: float = 10.0
    drain_timeout: float = 30.0       # wait for stragglers after churn
    # scenario: "churn" (singleton pods), "gang_churn" — gangs of
    # `gang_size` pods arriving/departing as units under the gang_preempt
    # objective, with an occasional whole-node high-priority pod applying
    # preemption pressure (every `preempt_every`-th creation burst) — or
    # "leader_kill": the same churn against a 3-member ReplicatedStore and
    # `apiservers` API servers behind the discovery proxy, with the storage
    # LEADER and one apiserver killed mid-churn (chaos as a first-class
    # scenario, ROADMAP item 4) — the report must show zero lost acked
    # bindings, the failover window, and a flight-recorder bundle
    scenario: str = "churn"
    gang_size: int = 3
    preempt_every: int = 8
    # leader_kill knobs
    apiservers: int = 2
    store_members: int = 3
    kill_at_fraction: float = 0.4     # of duration_seconds into the churn
    rejoin_after: float = 1.0         # seconds after the kill
    data_dir: str = ""                # member data dirs; "" = mkdtemp
    objective: str = ""               # "" = scenario default
    # SLO objectives (specs built in default_slos; override via `slos`)
    slo_pods_per_sec: float = 0.0     # 0 = half the create rate
    slo_e2e_p99_seconds: float = 4.0
    slo_watch_lag_seconds: float = 2.0
    slos: Optional[List[SLOSpec]] = None
    # per-phase watchdog deadlines; missing phases get defaults
    phase_deadlines: Dict[str, float] = field(default_factory=dict)
    # kernel stage deadlines passed through to the BatchScheduler
    stage_deadlines: Optional[dict] = None
    # fault injection (tests / chaos): seed a hang in this kernel stage with
    # a tiny deadline — the soak must end wedged, never hung
    hang_stage: str = ""

    def in_flight_cap(self) -> int:
        return self.max_in_flight or max(int(self.create_rate * 2), 50)

    def effective_objective(self) -> str:
        """The scheduling objective the soak's scheduler runs under."""
        if self.objective:
            return self.objective
        return "gang_preempt" if self.scenario == "gang_churn" else ""

    def deadlines(self) -> Dict[str, float]:
        d = {"boot": 120.0,
             "churn": self.duration_seconds * 3 + 60.0,
             "drain": self.drain_timeout * 2 + 30.0,
             "report": 60.0}
        d.update(self.phase_deadlines)
        return d


def default_slos(cfg: SoakConfig, target: str) -> List[SLOSpec]:
    """The BASELINE-shaped objectives: steady pods/s, e2e schedule p99, and
    informer watch lag, each over a (long, short) burn-rate window pair
    derived from the scrape period."""
    long_w, short_w = cfg.scrape_period * 4, cfg.scrape_period
    windows = (Window(long_w, 1.0), Window(short_w, 1.0))
    return [
        SLOSpec(name="pods-per-sec", target=target, sli="hist_rate",
                metric=E2E_HIST, bound="min",
                objective=cfg.slo_pods_per_sec or cfg.create_rate / 2,
                windows=windows),
        SLOSpec(name="schedule-e2e-p99", target=target, sli="quantile",
                metric=E2E_HIST, quantile=0.99, bound="max",
                objective=cfg.slo_e2e_p99_seconds, windows=windows),
        SLOSpec(name="informer-watch-lag", target=target, sli="gauge",
                metric="informer_watch_lag_seconds",
                labels=(("resource", "pods"),), bound="max",
                objective=cfg.slo_watch_lag_seconds, windows=windows),
    ]


def _e2e_count(rnd) -> float:
    """Absolute e2e-histogram observation count in a scraped round (0.0
    when the series hasn't appeared yet)."""
    fam = rnd.families.get(E2E_HIST) if rnd is not None else None
    h = fam.histogram() if fam is not None else None
    return h.count if h is not None else 0.0


def _reasons_of(rnd) -> Dict[str, float]:
    """Absolute scheduler_unschedulable_reasons_total values by predicate
    in a scraped round."""
    return _counter_abs(rnd, REASONS_COUNTER, "predicate")


def _reasons_delta(rnd, base: Dict[str, float]) -> Dict[str, float]:
    """Per-predicate unschedulable-reason movement vs the boot baseline —
    reasons from before this soak are not this soak's reasons."""
    return _counter_delta(rnd, base, REASONS_COUNTER, "predicate")


def _mk_pod(i: int, labels=None, annotations=None, cpu="100m"):
    from kubernetes_tpu.api import types as api
    lbls = {"app": "soak"}
    lbls.update(labels or {})
    return api.Pod(
        metadata=api.ObjectMeta(name=f"soak-{i:07d}", namespace="default",
                                labels=lbls, annotations=annotations),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="pause",
            resources=api.ResourceRequirements(
                requests={"cpu": cpu, "memory": "100Mi"}))]))


def _counter_delta(rnd, base: Dict[str, float], metric: str,
                   label: str) -> Dict[str, float]:
    """Per-label-value movement of a counter family vs an absolute
    baseline snapshot — counts from before this soak are not this
    soak's counts."""
    out = {}
    for k, v in _counter_abs(rnd, metric, label).items():
        delta = v - base.get(k, 0.0)
        if delta > 0:
            out[k] = delta
    return out


def _counter_abs(rnd, metric: str, label: str) -> Dict[str, float]:
    fam = rnd.families.get(metric) if rnd is not None else None
    return ({dict(lk).get(label, "?"): v for lk, v in fam.samples.items()}
            if fam else {})


class _Churner:
    """Paced create/delete driver: creates pods at `rate`, deletes the
    oldest once the live population exceeds the cap (bind happens in the
    scheduler between the two)."""

    def __init__(self, client, rate: float, cap: int):
        self.client = client
        self.rate = rate
        self.cap = cap
        self.created = 0
        self.deleted = 0
        self.create_errors = 0
        self._live: List[str] = []
        self._debt = 0.0
        self._last = None

    def tick(self, now: float) -> None:
        if self._last is None:
            self._last = now
            return
        self._debt += (now - self._last) * self.rate
        self._last = now
        n = int(self._debt)
        if n <= 0:
            return
        self._debt -= n
        for _ in range(n):
            try:
                self.client.create("pods", _mk_pod(self.created))
                self._live.append(f"soak-{self.created:07d}")
                self.created += 1
            except Exception as e:
                self.create_errors += 1
                log.warning("soak create failed: %s", e)
        while len(self._live) > self.cap:
            name = self._live.pop(0)
            try:
                self.client.delete("pods", name, "default")
                self.deleted += 1
            except Exception:
                self.deleted += 1  # already gone: deletion still happened


class _GangChurner(_Churner):
    """gang_churn driver: pods arrive as whole gangs of `gang_size` (one
    gang label per burst, so the scheduler must co-place them on one
    topology domain), departures delete whole gangs oldest-first, and every
    `preempt_every`-th burst is ONE whole-node high-priority pod instead —
    sustained preemption pressure once the cluster fills."""

    def __init__(self, client, rate: float, cap: int, gang_size: int,
                 preempt_every: int, node_cpu_m: int = 4000):
        super().__init__(client, rate, cap)
        self.gang_size = max(gang_size, 1)
        self.preempt_every = max(preempt_every, 2)
        self.node_cpu_m = node_cpu_m
        self._bursts = 0
        # name allocator: advances per name handed out, NOT per successful
        # create — a mid-burst create failure must not make the next burst
        # reuse a name that already exists (AlreadyExists would leave that
        # gang permanently short a member)
        self._name_seq = 0
        # arrival bursts, oldest first — departures remove whole units so
        # the cap trim never leaves a partially-departed gang running
        self._groups: list = []

    def tick(self, now: float) -> None:
        from kubernetes_tpu.scheduler.objectives.config import (
            GANG_LABEL, PRIORITY_ANNOTATION,
        )
        if self._last is None:
            self._last = now
            return
        self._debt += (now - self._last) * self.rate
        self._last = now
        while self._debt >= self.gang_size:
            self._debt -= self.gang_size
            self._bursts += 1
            if self._bursts % self.preempt_every == 0:
                # a near-whole-node high-priority pod: schedulable only by
                # evicting lower-priority gang members once nodes fill
                members = [_mk_pod(
                    self._name_seq,
                    annotations={PRIORITY_ANNOTATION: "10"},
                    cpu=f"{self.node_cpu_m - 200}m")]
            else:
                gang = f"gang-{self._bursts:06d}"
                members = [_mk_pod(self._name_seq + j,
                                   labels={GANG_LABEL: gang})
                           for j in range(self.gang_size)]
            self._name_seq += len(members)
            burst = []
            for p in members:
                try:
                    self.client.create("pods", p)
                    burst.append(p.metadata.name)
                    self._live.append(p.metadata.name)
                    self.created += 1
                except Exception as e:
                    self.create_errors += 1
                    log.warning("soak create failed: %s", e)
            if burst:
                self._groups.append(burst)
        # whole units oldest-first: a pod-at-a-time trim goes out of gang
        # alignment at the first 1-pod preempt burst and then splits every
        # gang it touches, which is not the departure pattern this
        # scenario claims to exercise
        while len(self._live) > self.cap and self._groups:
            for name in self._groups.pop(0):
                self._live.pop(0)
                try:
                    self.client.delete("pods", name, "default")
                    self.deleted += 1
                except Exception:
                    self.deleted += 1  # already gone (possibly preempted)


def run_soak(cfg: SoakConfig, scraper: Optional[Scraper] = None) -> dict:
    """Run the churn soak; returns the report dict bench.py --mode soak
    emits. Never hangs: each phase runs under a watchdog deadline and a
    blown deadline ends the run with wedged=true + the phase name."""
    report: dict = {
        "mode": "soak",
        "config": {"nodes": cfg.num_nodes, "create_rate": cfg.create_rate,
                   "duration_seconds": cfg.duration_seconds,
                   "scrape_period": cfg.scrape_period,
                   "in_flight_cap": cfg.in_flight_cap(),
                   "scenario": cfg.scenario,
                   "objective": cfg.effective_objective() or "default"},
        "rounds": [], "slos": [], "wedged": False,
    }
    state: dict = {}
    try:
        watchdog.run_stages(
            lambda stage: _soak_phases(cfg, report, state, stage, scraper),
            deadlines=cfg.deadlines(), registry=None)
    except watchdog.StageTimeout as e:
        # the harness's own watchdog fired: the soak is wedged IN that
        # phase — report it instead of hanging. The worker thread is
        # abandoned mid-call; flag it BEFORE teardown (teardown is what
        # unblocks it) so when it resumes it stops instead of racing us
        # for the report dict.
        state["abandoned"] = True
        report["wedged"] = True
        report["wedged_phase"] = e.stage
        report["error"] = str(e)
        from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
        METRICS.inc("soak_phase_timeout_total", phase=e.stage)
        _attach_bundle(report, "soak-phase-timeout",
                       {"phase": e.stage, "error": str(e)})
    except Exception as e:
        state["abandoned"] = True
        report["error"] = repr(e)
        report["wedged"] = True
        _attach_bundle(report, "soak-error", {"error": repr(e)})
    finally:
        _teardown(state)
    return report


def _attach_bundle(report: dict, reason: str, trigger: dict) -> None:
    """Dump a forensic bundle for a wedged/errored soak and put its path in
    the report — the artifact the next postmortem starts from. Best-effort:
    a failed dump must not mask the wedge verdict itself."""
    trigger = dict(trigger)
    trigger["slos"] = report.get("slos") or (
        report["rounds"][-1].get("slos") if report.get("rounds") else None)
    try:
        path = RECORDER.dump(reason, trigger=trigger)
    except Exception:
        log.exception("flight-recorder dump failed for wedged soak")
        return
    if path is not None:
        report["flight_recorder_bundle"] = path


class SoakAbandoned(RuntimeError):
    """Raised inside the abandoned worker after a phase timeout: the caller
    already returned a wedged report; this thread must stop touching it."""


def _soak_phases(cfg: SoakConfig, report: dict, state: dict, stage,
                 scraper: Optional[Scraper]) -> None:
    def guard(fn):
        # the worker survives its own abandonment (a hung call eventually
        # unblocks during teardown); it must then die quietly, not run the
        # remaining phases against a report the caller already returned
        def inner():
            if state.get("abandoned"):
                raise SoakAbandoned()
            return fn()
        return inner

    stage("boot", guard(lambda: _boot(cfg, state, scraper)))
    stage("churn", guard(lambda: _churn(cfg, state, report)))
    stage("drain", guard(lambda: _drain(cfg, state, report)))
    stage("report", guard(lambda: _finalize(cfg, state, report)))


def _boot(cfg: SoakConfig, state: dict, scraper: Optional[Scraper]) -> None:
    """API server + debugserver + HollowCluster + batch scheduler + scraper
    baseline round. leader_kill boots the replicated control plane instead:
    3-member quorum store under one Registry served by 2 apiservers behind
    the health-gated discovery proxy — every client below talks to the
    PROXY, so the chaos kills exercise the real failover paths."""
    from kubernetes_tpu.api import binary_codec
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.client.record import EventRecorder
    from kubernetes_tpu.kubemark import HollowCluster
    from kubernetes_tpu.scheduler.factory import ConfigFactory
    from kubernetes_tpu.utils.debugserver import DebugServer

    if cfg.scenario == "leader_kill":
        _boot_replicated_plane(cfg, state)
        server = state["server"]
        mk = lambda: RESTClient(port=state["proxy"].port,  # noqa: E731
                                qps=50000, burst=50000)
        client = state["client"] = RESTClient(
            port=state["proxy"].port, qps=50000, burst=50000,
            content_type=binary_codec.CONTENT_TYPE)
    else:
        server = state["server"] = APIServer().start()
        mk = lambda: RESTClient.for_server(  # noqa: E731
            server, qps=50000, burst=50000)
        client = state["client"] = RESTClient.for_server(
            server, qps=50000, burst=50000,
            content_type=binary_codec.CONTENT_TYPE)
    hollow = state["hollow"] = HollowCluster(
        mk(), num_nodes=cfg.num_nodes)
    hollow.start(heartbeat_period=cfg.heartbeat_period)
    factory = state["factory"] = ConfigFactory(client)
    factory.run(timeout=60)
    sched = state["sched"] = factory.create_batch_from_provider(
        batch_size=cfg.batch_size, stage_deadlines=cfg.stage_deadlines,
        objective=cfg.effective_objective() or None,
        microbatch_ms=cfg.microbatch_ms)
    if cfg.hang_stage:
        _seed_hang(sched, cfg.hang_stage)
    # the debug mux every component serves; the scraper reads THIS, not the
    # in-process registry — SLIs come from what the component exports
    dbg = state["debug"] = DebugServer(
        port=0, healthz=sched.healthy,
        configz={"soak": dict(nodes=cfg.num_nodes,
                              create_rate=cfg.create_rate)}).start()
    scr = state["scraper"] = scraper or Scraper()
    scr.add_target("scheduler", "127.0.0.1", dbg.port)
    scr.scrape()  # baseline round: deltas in round 1 measure churn only
    base = scr.last_good("scheduler")
    if base is None:
        # no baseline means every later delta would be absolute counter
        # values — in a long-lived process that miscounts pre-soak history
        # as this soak's (including phantom wedge verdicts). Fatal.
        raise RuntimeError("baseline scrape of the scheduler target failed")
    state["steady_from_ts"] = base.ts
    # kernel-round baselines for the microbatch block (rebased again at
    # warmup end, like the e2e count): boot/warmup rounds are not
    # steady-state cadence
    state["rounds_base"] = sched.kernel_batches
    state["kpods_base"] = sched.kernel_pods
    # absolute baselines (counter values, not rounds): totals stay correct
    # even when a long soak outgrows the scraper's bounded round history
    fam = base.families.get(TIMEOUT_COUNTER)
    state["timeout_base_by_stage"] = (
        {dict(lk).get("stage", "?"): v for lk, v in fam.samples.items()}
        if fam else {})
    state["reasons_base"] = _reasons_of(base)
    state["preempt_base"] = _counter_abs(base, PREEMPT_COUNTER, "reason")
    state["gang_base"] = _counter_abs(base, GANG_COUNTER, "outcome")
    state["e2e_base"] = _e2e_count(base)
    state["steady_base_count"] = state["e2e_base"]
    state["engine"] = SLOEngine(
        scr, cfg.slos if cfg.slos is not None
        else default_slos(cfg, "scheduler"),
        recorder=EventRecorder(client, "soak-harness"))
    sched.run()


def _boot_replicated_plane(cfg: SoakConfig, state: dict) -> None:
    """The leader_kill substrate: ReplicatedStore (3 members) -> one shared
    Registry -> `cfg.apiservers` APIServers -> DiscoveryProxy. Also arms
    the chaos plan, the bind ledger (acked-write loss detection), and the
    controller-leader-election handover probe."""
    import tempfile

    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.client.leaderelection import (
        LeaderElectionConfig, LeaderElector,
    )
    from kubernetes_tpu.discovery import DiscoveryProxy
    from kubernetes_tpu.registry.generic import Registry
    from kubernetes_tpu.storage import ReplicatedStore

    data_dir = cfg.data_dir or tempfile.mkdtemp(prefix="ktpu-leaderkill-")
    store = state["store"] = ReplicatedStore.local(
        data_dir, n=cfg.store_members, heartbeat_period=0.25,
        window=65536, watcher_queue=65536)
    registry = Registry(store)
    servers = state["servers"] = [APIServer(registry).start()
                                  for _ in range(max(cfg.apiservers, 2))]
    state["server"] = servers[0]
    proxy = state["proxy"] = DiscoveryProxy(
        [f"127.0.0.1:{s.port}" for s in servers]).start()

    # acked-bind ledger: watch the FACADE, whose events publish only after
    # the quorum ack — exactly the set of binds the cluster acknowledged.
    # Anything recorded here and later absent/unbound (without a DELETE
    # event) is a lost acknowledged write.
    state["ledger"] = {}
    state["ledger_watch"] = store.watch("/pods/")
    state["lost_bindings_events"] = 0

    # controller/scheduler leader election must span apiserver failover:
    # two electors race for one lease through the proxy; the chaos step
    # gracefully stops the incumbent and measures successor acquisition
    # (the release-on-stop satellite's number)
    state["elect_flags"] = flags = {"a": False, "b": False}
    le_cfg = dict(lock_namespace="default", lock_name="soak-leader",
                  lease_duration=3.0, renew_deadline=2.0, retry_period=0.2)

    def mk_elector(name):
        return LeaderElector(
            RESTClient(port=proxy.port, qps=1000, burst=1000,
                       user_agent=f"soak-elector-{name}"),
            LeaderElectionConfig(identity=f"cm-{name}", **le_cfg),
            on_started_leading=lambda: flags.__setitem__(name, True),
            on_stopped_leading=lambda: None)

    state["elector_a"] = mk_elector("a").run()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not state["elector_a"].is_leader:
        time.sleep(0.05)
    state["elector_b"] = mk_elector("b").run()
    state["chaos"] = {"done": False, "rejoined": False,
                      "killed_member": None, "killed_apiserver": None}


def _drain_ledger(state: dict, timeout: float = 0.0) -> None:
    """Pull pending post-quorum events into the acked-bind ledger. An event
    setting spec.nodeName records the ack; DELETED forgets the pod; an
    OBSERVED un-bind (nodeName present then gone without a delete) is a
    lost acked write the moment it happens."""
    w = state.get("ledger_watch")
    if w is None:
        return
    ledger = state["ledger"]
    while True:
        ev = w.next(timeout=timeout)
        if ev is None:
            return
        if ev.type == "ERROR":
            # slow-watcher drop: the ledger is blind from here — the
            # verdict must say so rather than claim zero loss
            state["ledger_watch"] = None
            state["ledger_dropped"] = True
            return
        key = ev.key
        if ev.type == "DELETED":
            ledger.pop(key, None)
            continue
        node = ((ev.obj.get("spec") or {}).get("nodeName")) or ""
        if node:
            ledger[key] = node
        elif key in ledger:
            state["lost_bindings_events"] += 1


def _inject_chaos(cfg: SoakConfig, state: dict) -> None:
    """The leader_kill moment: kill the storage leader AND the primary
    apiserver mid-churn, and gracefully stop the incumbent controller
    leader — then keep churning. Everything above L0 must ride it out."""
    chaos = state["chaos"]
    chaos["done"] = True
    group = state["store"].group
    chaos["killed_member"] = group.kill_leader()
    # kill the PRIMARY apiserver: the proxy's preferred member, so the
    # rotation path is actually exercised (informers re-list through it)
    victim = state["servers"][0]
    chaos["killed_apiserver"] = f"127.0.0.1:{victim.port}"
    victim.stop()
    chaos["handover_t0"] = time.monotonic()
    # graceful stop releases the lease — but stop() joins the elector's
    # renew thread, which may sit in a request to the apiserver that just
    # died; the churn loop must not wait that out
    import threading
    threading.Thread(target=state["elector_a"].stop,
                     name="chaos-elector-stop", daemon=True).start()
    chaos["t"] = time.monotonic()
    RECORDER.note("chaos_leader_kill",
                  killed_member=chaos["killed_member"],
                  killed_apiserver=chaos["killed_apiserver"])
    RECORDER.snapshot_metrics()
    log.warning("chaos: killed storage leader %s and apiserver %s",
                chaos["killed_member"], chaos["killed_apiserver"])


def _tick_chaos(cfg: SoakConfig, state: dict, now: float) -> None:
    """Per-loop chaos bookkeeping for leader_kill: fire the kill at its
    offset, rejoin the killed member after `rejoin_after`, record the
    elector handover when the successor takes the lease."""
    chaos = state.get("chaos")
    if chaos is None:
        return
    # drain every tick from boot: the ledger watch has a bounded queue,
    # and at 1k-node scale the pre-kill churn alone would overflow it —
    # a dropped watcher makes the loss verdict wrong in both directions
    _drain_ledger(state)
    t0 = state.get("t0", now)
    if not chaos["done"]:
        if now - t0 >= cfg.duration_seconds * cfg.kill_at_fraction:
            _inject_chaos(cfg, state)
        return
    if "handover_t0" in chaos and "handover_seconds" not in chaos \
            and state["elector_b"].is_leader:
        chaos["handover_seconds"] = time.monotonic() - chaos["handover_t0"]
        RECORDER.note("leader_lease_handover",
                      seconds=chaos["handover_seconds"])
    if not chaos["rejoined"] and chaos["killed_member"] is not None \
            and now - chaos["t"] >= cfg.rejoin_after:
        chaos["rejoined"] = True
        try:
            state["store"].group.restart_member(chaos["killed_member"])
            RECORDER.note("chaos_member_rejoined",
                          member=chaos["killed_member"])
        except Exception:
            log.exception("rejoin of killed member failed")


def _seed_hang(sched, stage_name: str) -> None:
    """Fault injection: every kernel batch parks inside `stage_name` (with a
    tiny deadline so the scheduler's watchdog converts it) — the soak must
    finish wedged via the fallback path, never hang."""
    sched.stage_deadlines[stage_name] = 0.2

    def hanging(pending, weights=None, device=None, stage=None, **kw):
        run = stage or (lambda _n, fn: fn())
        return run(stage_name, lambda: time.sleep(3600))

    sched._inc.schedule = hanging


def _churn(cfg: SoakConfig, state: dict, report: dict) -> None:
    if cfg.scenario == "gang_churn":
        churner = state["churner"] = _GangChurner(
            state["client"], cfg.create_rate, cfg.in_flight_cap(),
            cfg.gang_size, cfg.preempt_every)
    else:
        churner = state["churner"] = _Churner(
            state["client"], cfg.create_rate, cfg.in_flight_cap())
    scr: Scraper = state["scraper"]
    engine: SLOEngine = state["engine"]
    state["t0"] = time.monotonic()
    stop = time.monotonic() + cfg.duration_seconds
    next_scrape = time.monotonic() + cfg.scrape_period
    while not state.get("abandoned"):
        now = time.monotonic()
        if now >= stop:
            break
        churner.tick(now)
        _tick_chaos(cfg, state, now)
        if now >= next_scrape:
            next_scrape = now + cfg.scrape_period
            scr.scrape()
            _record_round(cfg, state, report, engine)
        time.sleep(0.01)


def _record_round(cfg: SoakConfig, state: dict, report: dict,
                  engine: SLOEngine) -> None:
    scr: Scraper = state["scraper"]
    churner: _Churner = state["churner"]
    num = finite_round

    delta = scr.hist_delta("scheduler", E2E_HIST)  # adjacent rounds
    report["rounds"].append({
        "t": round(time.monotonic() - state.get("t0", time.monotonic()), 2),
        "created": churner.created, "deleted": churner.deleted,
        "bound_in_round": int(delta.count),
        "pods_per_sec": num(scr.hist_rate("scheduler", E2E_HIST)),
        "e2e_p50_seconds": num(delta.quantile(0.5)),
        "e2e_p99_seconds": num(delta.quantile(0.99)),
        "queue_wait_p99_seconds": num(scr.quantile(
            "scheduler", QUEUE_HIST, 0.99)),
        "watch_lag_seconds": num(scr.gauge_value(
            "scheduler", "informer_watch_lag_seconds", resource="pods")),
        "unschedulable_reasons": _reasons_delta(
            scr.last_good("scheduler"), state.get("reasons_base", {})),
        "slos": {r.name: r.verdict for r in engine.evaluate()},
    })
    rnd = report["rounds"][-1]
    if cfg.scenario == "gang_churn":
        last = scr.last_good("scheduler")
        gangs = _counter_delta(last, state.get("gang_base", {}),
                               GANG_COUNTER, "outcome")
        rnd["preemptions"] = sum(_counter_delta(
            last, state.get("preempt_base", {}),
            PREEMPT_COUNTER, "reason").values())
        rnd["gangs_placed"] = gangs.get("placed", 0.0)
        rnd["gangs_rejected"] = gangs.get("rejected", 0.0)
    # black-box feed: every scraped round (and its counter movement) lands
    # in the flight recorder's notes ring, so a bundle dumped mid-wedge
    # shows the rounds leading INTO it, not just the final state
    RECORDER.note("soak_round", round=rnd)
    RECORDER.snapshot_metrics()
    if len(report["rounds"]) == cfg.warmup_rounds:
        # warmup over: the steady-state aggregate starts at THIS scrape
        last = scr.last("scheduler")
        if last is not None:
            state["steady_from_ts"] = last.ts
            state["steady_base_count"] = _e2e_count(last)
        sched = state.get("sched")
        if sched is not None:
            state["rounds_base"] = sched.kernel_batches
            state["kpods_base"] = sched.kernel_pods


def _drain(cfg: SoakConfig, state: dict, report: dict) -> None:
    """Stop creating; wait (bounded) for the pending queue to empty so the
    steady-state window isn't cut off mid-batch."""
    factory = state["factory"]
    deadline = time.monotonic() + cfg.drain_timeout
    while time.monotonic() < deadline and len(factory.pending) > 0:
        time.sleep(0.05)
    state["scraper"].scrape()


def _finalize(cfg: SoakConfig, state: dict, report: dict) -> None:
    scr: Scraper = state["scraper"]
    churner: _Churner = state.get("churner")
    engine: SLOEngine = state["engine"]
    sched = state["sched"]
    num = finite_round
    out: dict = {}  # staged locally; merged into report in ONE update below

    # the newest PARSED round: an error round (dead target at drain time)
    # has empty families, which would read as "every counter reset to 0" —
    # negative pod counts and a silently dropped wedge verdict
    last = scr.last_good("scheduler")
    if last is None:
        if state.get("abandoned"):
            raise SoakAbandoned()
        report["error"] = "no successful scrape round; SLIs unknowable"
        report["wedged"] = True  # can't prove it wasn't
        return
    from_ts = state.get("steady_from_ts")
    if last is not None and from_ts is not None:
        steady_window = max(last.ts - from_ts, cfg.scrape_period)
    else:
        steady_window = max(
            cfg.duration_seconds - cfg.warmup_rounds * cfg.scrape_period,
            cfg.scrape_period)
    # totals from absolute counter baselines (boot / warmup-end snapshots),
    # NOT from round-window deltas: a soak longer than the scraper's round
    # history must still count every bind
    final_count = _e2e_count(last)
    steady_bound = final_count - state.get("steady_base_count", 0.0)
    out["pods_created"] = churner.created if churner else 0
    out["pods_deleted"] = churner.deleted if churner else 0
    out["create_errors"] = churner.create_errors if churner else 0
    out["pods_bound"] = int(final_count - state.get("e2e_base", 0.0))
    # latency quantiles are window-scoped (bounded history: at most the
    # retained rounds — fine, p50/p99 over the tail is still steady state)
    steady = scr.hist_delta("scheduler", E2E_HIST, steady_window)
    out["steady_state"] = {
        "window_seconds": steady_window,
        "pods_bound": int(steady_bound),
        "pods_per_sec": num(steady_bound / steady_window)
        if steady_window > 0 else None,
        "e2e_p50_seconds": num(steady.quantile(0.5)),
        "e2e_p99_seconds": num(steady.quantile(0.99)),
        "queue_wait_p99_seconds": num(scr.quantile(
            "scheduler", QUEUE_HIST, 0.99, steady_window)),
    }
    out["slos"] = [r.as_dict() for r in engine.evaluate()]
    # the scraped per-predicate unschedulable breakdown for the whole soak
    # (ISSUE 12): {} on a clean run — present either way so consumers can
    # rely on the key
    out["unschedulable_reasons"] = _reasons_delta(
        last, state.get("reasons_base", {}))
    if cfg.scenario == "gang_churn":
        # the objective verdicts for the whole soak, scraped off the same
        # counters the operator's dashboards read (baseline-rebased)
        gangs = _counter_delta(last, state.get("gang_base", {}),
                               GANG_COUNTER, "outcome")
        out["preemptions"] = _counter_delta(
            last, state.get("preempt_base", {}), PREEMPT_COUNTER, "reason")
        out["gangs_placed"] = gangs.get("placed", 0.0)
        out["gangs_rejected"] = gangs.get("rejected", 0.0)
    if cfg.scenario == "leader_kill":
        _finalize_leader_kill(cfg, state, out)
    out["kernel"] = {
        "batches": sched.kernel_batches, "pods": sched.kernel_pods,
        "failures": sched.kernel_failures, "health": sched.health,
    }
    # the micro-batch verdict: solve cadence next to throughput, plus the
    # device-residency proof (the incremental mirror's node-side arrays and
    # victim tables re-upload only on change — last_upload_bytes is the
    # per-round H2D bill, not a full re-tensorize)
    inc = getattr(sched, "_inc", None)
    # steady-window cadence: rounds/pods rebased against the warmup-end
    # snapshot, exactly like the steady_state e2e count above
    steady_rounds = sched.kernel_batches - state.get("rounds_base", 0)
    steady_kpods = sched.kernel_pods - state.get("kpods_base", 0)
    out["microbatch"] = {
        "window_ms": cfg.microbatch_ms,
        "rounds": steady_rounds,
        "rounds_per_second": num(steady_rounds / steady_window)
        if steady_window > 0 else None,
        "avg_pods_per_round": num(steady_kpods / max(steady_rounds, 1)),
        "device_resident": inc is not None,
        "incremental_builds": inc.builds if inc is not None else 0,
        "last_upload_bytes": inc.last_upload_bytes
        if inc is not None else None,
        "last_build_seconds": num(inc.last_build_seconds, 4)
        if inc is not None else None,
    }
    rounds = list(scr._rounds.get("scheduler", ()))
    out["scrape"] = {
        "target": "scheduler", "rounds": len(rounds),
        "errors": sum(1 for r in rounds if r.error),
        # quantiles above only see the retained rounds when true
        "history_truncated": len(rounds) >= scr._history,
    }
    # the wedge verdict, from the SCRAPED surface: the scheduler's own
    # stage watchdog fired mid-soak (per-stage DELTAS vs the boot baseline
    # — timeouts from before the soak are not this soak's wedge)
    fam = last.families.get(TIMEOUT_COUNTER)
    base_by_stage = state.get("timeout_base_by_stage", {})
    fired = {}
    for lk, v in (fam.samples.items() if fam else ()):
        stage_name = dict(lk).get("stage", "?")
        delta = v - base_by_stage.get(stage_name, 0.0)
        if delta > 0:
            fired[stage_name] = delta
    if fired:
        out["wedged"] = True
        out["stage_timeouts"] = fired
        # the forensic bundle IS the acceptance artifact for a wedged soak:
        # the timed-out stage's span, the audit records around it, and the
        # SLO verdicts, one JSON file whose path rides in the report
        _attach_bundle(out, "soak-wedged", {"stage_timeouts": fired})
    # single merge, re-checking abandonment right before it: if the report
    # phase itself blew its deadline, the caller already returned `report`
    # — this thread must not mutate it mid-serialization
    if state.get("abandoned"):
        raise SoakAbandoned()
    report.update(out)


def _finalize_leader_kill(cfg: SoakConfig, state: dict, out: dict) -> None:
    """The chaos verdict: every acked bind still present, the failover
    window, lease handover time, and member convergence — plus the
    flight-recorder bundle that captures the window (the acceptance
    artifact even on a clean run)."""
    chaos = state.get("chaos") or {}
    group = state["store"].group
    _drain_ledger(state, timeout=0.5)
    ledger = state.get("ledger", {})
    lost = state.get("lost_bindings_events", 0)
    store = state["store"]
    for key, node in ledger.items():
        try:
            obj, _rv = store.get(key)
        except Exception:
            lost += 1  # acked bind vanished without a DELETE event
            continue
        if ((obj.get("spec") or {}).get("nodeName") or "") != node:
            lost += 1
    failover = {
        "killed_member": chaos.get("killed_member"),
        "killed_apiserver": chaos.get("killed_apiserver"),
        "chaos_fired": bool(chaos.get("done")),
        "failover_seconds": finite_round(max(group.failovers), 4)
        if group.failovers else None,
        "leader_transitions": group.leader_transitions,
        "lost_bindings": lost,
        "acked_binds_tracked": len(ledger),
        "election_handover_seconds": finite_round(
            chaos["handover_seconds"], 3)
        if "handover_seconds" in chaos else None,
        "member_rejoined": bool(chaos.get("rejoined")),
        "members_converged": group.converged(),
        "quorum_members_alive": len(group.alive_members()),
        "ledger_dropped": bool(state.get("ledger_dropped")),
    }
    out["failover"] = failover
    if state.get("ledger_dropped"):
        # a blind ledger cannot prove zero loss — never report it as such
        out["wedged"] = True
        out.setdefault("error", "acked-bind ledger watch was dropped; "
                                "loss verdict unprovable")
    if lost or (chaos.get("done") and not group.failovers):
        # lost acked writes — or the kill never produced a failover at
        # all — is exactly the dishonesty this scenario exists to catch
        out["wedged"] = True
        out.setdefault("error",
                       f"leader_kill verdict failed: lost_bindings={lost}, "
                       f"failovers={group.failovers}")
    # the failover window's black box ships on every leader_kill run —
    # spans, audit tail, chaos notes, SLO verdicts around the kill
    _attach_bundle(out, "leader-kill-failover", {"failover": failover})


def _teardown(state: dict) -> None:
    for key, stopper in (("sched", "stop"), ("factory", "stop"),
                         ("hollow", "stop"), ("debug", "stop"),
                         ("elector_a", "stop"), ("elector_b", "stop"),
                         ("ledger_watch", "stop"), ("proxy", "stop")):
        obj = state.get(key)
        if obj is None:
            continue
        try:
            getattr(obj, stopper)()
        except Exception:
            log.exception("soak teardown: %s failed", key)
    for server in state.get("servers", [state.get("server")]):
        if server is None:
            continue
        try:
            server.stop()
        except Exception:
            log.exception("soak teardown: apiserver stop failed")
    store = state.get("store")
    if store is not None:
        try:
            store.close()
        except Exception:
            log.exception("soak teardown: store close failed")
