"""Pull-based metrics collection over component debugserver /metrics.

The SLO engine and the soak harness must compute SLIs from what components
actually EXPORT — not from in-process registry globals — or the published
numbers and the observable surface drift apart (the BENCH_r05 failure mode:
a wedged run reported as if it were data, because nothing scraped the run
while it happened). This module is the collector half:

- ``parse_prometheus_text``: a strict parser for the Prometheus text
  exposition format (the output of ``utils/metrics.render()``): # HELP /
  # TYPE headers, escaped label values (``\\``, ``\"``, ``\\n``), counter /
  gauge samples, and histogram ``_bucket``/``_sum``/``_count`` triples
  reassembled into cumulative-bucket snapshots.
- ``Scraper``: named HTTP targets, a bounded ring of timestamped rounds per
  target, and the delta math on top: counter deltas (reset-aware), rates,
  and histogram-window quantiles between any two rounds — the inputs the
  SLO burn-rate windows consume.

Scrape failures are themselves observable (``observability_scrape_total``
with an ``outcome`` label) and never raise out of ``scrape()``: a dead
component mid-soak is a finding, not a crash.
"""

from __future__ import annotations

import http.client
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

_ESCAPES = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        pair = s[i:i + 2]
        if pair in _ESCAPES:
            out.append(_ESCAPES[pair])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    """Parse the inside of a {...} label block, honoring escapes."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.index("=", i)
        name = body[i:eq].strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        val = []
        while j < n:
            c = body[j]
            if c == "\\":
                val.append(body[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        else:
            raise ValueError(f"unterminated label value in {body!r}")
        labels[name] = _unescape("".join(val))
        i = j + 1
        while i < n and body[i] in ", ":
            i += 1
    return labels


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


@dataclass
class HistogramSnapshot:
    """Cumulative-bucket state of one histogram series at scrape time."""

    buckets: Dict[float, float] = field(default_factory=dict)  # le -> cum
    sum: float = 0.0
    count: float = 0.0

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-th observation; NaN for
        an empty series (no samples != zero latency)."""
        if self.count <= 0:
            return float("nan")
        target = q * self.count
        for le in sorted(self.buckets):
            if self.buckets[le] >= target:
                return le
        return float("inf")

    def delta(self, before: Optional["HistogramSnapshot"]) -> "HistogramSnapshot":
        """Observations made between `before` and this snapshot. A count
        that went backwards means the exporter restarted — the delta is
        then this snapshot itself (same reset rule as counters)."""
        if before is None or before.count > self.count:
            return HistogramSnapshot(dict(self.buckets), self.sum, self.count)
        return HistogramSnapshot(
            {le: c - before.buckets.get(le, 0.0)
             for le, c in self.buckets.items()},
            self.sum - before.sum, self.count - before.count)


@dataclass
class Family:
    """One metric family parsed from an exposition."""

    name: str
    type: str = "untyped"
    help: str = ""
    # counter/gauge: label tuple -> value
    samples: Dict[Tuple, float] = field(default_factory=dict)
    # histogram: label tuple (le stripped) -> snapshot
    histograms: Dict[Tuple, HistogramSnapshot] = field(default_factory=dict)

    def value(self, **labels) -> float:
        return self.samples.get(tuple(sorted(labels.items())), float("nan"))

    def total(self) -> float:
        """Sum across every label combination (counter families)."""
        return sum(self.samples.values())

    def histogram(self, **labels) -> Optional[HistogramSnapshot]:
        return self.histograms.get(tuple(sorted(labels.items())))


_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_prometheus_text(text: str) -> Dict[str, Family]:
    """Parse a /metrics payload into {family name: Family}. Histogram
    `_bucket`/`_sum`/`_count` samples are folded back into their family
    (the one `# TYPE <name> histogram` declares)."""
    families: Dict[str, Family] = {}
    declared_hist: set = set()

    def fam(name: str) -> Family:
        f = families.get(name)
        if f is None:
            f = families[name] = Family(name)
        return f

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                f = fam(parts[2])
                f.type = parts[3].strip() if len(parts) > 3 else "untyped"
                if f.type == "histogram":
                    declared_hist.add(parts[2])
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam(parts[2]).help = _unescape(
                    parts[3] if len(parts) > 3 else "")
            continue
        # sample line: name[{labels}] value [timestamp]
        if "{" in line:
            name = line[:line.index("{")]
            rest = line[line.index("{") + 1:]
            # find the real closing brace: '}' inside a QUOTED label value
            # is literal (the format escapes only \\ \" \n — braces stay
            # raw), so track quote state, not just backslashes
            depth_end, j, in_quotes = None, 0, False
            while j < len(rest):
                c = rest[j]
                if c == "\\" and in_quotes:
                    j += 2
                    continue
                if c == '"':
                    in_quotes = not in_quotes
                elif c == "}" and not in_quotes:
                    depth_end = j
                    break
                j += 1
            if depth_end is None:
                raise ValueError(f"unterminated label block: {line!r}")
            labels = _parse_labels(rest[:depth_end])
            value = _parse_value(rest[depth_end + 1:].split()[0])
        else:
            name, value_s = line.split(None, 2)[:2]
            labels, value = {}, _parse_value(value_s)

        base = None
        for suffix in _HIST_SUFFIXES:
            if name.endswith(suffix) and name[: -len(suffix)] in declared_hist:
                base = name[: -len(suffix)]
                break
        if base is not None:
            suffix = name[len(base):]
            le = labels.pop("le", None)
            lk = tuple(sorted(labels.items()))
            snap = fam(base).histograms.setdefault(lk, HistogramSnapshot())
            if suffix == "_bucket":
                if le is None:
                    raise ValueError(f"bucket sample without le: {line!r}")
                snap.buckets[_parse_value(le)] = value
            elif suffix == "_sum":
                snap.sum = value
            else:
                snap.count = value
        else:
            fam(name).samples[tuple(sorted(labels.items()))] = value
    return families


@dataclass
class Round:
    """One timestamped scrape of one target."""

    ts: float
    families: Dict[str, Family]
    error: Optional[str] = None


class Scraper:
    """Named /metrics targets + a bounded per-target history of parsed
    rounds, with the counter/histogram delta math the SLO windows read."""

    def __init__(self, history: int = 256, timeout: float = 5.0,
                 clock=time.monotonic, registry=METRICS):
        self._targets: Dict[str, Tuple[str, int, str]] = {}
        self._rounds: Dict[str, deque] = {}
        self._history = history
        self._timeout = timeout
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()

    def add_target(self, name: str, host: str, port: int,
                   path: str = "/metrics") -> None:
        with self._lock:
            self._targets[name] = (host, port, path)
            self._rounds.setdefault(name, deque(maxlen=self._history))

    def targets(self) -> List[str]:
        with self._lock:
            return list(self._targets)

    # --- collection ----------------------------------------------------------

    def _fetch(self, host: str, port: int, path: str) -> str:
        from kubernetes_tpu.utils.nethost import NoDelayHTTPConnection
        conn = NoDelayHTTPConnection(host, port, timeout=self._timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read().decode()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status} from {path}")
            return body
        finally:
            conn.close()

    def scrape(self, name: Optional[str] = None) -> Dict[str, Round]:
        """Pull one round from every target (or just `name`). Failures are
        recorded as an error Round + a counter tick, never raised."""
        with self._lock:
            todo = ({name: self._targets[name]} if name is not None
                    else dict(self._targets))
        out = {}
        for tname, (host, port, path) in todo.items():
            try:
                text = self._fetch(host, port, path)
                rnd = self.ingest(tname, text)
                self._registry.inc("observability_scrape_total",
                                   target=tname, outcome="ok")
            except Exception as e:
                rnd = Round(ts=self._clock(), families={}, error=repr(e))
                with self._lock:
                    self._rounds[tname].append(rnd)
                self._registry.inc("observability_scrape_total",
                                   target=tname, outcome="error")
            out[tname] = rnd
        return out

    def ingest(self, name: str, text: str,
               ts: Optional[float] = None) -> Round:
        """Parse an exposition payload into the target's history — the seam
        scrape() feeds and tests drive directly (no HTTP needed)."""
        rnd = Round(ts=self._clock() if ts is None else ts,
                    families=parse_prometheus_text(text))
        with self._lock:
            self._rounds.setdefault(
                name, deque(maxlen=self._history)).append(rnd)
        return rnd

    # --- reading -------------------------------------------------------------

    def last(self, name: str) -> Optional[Round]:
        with self._lock:
            rounds = self._rounds.get(name)
            return rounds[-1] if rounds else None

    def last_good(self, name: str) -> Optional[Round]:
        """Newest round that actually parsed (scrape failures produce error
        rounds with empty families — reading those as data would turn 'the
        target died' into 'every counter reset to zero')."""
        with self._lock:
            rounds = self._rounds.get(name, ())
            for rnd in reversed(rounds):
                if not rnd.error:
                    return rnd
        return None

    def _window_bounds(self, name: str, window_seconds: Optional[float]
                       ) -> Tuple[Optional[Round], Optional[Round]]:
        """(start round, newest good round). The start is the last round
        at-or-before the cutoff, so the delta covers AT LEAST the window —
        a round landing epsilon past the cutoff (scrape jitter) must not
        silently shrink a one-period window to nothing."""
        with self._lock:
            rounds = [r for r in self._rounds.get(name, ()) if not r.error]
        if not rounds:
            return None, None
        newest = rounds[-1]
        if window_seconds is None:
            # adjacent-round delta
            return (rounds[-2] if len(rounds) > 1 else None), newest
        cutoff = newest.ts - window_seconds
        at_or_before = [r for r in rounds if r.ts <= cutoff]
        return (at_or_before[-1] if at_or_before else rounds[0]), newest

    @staticmethod
    def _counter_between(old: Optional[Round], new: Round, family: str,
                         labels: dict) -> float:
        newf = new.families.get(family)
        if newf is None:
            return float("nan")
        cur = newf.total() if not labels else newf.value(**labels)
        if math.isnan(cur):
            return float("nan")
        if old is None or old is new:
            return cur
        oldf = old.families.get(family)
        prev = (oldf.total() if not labels else oldf.value(**labels)) \
            if oldf is not None else 0.0
        if math.isnan(prev):
            prev = 0.0
        return cur if cur < prev else cur - prev

    @staticmethod
    def _hist_between(old: Optional[Round], new: Optional[Round],
                      family: str, labels: dict) -> HistogramSnapshot:
        empty = HistogramSnapshot()
        if new is None:
            return empty
        newf = new.families.get(family)
        if newf is None:
            return empty
        snap = newf.histogram(**labels)
        if snap is None:
            return empty
        before = None
        if old is not None and old is not new:
            oldf = old.families.get(family)
            before = oldf.histogram(**labels) if oldf is not None else None
        return snap.delta(before)

    def counter_delta(self, name: str, family: str,
                      window_seconds: Optional[float] = None,
                      **labels) -> float:
        """Counter increase over the window (or since the previous round).
        Reset-aware: a value that went backwards restarts the count from
        the new value. NaN when the series was never scraped."""
        old, new = self._window_bounds(name, window_seconds)
        if new is None:
            return float("nan")
        return self._counter_between(old, new, family, labels)

    def counter_rate(self, name: str, family: str,
                     window_seconds: Optional[float] = None,
                     **labels) -> float:
        """Per-second counter rate over the window. One _window_bounds
        call feeds BOTH the numerator delta and the denominator duration —
        a concurrent scrape between two lookups must not skew the rate."""
        old, new = self._window_bounds(name, window_seconds)
        if new is None or old is None or old is new or new.ts <= old.ts:
            return float("nan")
        return self._counter_between(old, new, family, labels) \
            / (new.ts - old.ts)

    def gauge_value(self, name: str, family: str, **labels) -> float:
        rnd = self.last_good(name)
        if rnd is None:
            return float("nan")
        f = rnd.families.get(family)
        return float("nan") if f is None else f.value(**labels)

    def hist_delta(self, name: str, family: str,
                   window_seconds: Optional[float] = None,
                   **labels) -> HistogramSnapshot:
        """Histogram observations inside the window (empty snapshot — NaN
        quantiles — when the series was never scraped)."""
        old, new = self._window_bounds(name, window_seconds)
        return self._hist_between(old, new, family, labels)

    def quantile(self, name: str, family: str, q: float,
                 window_seconds: Optional[float] = None, **labels) -> float:
        return self.hist_delta(name, family, window_seconds,
                               **labels).quantile(q)

    def hist_rate(self, name: str, family: str,
                  window_seconds: Optional[float] = None,
                  **labels) -> float:
        """Observations per second over the window, from the histogram's
        count series — the throughput SLI for latency histograms (each
        e2e-latency observation IS one scheduled pod). Same single-window
        contract as counter_rate."""
        old, new = self._window_bounds(name, window_seconds)
        if new is None or old is None or old is new or new.ts <= old.ts:
            return float("nan")
        return self._hist_between(old, new, family, labels).count \
            / (new.ts - old.ts)
