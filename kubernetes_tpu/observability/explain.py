"""Scheduler decision ledger: per-predicate why/why-not explainability.

The reference scheduler's signature observability artifact is the per-pod
failure breakdown ("0/5000 nodes are available: 3200 Insufficient cpu,
1800 MatchNodeSelector.") — plugin/pkg/scheduler/generic_scheduler.go:40-67
histograms each node's FIRST failing predicate.  The batched kernel
(ops/kernel.py) collapses every predicate into one fused mask, so this
module defines the shared taxonomy both sides speak:

- ``PREDICATES`` is the canonical elimination order.  The kernel emits, per
  pod, cumulative surviving-node counts after each row (static rows from
  static_pass, dynamic rows from the scan step — reductions over the masks
  the solve already computed).  ``oracle_breakdown`` replays the SAME rows
  node-by-node through the Python predicates (scheduler/predicates.py), and
  the oracle-equivalence test (tests/test_explain.py) pins them equal.
- ``decode_batch`` turns the kernel's raw extras into ``DecisionRecord``s:
  elimination histogram for unschedulable pods, winner + runner-up score
  decompositions (scheduler/priorities.py component names) for placed ones.
  Score components the kernel legitimately omits as argmax-neutral
  constants (taint_toleration=10 when no PreferNoSchedule taint is traced,
  equal) are reconstructed here so totals match the priorities.py replay
  exactly.
- ``DecisionLedger`` is the bounded ring behind ``/explainz`` on every
  debug mux and the ``decisions`` block of flight-recorder bundles.
- ``note_unschedulable`` feeds ``scheduler_unschedulable_reasons_total
  {predicate}`` (incremented by eliminated-node count), for both kernel
  decisions (exact, from the record) and sequential-oracle FitErrors
  (parsed from the per-node failure map).

Import-light on purpose: no jax at module import — kernel helpers are
imported lazily inside the decode, so the debug mux can serve /explainz in
processes that never touch a device.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.scheduler.generic import FitError
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso

# Canonical predicate rows, in elimination order: (key, reason text).  The
# kernel's cumulative survivor chain and the Python replay both walk this
# exact order, so "first failing predicate" attribution agrees bit-for-bit.
# MatchNodeSelector covers nodeSelector AND volume-zone labels (the kernel
# folds a bound PV's zone/region requirements into the selector columns —
# ops/tensorize.py _fold_volume_zone).
PREDICATES: Tuple[Tuple[str, str], ...] = (
    ("MatchNodeSelector", "MatchNodeSelector"),
    ("NodeAffinity", "MatchNodeAffinity"),
    ("PodToleratesNodeTaints", "PodToleratesNodeTaints"),
    ("CheckNodeMemoryPressure", "NodeUnderMemoryPressure"),
    ("HostName", "HostName"),
    ("MaxPods", "Too many pods"),
    ("InsufficientCPU", "Insufficient cpu"),
    ("InsufficientMemory", "Insufficient memory"),
    ("InsufficientGPU", "Insufficient gpu"),
    ("PodFitsHostPorts", "PodFitsHostPorts"),
    ("NoDiskConflict", "NoDiskConflict"),
    ("MaxVolumeCount", "MaxVolumeCount"),
    ("MatchInterPodAffinity", "MatchInterPodAffinity"),
)
PREDICATE_KEYS = tuple(k for k, _ in PREDICATES)
# gang mode appends one elimination row after the canonical 13: nodes a
# gang member loses to the topology-domain restriction (no topology label,
# wrong domain, or the whole gang already failed) — ops/kernel.py's
# "gang topology" row
GANG_PREDICATE = ("GangTopology", "NoMatchingGangDomain")
_REASON_TEXT = dict(PREDICATES + (GANG_PREDICATE,))
N_STATIC_ROWS = 5  # selector..host come from static_pass; the rest from scan


def predicate_keys_for(n_rows: int) -> Tuple[str, ...]:
    """Row keys for a survivor tuple: the canonical 13, plus the gang row
    when the solve traced one (len tells which — the axis is static per
    objective config)."""
    keys = PREDICATE_KEYS
    if n_rows > len(keys):
        keys = keys + (GANG_PREDICATE[0],)
    return keys[:n_rows]


# Canonical score component order (scheduler/priorities.py names); decode
# and oracle both emit every component whose weight is nonzero.  Objective
# modes may append non-canonical components ("binpack") after these.
COMPONENTS: Tuple[str, ...] = (
    "least_requested", "balanced", "spread", "node_affinity",
    "taint_toleration", "interpod_affinity", "image_locality", "equal",
)
COMPONENT_ORDER: Tuple[str, ...] = COMPONENTS + ("binpack",)

REASONS_COUNTER = "scheduler_unschedulable_reasons_total"


@dataclass
class DecisionRecord:
    """One scheduling decision, fully explained."""

    pod: str                           # ns/name
    node: Optional[str]                # chosen node; None = unschedulable
    nodes_total: int                   # schedulable-node universe size
    survivors: Tuple[int, ...]         # cumulative, len == len(PREDICATES)
    score: Optional[float] = None
    components: Dict[str, float] = field(default_factory=dict)
    runner_up: Optional[str] = None
    runner_up_score: Optional[float] = None
    runner_up_components: Dict[str, float] = field(default_factory=dict)
    ts: str = ""
    # objective verdicts (scheduler/objectives/decode.annotate_records):
    # preemption = {"node": nominated, "victims": [...]} on a preemptor;
    # gang = {"name": ..., "outcome": "placed"|"rejected"} on a gang member
    preemption: Optional[dict] = None
    gang: Optional[dict] = None

    @property
    def feasible(self) -> int:
        return self.survivors[-1] if self.survivors else 0

    def eliminations(self) -> "OrderedDict[str, int]":
        """predicate key -> nodes it eliminated (first-failure attribution),
        canonical order, zero rows omitted."""
        out: "OrderedDict[str, int]" = OrderedDict()
        prev = self.nodes_total
        for key, surv in zip(predicate_keys_for(len(self.survivors)),
                             self.survivors):
            gone = prev - surv
            if gone > 0:
                out[key] = gone
            prev = surv
        return out

    def to_dict(self) -> dict:
        d = {
            "pod": self.pod, "node": self.node,
            "nodes_total": self.nodes_total,
            "survivors": list(self.survivors),
            "eliminations": dict(self.eliminations()),
            "ts": self.ts,
        }
        if self.preemption is not None:
            d["preemption"] = dict(self.preemption)
        if self.gang is not None:
            d["gang"] = dict(self.gang)
        if self.node is None:
            d["reason"] = format_reason(self)
        else:
            d.update({
                "score": self.score, "components": dict(self.components),
                "runner_up": self.runner_up,
                "runner_up_score": self.runner_up_score,
                "runner_up_components": dict(self.runner_up_components),
                "summary": format_assigned(self),
            })
        return d


def format_reason(rec: DecisionRecord) -> str:
    """The reference-style unschedulable breakdown: '0/N nodes are
    available: <count> <reason>, ...' — counts descending, names as
    tie-break, trailing period included (generic_scheduler.go:40-67
    flavor).  A preemptor's record formats as its nomination instead (the
    same string the FailedScheduling event carries), so every surface
    agrees in preempt mode too."""
    if rec.preemption is not None:
        from kubernetes_tpu.scheduler.objectives.decode import (
            preemption_message,
        )
        return preemption_message(rec.preemption["node"],
                                  rec.preemption["victims"])
    elim = rec.eliminations()
    if not elim:
        return (f"0/{rec.nodes_total} nodes are available: "
                f"no schedulable nodes.")
    parts = ", ".join(
        f"{n} {_REASON_TEXT[k]}"
        for k, n in sorted(elim.items(), key=lambda kv: (-kv[1], kv[0])))
    return f"0/{rec.nodes_total} nodes are available: {parts}."


def format_assigned(rec: DecisionRecord) -> str:
    """Compact winner summary carried on the Scheduled event (and parsed
    back by kubectl describe's Scheduling section)."""
    comps = " ".join(f"{k}={v:g}" for k, v in rec.components.items())
    s = f"score {rec.score:g} ({comps})"
    if rec.runner_up is not None:
        s += f"; runner-up {rec.runner_up} score {rec.runner_up_score:g}"
    return s


def reason_signature(rec: DecisionRecord) -> Tuple[str, ...]:
    """The elimination histogram's SHAPE (which predicates fired, not their
    exact counts): the event-dedup identity, so retries whose counts drift
    with cluster churn still collapse onto one FailedScheduling Event."""
    return tuple(sorted(rec.eliminations().keys()))


class KernelFitError(FitError):
    """FitError whose message is the kernel's reference-style breakdown and
    which carries the full DecisionRecord for metrics/event correlation."""

    def __init__(self, pod, record: DecisionRecord):
        self.explanation = record
        self.signature = reason_signature(record)
        FitError.__init__(self, pod, {})
        self._message = format_reason(record)

    def __str__(self) -> str:
        return self._message


# --- kernel output decode -----------------------------------------------------

def decode_batch(ct, out, extras, weights, feats,
                 objective=None) -> List[DecisionRecord]:
    """Host decode of the kernel's explain extras into DecisionRecords.

    `out` is the [P] assignment vector, `extras` the dict _schedule_jit
    returned (static_surv/surv/win_*/run_*), both already numpy.  Constants
    the kernel omits as argmax-neutral are added back here so totals equal
    the priorities.py replay: taint_toleration contributes a flat
    10*weight when no PreferNoSchedule taint is traced, equal a flat
    weight*1 (already inside the kernel total when its weight is nonzero).

    With an enabled objective config, the emitted component list may carry
    "binpack" and the dynamic survivor block one extra gang-topology row —
    both decoded here; the objective verdicts themselves (victim sets, gang
    outcomes) are stamped afterwards by objectives.decode.annotate_records."""
    from kubernetes_tpu.ops.kernel import explain_component_names

    wd = dict(weights.__dict__)
    emitted = explain_component_names(feats, weights, objective)
    ts = _now_iso()
    NEG_HALF = -5e8  # anything below: the NEG sentinel, not a score

    static_surv = extras["static_surv"]
    dyn_surv = extras["surv"]
    win_comp = extras["win_comp"]
    win_total = extras["win_total"]
    run_idx = extras["run_idx"]
    run_total = extras["run_total"]
    run_comp = extras["run_comp"]

    # canonical component names match Weights fields 1:1
    wmap = {name: wd[name] for name in COMPONENTS}
    taint_const = (float(wmap["taint_toleration"]) * 10.0
                   if "taint_toleration" not in emitted
                   and wmap["taint_toleration"] else 0.0)

    def _components(vec) -> Dict[str, float]:
        comp = {name: float(v) for name, v in zip(emitted, vec)}
        for name in COMPONENTS:
            if name in comp or not wmap[name]:
                continue
            if name == "taint_toleration":
                comp[name] = taint_const
            elif name == "equal":
                comp[name] = float(wmap["equal"])  # already in kernel total
            else:
                comp[name] = 0.0  # oracle value when the feature is absent
        return {name: comp[name] for name in COMPONENT_ORDER if name in comp}

    # the kernel's survivor chain starts from node_valid — in the
    # incremental mirror n_real_nodes is the slot high-water mark and can
    # exceed the live node count (holes), so count the valid mask itself
    nodes_total = int(ct.node_valid.sum())
    records: List[DecisionRecord] = []
    for i in range(ct.n_real_pods):
        surv = tuple(int(round(float(v))) for v in static_surv[i]) + \
            tuple(int(round(float(v))) for v in dyn_surv[i])
        n = int(out[i])
        pod_key = ct.pod_keys[i]
        if n < 0:
            records.append(DecisionRecord(
                pod=pod_key, node=None, nodes_total=nodes_total,
                survivors=surv, ts=ts))
            continue
        rec = DecisionRecord(
            pod=pod_key, node=ct.node_names[n], nodes_total=nodes_total,
            survivors=surv, ts=ts,
            score=float(win_total[i]) + taint_const,
            components=_components(win_comp[i]))
        if float(run_total[i]) > NEG_HALF:
            ri = int(run_idx[i])
            rec.runner_up = ct.node_names[ri]
            rec.runner_up_score = float(run_total[i]) + taint_const
            rec.runner_up_components = _components(run_comp[i])
        records.append(rec)
    return records


# --- the ledger ---------------------------------------------------------------

class DecisionLedger:
    """Bounded ring of the newest decisions + latest-per-pod index, serving
    /explainz and the flight recorder's `decisions` block."""

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: "deque[DecisionRecord]" = deque(maxlen=capacity)
        self._by_pod: Dict[str, DecisionRecord] = {}

    def add(self, rec: DecisionRecord) -> None:
        with self._lock:
            evicted = (self._ring[0]
                       if len(self._ring) == self.capacity else None)
            self._ring.append(rec)
            if evicted is not None and self._by_pod.get(evicted.pod) is evicted:
                del self._by_pod[evicted.pod]
            self._by_pod[rec.pod] = rec

    def get(self, pod: str) -> Optional[DecisionRecord]:
        with self._lock:
            return self._by_pod.get(pod)

    def tail(self, n: int = 256) -> List[DecisionRecord]:
        if n <= 0:
            return []
        with self._lock:
            return list(self._ring)[-n:]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_pod.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


LEDGER = DecisionLedger()


def render_explainz(ledger: DecisionLedger, pod: Optional[str] = None,
                    n=None) -> dict:
    """JSON-ready /explainz payload: the newest-last decision tail, or one
    pod's latest decision (?pod=ns/name)."""
    if pod:
        rec = ledger.get(pod)
        return {"pod": pod,
                "decision": rec.to_dict() if rec is not None else None}
    try:
        count = int(n) if n else 64
    except (TypeError, ValueError):
        count = 64
    return {"capacity": ledger.capacity, "size": len(ledger),
            "decisions": [r.to_dict() for r in ledger.tail(count)]}


# --- metrics ------------------------------------------------------------------

def note_unschedulable(err: Exception) -> None:
    """Feed scheduler_unschedulable_reasons_total{predicate} from a failed
    decision: exact per-predicate eliminated-node counts when the error
    carries a DecisionRecord (kernel path), a parsed per-node failure
    histogram for plain FitErrors (sequential-oracle path)."""
    rec = getattr(err, "explanation", None)
    if rec is not None:
        for pred, count in rec.eliminations().items():
            METRICS.inc(REASONS_COUNTER, float(count), predicate=pred)
        return
    failed = getattr(err, "failed_predicates", None)
    if not failed:
        return
    hist: Dict[str, int] = {}
    for reason in failed.values():
        # generic.find_nodes_that_fit formats values as "<PredicateKey>:
        # <reason>" — take the key. Anything that isn't an identifier-shaped
        # key (manual FitErrors, free text) buckets into "Other": a metric
        # label must never grow one series per node/volume name.
        name = str(reason).split(":", 1)[0].strip()
        if not name.replace("_", "").isalnum():
            name = "Other"
        hist[name] = hist.get(name, 0) + 1
    for name, count in hist.items():
        METRICS.inc(REASONS_COUNTER, float(count), predicate=name)


# --- the Python replay (the oracle-equivalence anchor) ------------------------

def oracle_breakdown(nodes, existing, pending, args, assignments,
                     weights=None, objective=None) -> List[DecisionRecord]:
    """Node-by-node replay of scheduler/predicates.py + priorities.py over
    the canonical rows, with the kernel's sequential-commit semantics (each
    pod's decision sees every prior in-batch commit from `assignments`).

    This is the ground truth the kernel's explain output must match exactly
    (the ISSUE-12 acceptance anchor): cumulative survivor counts per
    predicate row, and — for placed pods — the winner/runner-up weighted
    score decomposition.

    With an enabled objective config the replay delegates to the objective
    oracle (scheduler/objectives/oracle.py), which derives its OWN
    placements/victims/gang verdicts node-by-node — `assignments` is
    ignored there; the oracle-equivalence tests pin the kernel's outputs
    equal to the replay's, not the other way around.  `pending` must
    already be in gang order (objectives.gang_order) in gang mode, exactly
    as the kernel solves it."""
    if objective is not None and getattr(objective, "enabled", False):
        from kubernetes_tpu.scheduler.objectives.oracle import (
            oracle_objective,
        )
        return oracle_objective(nodes, existing, pending, args, objective,
                                weights=weights).records
    from kubernetes_tpu.api.serialization import deep_copy
    from kubernetes_tpu.ops.kernel import Weights
    from kubernetes_tpu.scheduler import predicates as preds
    from kubernetes_tpu.scheduler import priorities as prios
    from kubernetes_tpu.scheduler.cache import NodeInfo

    w = weights or Weights()
    wd = dict(w.__dict__)

    info = {n.metadata.name: NodeInfo(n) for n in nodes}
    for ep in existing:
        name = ep.spec.node_name if ep.spec else ""
        if name in info:
            info[name].add_pod(ep)

    pvc, pv = getattr(args, "pvc_lookup", None), getattr(args, "pv_lookup", None)
    vz = preds.VolumeZoneChecker(pvc, pv) if pvc and pv else None
    vol_ebs = preds.MaxPDVolumeCountChecker(
        "ebs", preds.DEFAULT_MAX_EBS_VOLUMES, pvc, pv)
    vol_gce = preds.MaxPDVolumeCountChecker(
        "gce-pd", preds.DEFAULT_MAX_GCE_PD_VOLUMES, pvc, pv)
    interpod = preds.InterPodAffinity(args.pod_lister, args.node_lookup)
    interpod_prio = prios.InterPodAffinityPriority(
        args.pod_lister, args.node_lookup,
        getattr(args, "hard_pod_affinity_weight", 1))
    spread = prios.SelectorSpread(args.service_lister, args.controller_lister,
                                  args.replicaset_lister)
    prio_fns = {
        "least_requested": prios.least_requested,
        "balanced": prios.balanced_resource_allocation,
        "spread": spread,
        "node_affinity": prios.node_affinity_priority,
        "taint_toleration": prios.taint_toleration_priority,
        "interpod_affinity": interpod_prio,
        "image_locality": prios.image_locality_priority,
        "equal": prios.equal_priority,
    }

    def _res_row(resource):
        def chk(pod, ni):
            try:
                preds.pod_fits_resources(pod, ni)
            except preds.InsufficientResource as e:
                if e.resource == resource:
                    raise
        return chk

    records: List[DecisionRecord] = []
    for i, pod in enumerate(pending):
        sel_pod = deep_copy(pod)
        if sel_pod.spec:
            sel_pod.spec.affinity = None
        aff_pod = deep_copy(pod)
        if aff_pod.spec:
            aff_pod.spec.node_selector = None

        def _sel(p, ni):
            preds.pod_matches_node_selector(sel_pod, ni)
            if vz is not None:
                vz(p, ni)

        def _volcap(p, ni):
            vol_ebs(p, ni)
            vol_gce(p, ni)

        checks = [
            _sel,
            lambda p, ni: preds.pod_matches_node_selector(aff_pod, ni),
            preds.pod_tolerates_node_taints,
            preds.check_node_memory_pressure,
            preds.pod_fits_host,
            _res_row("pods"), _res_row("cpu"),
            _res_row("memory"), _res_row("gpu"),
            preds.pod_fits_host_ports,
            preds.no_disk_conflict,
            _volcap,
            interpod,
        ]
        assert len(checks) == len(PREDICATES)
        interpod.begin_pod(pod)
        cand = list(nodes)
        surv = []
        for chk in checks:
            kept = []
            for nd in cand:
                try:
                    chk(pod, info[nd.metadata.name])
                    kept.append(nd)
                except preds.PredicateFailure:
                    pass
            cand = kept
            surv.append(len(cand))

        host = assignments[i]
        rec = DecisionRecord(pod=f"{pod.metadata.namespace}/{pod.metadata.name}",
                             node=host, nodes_total=len(nodes),
                             survivors=tuple(surv))
        if host is not None:
            names = [name for name in COMPONENTS if wd[name]]
            raw = {name: prio_fns[name](pod, info, cand) for name in names}
            totals = {nd.metadata.name: float(sum(
                wd[name] * raw[name][nd.metadata.name] for name in names))
                for nd in cand}
            rec.components = {name: float(wd[name] * raw[name][host])
                              for name in names}
            rec.score = totals[host]
            best, best_s = None, None
            for nd in cand:
                nm = nd.metadata.name
                if nm == host:
                    continue
                if best_s is None or totals[nm] > best_s:
                    best, best_s = nm, totals[nm]
            rec.runner_up, rec.runner_up_score = best, best_s
            if best is not None:
                rec.runner_up_components = {
                    name: float(wd[name] * raw[name][best]) for name in names}
            # commit (the replay's AssumePod)
            committed = deep_copy(pod)
            committed.spec.node_name = host
            info[host].add_pod(committed)
            if hasattr(args.pod_lister, "pods"):
                args.pod_lister.pods.append(committed)
        records.append(rec)
    return records
