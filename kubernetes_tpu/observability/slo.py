"""Declarative SLOs over scraped SLIs, evaluated as multi-window burn rates.

An ``SLOSpec`` names an SLI (a counter rate, a histogram quantile, or a
gauge), an objective, and a set of evaluation windows. Evaluation follows
the SRE multi-window multi-burn-rate pattern: the *burn rate* is how many
times faster than budget the objective is being consumed —

- for a ``max`` bound (latency, depth): ``burn = sli / objective``
- for a ``min`` bound (throughput):      ``burn = objective / sli``

so burn <= 1 means "inside objective". A spec fires ("burning") only when
EVERY window's burn exceeds its threshold: the long window proves the
violation is sustained, the short window proves it is still happening —
a transient spike trips neither alone.

"No samples" is explicit, not zero: an SLI that evaluates to NaN in any
window yields the ``no_data`` verdict (the empty-series lesson from
``Histogram.quantile``: a silent 0.0 would read as either a perfect
latency or a dead cluster depending on the bound — both wrong).

Verdicts surface three ways: the returned ``SLOResult`` list (what
``bench.py --mode soak`` embeds), ``slo_burn_rate{slo,window}`` gauges +
``slo_evaluations_total{slo,verdict}`` counters on the registry, and —
when a recorder is wired — ``SLOViolation``/``SLORecovered`` Events
through the PR-8 correlation stack, so a sustained burn is one
aggregated Event stream, not a storm.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.observability.scrape import Scraper
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

VERDICT_OK = "ok"
VERDICT_BURNING = "burning"
VERDICT_NO_DATA = "no_data"


@dataclass(frozen=True)
class Window:
    """One evaluation window: the SLI is computed over `seconds` of scrape
    history and compared against `burn_threshold`."""

    seconds: float
    burn_threshold: float = 1.0


@dataclass(frozen=True)
class SLOSpec:
    name: str
    target: str            # scraper target the SLI reads from
    sli: str               # "rate" | "hist_rate" | "quantile" | "gauge"
    metric: str            # family name on that target
    objective: float       # the budget the burn rate is measured against
    bound: str = "max"     # "max": sli must stay <= objective; "min": >=
    quantile: float = 0.99  # for sli == "quantile"
    labels: Tuple[Tuple[str, str], ...] = ()
    windows: Tuple[Window, ...] = (Window(30.0, 1.0), Window(5.0, 1.0))

    def describe(self) -> str:
        op = "<=" if self.bound == "max" else ">="
        sli = (f"p{int(self.quantile * 100)}({self.metric})"
               if self.sli == "quantile" else f"{self.sli}({self.metric})")
        return f"{sli} {op} {self.objective:g}"


@dataclass
class WindowResult:
    seconds: float
    sli: float
    burn: float
    threshold: float

    def as_dict(self) -> dict:
        from kubernetes_tpu.utils.metrics import finite_round
        return {"seconds": self.seconds, "sli": finite_round(self.sli),
                "burn": finite_round(self.burn), "threshold": self.threshold}


@dataclass
class SLOResult:
    name: str
    verdict: str
    objective: str
    windows: List[WindowResult] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"name": self.name, "verdict": self.verdict,
                "objective": self.objective,
                "windows": [w.as_dict() for w in self.windows]}


class SLO:
    """An Event-postable identity for one spec (EventRecorder derives the
    involved-object kind from the class name)."""

    def __init__(self, name: str):
        self.metadata = api.ObjectMeta(name=name, namespace="default")


class SLOEngine:
    def __init__(self, scraper: Scraper, specs: Sequence[SLOSpec],
                 recorder=None, registry=METRICS):
        self.scraper = scraper
        self.specs = list(specs)
        self.recorder = recorder
        self.registry = registry
        self._objects: Dict[str, SLO] = {}
        # SLOs with an open (posted, un-recovered) violation: survives
        # no_data gaps, so burning -> no_data -> ok still closes the loop
        self._open_violations: set = set()

    # --- SLI computation -----------------------------------------------------

    def _sli(self, spec: SLOSpec, window: Window) -> float:
        labels = dict(spec.labels)
        if spec.sli == "rate":
            return self.scraper.counter_rate(spec.target, spec.metric,
                                             window.seconds, **labels)
        if spec.sli == "quantile":
            return self.scraper.quantile(spec.target, spec.metric,
                                         spec.quantile, window.seconds,
                                         **labels)
        if spec.sli == "hist_rate":
            return self.scraper.hist_rate(spec.target, spec.metric,
                                          window.seconds, **labels)
        if spec.sli == "gauge":
            return self.scraper.gauge_value(spec.target, spec.metric,
                                            **labels)
        raise ValueError(f"unknown sli type {spec.sli!r}")

    @staticmethod
    def burn_rate(sli: float, objective: float, bound: str) -> float:
        """How many times over budget the SLI is; <= 1.0 means healthy.
        Only NaN (no samples) maps to NaN/no_data — an INFINITE latency SLI
        (every observation beyond the top bucket) is the worst possible
        violation and must burn infinitely, not read as missing data."""
        if math.isnan(sli):
            return float("nan")
        if bound == "max":
            if objective <= 0:
                return float("inf") if sli > 0 else 0.0
            return sli / objective  # inf / x = inf: beyond-bucket burns
        # bound == "min": zero throughput burns infinitely fast
        if sli <= 0:
            return float("inf")
        return objective / sli  # x / inf = 0: infinite throughput is fine

    # --- evaluation ----------------------------------------------------------

    def evaluate_one(self, spec: SLOSpec) -> SLOResult:
        windows: List[WindowResult] = []
        # an empty windows tuple is a misconfiguration: with no evidence
        # the verdict must be no_data, never a permanent default-burning
        burning, no_data = bool(spec.windows), not spec.windows
        for w in spec.windows:
            sli = self._sli(spec, w)
            burn = self.burn_rate(sli, spec.objective, spec.bound)
            windows.append(WindowResult(w.seconds, sli, burn,
                                        w.burn_threshold))
            if math.isnan(burn):
                no_data = True
            elif burn <= w.burn_threshold:
                burning = False
            # gauge encoding: -1 = no data; inf clamps to a large finite
            # value (a beyond-bucket burn must still read as burning)
            gauge = (-1.0 if math.isnan(burn)
                     else min(burn, 1e9))
            self.registry.set_gauge("slo_burn_rate", gauge,
                                    slo=spec.name, window=f"{w.seconds:g}s")
        verdict = (VERDICT_NO_DATA if no_data
                   else VERDICT_BURNING if burning else VERDICT_OK)
        return SLOResult(spec.name, verdict, spec.describe(), windows)

    def evaluate(self) -> List[SLOResult]:
        results = []
        for spec in self.specs:
            res = self.evaluate_one(spec)
            results.append(res)
            self.registry.inc("slo_evaluations_total",
                              slo=spec.name, verdict=res.verdict)
            if res.verdict == VERDICT_BURNING:
                self.registry.inc("slo_violations_total", slo=spec.name)
                if spec.name not in self._open_violations:
                    # burn TRANSITION (not every round of a sustained burn):
                    # black-box the moment it started — the bundle carries
                    # the spans/audit/rounds leading into the violation
                    self._flight_dump(spec, res)
            self._post_event(spec, res)
            if res.verdict == VERDICT_BURNING:
                self._open_violations.add(spec.name)
            elif res.verdict == VERDICT_OK:
                # a no_data gap in between must not leave the violation
                # dangling forever once the SLI provably recovered
                self._open_violations.discard(spec.name)
        return results

    @staticmethod
    def _flight_dump(spec: SLOSpec, res: SLOResult):
        try:
            # lazy import: flightrecorder depends on the audit module; the
            # SLO engine must stay usable without the apiserver half loaded
            from kubernetes_tpu.observability.flightrecorder import RECORDER
            RECORDER.dump(f"slo-burn-{spec.name}", force=False,
                          trigger={"slo": spec.name,
                                   "objective": spec.describe(),
                                   "windows": [w.as_dict()
                                               for w in res.windows]})
        except Exception:
            import logging
            logging.getLogger("slo").exception(
                "flight-recorder dump failed for burning SLO %s", spec.name)

    def _post_event(self, spec: SLOSpec, res: SLOResult):
        if self.recorder is None:
            return
        obj = self._objects.setdefault(spec.name, SLO(spec.name))
        if res.verdict == VERDICT_BURNING:
            # worst burn including inf (zero throughput burns infinitely —
            # that must read as "inf", not filter away to a garbled "nan")
            worst = max((w.burn for w in res.windows
                         if not math.isnan(w.burn)), default=float("nan"))
            self.recorder.event(
                obj, "Warning", "SLOViolation",
                f"{spec.describe()} burning at {worst:.2f}x budget")
        elif res.verdict == VERDICT_OK and spec.name in self._open_violations:
            self.recorder.event(obj, "Normal", "SLORecovered",
                                f"{spec.describe()} back inside objective")
