"""Structured apiserver audit log.

Parity target: the reference's apiserver audit trail (--audit-log-path with
maxsize/maxbackup rotation, pkg/apiserver audit handler): one structured
record per completed request — verb, path, requesting component
(user-agent) and authenticated user, response status, latency, the trace id
propagated from the client's `traceparent` header, the storage CAS-retry
count the request burned, and the client-reported retry ordinal.

Two sinks, both bounded:

- an in-memory ring (`tail`) — what `/auditz` serves and what the flight
  recorder folds into forensic bundles;
- an optional JSON-lines file with size-based rotation (`path.1`..`path.N`
  backups), enabled via `AuditLog.open()` / the `KTPU_AUDIT_LOG` env var —
  the on-disk trail that survives the process.

`AUDIT` is the process-wide singleton, mirroring the metrics REGISTRY: the
apiserver writes it, every component's debug mux can serve it.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from dataclasses import asdict, dataclass
from typing import List, Optional

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso

log = logging.getLogger("audit")

DEFAULT_CAPACITY = 4096
DEFAULT_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_BACKUPS = 3


@dataclass
class AuditRecord:
    ts: str
    verb: str
    path: str
    component: str = ""      # client User-Agent (one logical client each)
    user: str = ""           # authenticated identity, "" on the insecure port
    status: int = 0          # 0 = connection died before a response was sent
    latency_seconds: float = 0.0
    trace_id: str = ""       # from the client traceparent, or server-minted
    span_id: str = ""        # the server-side request span
    parent_id: str = ""      # the client span that issued the request
    cas_retries: int = 0     # storage CAS conflicts burned serving this
    retries: int = 0         # client-side retry ordinal (x-ktpu-retries)

    def to_dict(self) -> dict:
        return asdict(self)


class AuditLog:
    """Bounded ring + optional rotating JSON-lines file."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 path: str = "", max_bytes: int = DEFAULT_MAX_BYTES,
                 backups: int = DEFAULT_BACKUPS):
        self._lock = threading.Lock()
        self._ring: "deque[AuditRecord]" = deque(maxlen=capacity)
        self._fh = None
        self._path = ""
        self._size = 0
        self._max_bytes = max_bytes
        self._backups = backups
        path = path or os.environ.get("KTPU_AUDIT_LOG", "")
        if path:
            self.open(path, max_bytes=max_bytes, backups=backups)

    # --- disk sink -----------------------------------------------------------

    def open(self, path: str, max_bytes: int = DEFAULT_MAX_BYTES,
             backups: int = DEFAULT_BACKUPS) -> "AuditLog":
        """Attach (or re-point) the rotating on-disk sink."""
        with self._lock:
            self._close_locked()
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._path = path
            self._max_bytes = max_bytes
            self._backups = backups
            self._fh = open(path, "a", encoding="utf-8")
            self._size = self._fh.tell()
        return self

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def close_if(self, path: str) -> None:
        """Close the disk sink only if it still points at `path` — the
        owner-release used by APIServer.stop(), which must not yank a sink
        a newer server has since re-pointed elsewhere."""
        with self._lock:
            if self._path == path:
                self._close_locked()

    def _close_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                log.warning("audit log close failed for %s", self._path)
            self._fh = None
            self._path = ""

    def _rotate_locked(self) -> None:
        self._fh.close()
        # shift path.N-1 -> path.N ... path -> path.1; the oldest falls off
        for i in range(self._backups - 1, 0, -1):
            src, dst = f"{self._path}.{i}", f"{self._path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        if self._backups > 0:
            os.replace(self._path, f"{self._path}.1")
            mode = "a"
        else:
            # no backups: truncate in place — max_bytes must still bound
            # the trail, not silently stop applying
            mode = "w"
        self._fh = open(self._path, mode, encoding="utf-8")
        self._size = 0

    # --- recording -----------------------------------------------------------

    def record(self, rec: AuditRecord) -> None:
        # serialize OUTSIDE the lock: every apiserver handler thread funnels
        # through here, and json.dumps under the lock would make the audit
        # trail a global serialization point (unlocked _fh peek is benign —
        # re-checked under the lock before writing)
        line = (json.dumps(rec.to_dict(), separators=(",", ":"))
                if self._fh is not None else None)
        with self._lock:
            self._ring.append(rec)
            if self._fh is not None and line is not None:
                try:
                    self._fh.write(line + "\n")
                    self._fh.flush()
                    self._size += len(line) + 1
                    if self._size >= self._max_bytes:
                        self._rotate_locked()
                except OSError:
                    # the ring is the primary sink; a full disk must not
                    # turn every API request into a 500
                    log.warning("audit disk write failed for %s", self._path)
        METRICS.inc("apiserver_audit_records_total", verb=rec.verb)

    # --- reads ---------------------------------------------------------------

    def tail(self, n: int = 256, verb: Optional[str] = None,
             path_contains: Optional[str] = None,
             trace_id: Optional[str] = None) -> List[AuditRecord]:
        """Newest-last slice of the ring, optionally filtered. n <= 0 is
        empty — out[-0:] would silently mean "everything"."""
        if n <= 0:
            return []
        with self._lock:
            out = list(self._ring)
        if verb is not None:
            out = [r for r in out if r.verb == verb]
        if path_contains is not None:
            out = [r for r in out if path_contains in r.path]
        if trace_id is not None:
            out = [r for r in out if r.trace_id == trace_id]
        return out[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def render_auditz(audit: AuditLog, n=256) -> dict:
    """JSON payload for the /auditz debug endpoint (newest last). `n` may
    be the raw query-string value — both the apiserver route and the debug
    mux hand it over untouched, so the parse lives in exactly one place."""
    try:
        n = int(n)
    except (TypeError, ValueError):
        n = 256
    records = audit.tail(n)
    return {"count": len(audit), "returned": len(records),
            "records": [r.to_dict() for r in records]}


def now_iso() -> str:
    return _now_iso()


AUDIT = AuditLog()
