"""Authentication and authorization.

Parity target: reference pkg/auth/ + pkg/apiserver/authenticator +
plugin/pkg/auth/ (SURVEY §2.3): request authenticators (bearer token file,
basic auth file, union, anonymous) and authorizers (always-allow, always-deny,
ABAC policy file, RBAC over the rbac API group, union).
"""

from kubernetes_tpu.auth.user import UserInfo  # noqa: F401
from kubernetes_tpu.auth.authenticators import (  # noqa: F401
    AnonymousAuthenticator,
    AuthenticationError,
    BasicAuthenticator,
    TokenAuthenticator,
    UnionAuthenticator,
)
from kubernetes_tpu.auth.x509 import X509Authenticator  # noqa: F401
from kubernetes_tpu.auth.authorizers import (  # noqa: F401
    ABACAuthorizer,
    AlwaysAllow,
    AlwaysDeny,
    AuthzAttributes,
    RBACAuthorizer,
    UnionAuthorizer,
)
