"""Request authenticators.

Parity target: reference pkg/apiserver/authenticator/authn.go — the assembled
chain tries bearer-token then basic auth; plugin/pkg/auth/authenticator/
token/tokenfile (CSV: token,user,uid[,groups]) and password/passwordfile
(CSV: password,user,uid[,groups]). Unauthenticated requests fall through to
the anonymous identity when allowed.
"""

from __future__ import annotations

import base64
import csv
import io
from typing import Dict, List, Optional

from kubernetes_tpu.auth import user as userpkg
from kubernetes_tpu.auth.user import UserInfo


class AuthenticationError(Exception):
    """401 Unauthorized."""


def _parse_rows(text: str):
    """Yield (secret, UserInfo) from the reference's CSV format:
    secret,user,uid[,group1|group2]."""
    for row in csv.reader(io.StringIO(text)):
        if not row or row[0].startswith("#"):
            continue
        if len(row) < 3:
            raise ValueError(f"auth file row needs >=3 columns: {row}")
        secret, name, uid = row[0].strip(), row[1].strip(), row[2].strip()
        groups = [g.strip() for g in row[3].split("|")] if len(row) > 3 and row[3] else []
        yield secret, UserInfo(name=name, uid=uid, groups=groups)


def _parse_csv(text: str) -> Dict[str, UserInfo]:
    """token -> UserInfo (tokens are unique per identity)."""
    return dict(_parse_rows(text))


class TokenAuthenticator:
    """Authorization: Bearer <token> against a token table."""

    def __init__(self, tokens: Dict[str, UserInfo]):
        self.tokens = tokens

    @classmethod
    def from_csv(cls, text: str) -> "TokenAuthenticator":
        return cls(_parse_csv(text))

    def authenticate(self, headers, peer_cert=None) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        token = auth[len("Bearer "):].strip()
        info = self.tokens.get(token)
        if info is None:
            raise AuthenticationError("invalid bearer token")
        return _with_authenticated(info)


class BasicAuthenticator:
    """Authorization: Basic base64(user:password), looked up by username so
    two users may share a password (reference passwordfile keys on username)."""

    def __init__(self, users: Dict[str, tuple]):
        # username -> (password, UserInfo)
        self.users = users

    @classmethod
    def from_csv(cls, text: str) -> "BasicAuthenticator":
        # CSV rows are password,user,uid[,groups] (reference layout)
        by_user: Dict[str, tuple] = {}
        for password, info in _parse_rows(text):
            by_user[info.name] = (password, info)
        return cls(by_user)

    def authenticate(self, headers, peer_cert=None) -> Optional[UserInfo]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(auth[len("Basic "):]).decode()
            username, _, password = decoded.partition(":")
        except Exception:
            raise AuthenticationError("malformed basic auth header") from None
        entry = self.users.get(username)
        if entry is None or entry[0] != password:
            raise AuthenticationError("invalid username/password")
        return _with_authenticated(entry[1])


class AnonymousAuthenticator:
    """Always succeeds with the anonymous identity."""

    def authenticate(self, headers, peer_cert=None) -> Optional[UserInfo]:
        return UserInfo(name=userpkg.ANONYMOUS,
                        groups=[userpkg.ALL_UNAUTHENTICATED])


class UnionAuthenticator:
    """First authenticator that recognizes the request wins; a recognizing
    authenticator that rejects fails the request (reference union.New)."""

    def __init__(self, authenticators: List):
        self.authenticators = authenticators

    def authenticate(self, headers, peer_cert=None) -> Optional[UserInfo]:
        for a in self.authenticators:
            info = a.authenticate(headers, peer_cert=peer_cert)
            if info is not None:
                return info
        raise AuthenticationError("no authenticator recognized the request")


def _with_authenticated(info: UserInfo) -> UserInfo:
    groups = list(info.groups)
    if userpkg.ALL_AUTHENTICATED not in groups:
        groups.append(userpkg.ALL_AUTHENTICATED)
    return UserInfo(name=info.name, uid=info.uid, groups=groups,
                    extra=dict(info.extra))
