"""User identity (reference pkg/auth/user/user.go user.Info)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

# well-known groups (reference pkg/auth/user)
ALL_AUTHENTICATED = "system:authenticated"
ALL_UNAUTHENTICATED = "system:unauthenticated"
ANONYMOUS = "system:anonymous"


@dataclass
class UserInfo:
    name: str = ""
    uid: str = ""
    groups: List[str] = field(default_factory=list)
    extra: Dict[str, List[str]] = field(default_factory=dict)
