"""Authorizers.

Parity target: reference pkg/auth/authorizer (Attributes), pkg/auth/
authorizer/abac (line-delimited JSON policy file), plugin/pkg/auth/authorizer/
rbac (Roles/RoleBindings/ClusterRoles/ClusterRoleBindings resolved per
request), and the union/always-allow/always-deny composition in
cmd/kube-apiserver/app/server.go NewAuthorizerFromAuthorizationConfig.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.auth.user import UserInfo


@dataclass
class AuthzAttributes:
    """Reference authorizer.AttributesRecord."""

    user: Optional[UserInfo] = None
    verb: str = ""            # get/list/watch/create/update/delete
    resource: str = ""        # plural
    subresource: str = ""
    namespace: str = ""
    api_group: str = ""
    name: str = ""
    resource_request: bool = True
    path: str = ""            # for non-resource requests


class Forbidden(Exception):
    """403."""


class AlwaysAllow:
    def authorize(self, attrs: AuthzAttributes) -> bool:
        return True


class AlwaysDeny:
    def authorize(self, attrs: AuthzAttributes) -> bool:
        return False


class UnionAuthorizer:
    """Any authorizer allowing is enough (reference union.New)."""

    def __init__(self, authorizers: List):
        self.authorizers = authorizers

    def authorize(self, attrs: AuthzAttributes) -> bool:
        return any(a.authorize(attrs) for a in self.authorizers)


class ABACAuthorizer:
    """Line-delimited JSON policy file. Accepts both the v0 flat form
    {"user","readonly","resource","namespace"} and the v1beta1 form
    {"kind":"Policy","spec":{...}} (reference pkg/auth/authorizer/abac)."""

    def __init__(self, policies: List[dict]):
        self.policies = policies

    @classmethod
    def from_file_text(cls, text: str) -> "ABACAuthorizer":
        policies = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            d = json.loads(ln)
            policies.append(d.get("spec", d))
        return cls(policies)

    def authorize(self, attrs: AuthzAttributes) -> bool:
        for p in self.policies:
            if self._matches(p, attrs):
                return True
        return False

    @staticmethod
    def _matches(p: dict, attrs: AuthzAttributes) -> bool:
        user = attrs.user or UserInfo()
        pu, pg = p.get("user", ""), p.get("group", "")
        if pu and pu != "*" and pu != user.name:
            return False
        if pg and pg != "*" and pg not in user.groups:
            return False
        if not pu and not pg:
            return False
        if p.get("readonly") and attrs.verb not in ("get", "list", "watch"):
            return False
        if attrs.resource_request:
            pr = p.get("resource", "")
            if pr and pr != "*" and pr != attrs.resource:
                return False
            pn = p.get("namespace", "")
            if pn and pn != "*" and pn != attrs.namespace:
                return False
            pag = p.get("apiGroup", "")
            if pag and pag != "*" and pag != attrs.api_group:
                return False
        else:
            path = p.get("nonResourcePath", "")
            if path and path != "*":
                if path.endswith("*"):
                    if not attrs.path.startswith(path[:-1]):
                        return False
                elif path != attrs.path:
                    return False
        return True


class RBACAuthorizer:
    """Resolves the requesting user's roles from RoleBindings in the request
    namespace plus ClusterRoleBindings, then matches PolicyRules (reference
    plugin/pkg/auth/authorizer/rbac/rbac.go authorizingVisitor)."""

    def __init__(self, registry):
        self.registry = registry

    def authorize(self, attrs: AuthzAttributes) -> bool:
        user = attrs.user or UserInfo()
        for rules in self._rules_for(user, attrs.namespace):
            for rule in rules:
                if self._rule_allows(rule, attrs):
                    return True
        return False

    def _rules_for(self, user: UserInfo, namespace: str):
        from kubernetes_tpu.apis import rbac as rbacapi
        from kubernetes_tpu.registry.generic import RegistryError

        def subject_matches(s):
            if s.kind == rbacapi.USER_KIND:
                return s.name in ("*", user.name)
            if s.kind == rbacapi.GROUP_KIND:
                return s.name in user.groups
            if s.kind == rbacapi.SERVICE_ACCOUNT_KIND:
                return user.name == f"system:serviceaccount:{s.namespace}:{s.name}"
            return False

        bindings = []
        try:
            items, _ = self.registry.list("clusterrolebindings")
            bindings += [(b, "") for b in items]
        except RegistryError:
            pass
        if namespace:
            try:
                items, _ = self.registry.list("rolebindings", namespace)
                bindings += [(b, namespace) for b in items]
            except RegistryError:
                pass
        for b, ns in bindings:
            if not any(subject_matches(s) for s in (b.subjects or [])):
                continue
            ref = b.role_ref
            if ref is None:
                continue
            try:
                if ref.kind == "ClusterRole" or not ns:
                    role = self.registry.get("clusterroles", ref.name)
                else:
                    role = self.registry.get("roles", ref.name, ns)
            except RegistryError:
                continue
            yield role.rules or []

    @staticmethod
    def _rule_allows(rule, attrs: AuthzAttributes) -> bool:
        def has(values, want):
            vals = values or []
            return "*" in vals or want in vals
        if not attrs.resource_request:
            return has(rule.non_resource_urls, attrs.path) and has(rule.verbs, attrs.verb)
        if not has(rule.verbs, attrs.verb):
            return False
        if not has(rule.resources, attrs.resource):
            return False
        groups = rule.api_groups if rule.api_groups is not None else [""]
        if "*" not in groups and attrs.api_group not in groups:
            return False
        if rule.resource_names:
            return attrs.name in rule.resource_names
        return True
