"""x509 client-certificate authenticator.

Parity target: reference plugin/pkg/auth/authenticator/request/x509 — the
TLS layer (SSLContext with the client CA loaded, CERT_OPTIONAL) verifies
the chain; this authenticator maps the ALREADY-VERIFIED peer certificate's
subject to an identity: CN -> user name, O -> groups
(x509.CommonNameUserConversion).
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.auth.user import UserInfo


class X509Authenticator:
    """Identity from the TLS peer certificate (ssl.getpeercert() dict)."""

    def authenticate(self, headers, peer_cert=None) -> Optional[UserInfo]:
        if not peer_cert:
            return None  # no client cert presented: fall through the chain
        name = ""
        groups = []
        for rdn in peer_cert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName":
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        if not name:
            return None
        return UserInfo(name=name, uid="", groups=groups)
