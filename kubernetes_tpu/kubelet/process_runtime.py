"""ProcessRuntime: a pod runtime that runs REAL OS processes.

Parity target: reference pkg/kubelet/dockertools/docker_manager.go — the
runtime that makes "scheduled" mean something physical. There is no
container engine in this environment, so the tpu-native analog runs one
host subprocess per container:

  - a container with spec.command/args runs exactly that argv;
  - a command-less container (the benchmark's "pause" image) runs the
    pause-equivalent: a sleep loop, the moral heir of build/pause/pause.c;
  - stdout+stderr stream to a per-container log file under the runtime
    root, which is what /containerLogs and `kubectl logs` serve
    (docker_manager.go GetContainerLogs); the previous incarnation's log
    survives one restart as `.prev` (kubectl logs --previous);
  - `exec` runs an argv with the container's environment and working
    directory and captures its output (docker exec analog,
    pkg/kubelet/server/server.go:237-298 serves it);
  - PLEG observes real exits: container_states() polls the child
    processes, so a killed process produces CONTAINER_DIED and the
    kubelet's restart policy applies to a real PID.

Isolation is process-level only (no namespaces/cgroups — this is a
scheduling-framework runtime, not a container engine). The FakeRuntime
remains the hollow-node default; ProcessRuntime is selected per-kubelet
(--runtime process).
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.kubelet.runtime import PodRuntime, RunningPod

# the pause-equivalent (build/pause/pause.c: pause forever, cheaply)
PAUSE_ARGV = ["/bin/sh", "-c", "while :; do sleep 3600; done"]


class _Proc:
    """One container's live process + its log handle."""

    def __init__(self, popen: subprocess.Popen, log_path: str, log_file):
        self.popen = popen
        self.log_path = log_path
        self.log_file = log_file


class ProcessRuntime(PodRuntime):
    """Subprocess-per-container runtime. Thread-safe; all state keyed by
    `ns/name` pod keys like the rest of the kubelet."""

    fakes_network = False

    def __init__(self, root_dir: Optional[str] = None,
                 grace_seconds: float = 2.0):
        self.root = root_dir or os.path.join(
            "/tmp", f"kubernetes-tpu-pods-{os.getpid()}")
        os.makedirs(self.root, exist_ok=True)
        self.grace_seconds = grace_seconds
        # kubelet-side volume pipeline (kubernetes_tpu/volume): emptyDir/
        # hostPath/PVC/cloud sources materialize under the sandbox and are
        # exposed to processes via $KTPU_MOUNTS (volume_manager.go analog);
        # the kubelet injects the API resolver for PVC->PV lookups
        from kubernetes_tpu.volume import VolumeManager
        self.volumes = VolumeManager(self.root)
        self._lock = threading.Lock()
        self._pods: Dict[str, RunningPod] = {}
        self._procs: Dict[str, Dict[str, _Proc]] = {}  # key -> cname -> proc

    # --- helpers --------------------------------------------------------------

    @staticmethod
    def _argv(c: api.Container) -> List[str]:
        if c.command:
            return list(c.command) + list(c.args or [])
        if c.args:
            # image entrypoints don't exist here; args alone run via sh
            return ["/bin/sh", "-c", " ".join(c.args)]
        return PAUSE_ARGV

    def _pod_dir(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "_"))

    def _env(self, pod: api.Pod, c: api.Container) -> Dict[str, str]:
        env = dict(os.environ)
        env["POD_NAME"] = pod.metadata.name
        env["POD_NAMESPACE"] = pod.metadata.namespace or "default"
        env["CONTAINER_NAME"] = c.name
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        # the container's volume view: $KTPU_MOUNTS/<mountPath with / -> _>
        # is a symlink to the materialized volume (see kubernetes_tpu/volume)
        env["KTPU_MOUNTS"] = os.path.join(self._pod_dir(key), "mounts",
                                          c.name)
        for e in c.env or []:
            if e.name:
                env[e.name] = e.value or ""
        return env

    def _spawn(self, key: str, pod: api.Pod, c: api.Container) -> _Proc:
        pod_dir = self._pod_dir(key)
        os.makedirs(pod_dir, exist_ok=True)
        log_path = os.path.join(pod_dir, f"{c.name}.log")
        if os.path.exists(log_path):
            # one previous incarnation's log survives (kubectl logs -p)
            shutil.move(log_path, log_path + ".prev")
        log_file = open(log_path, "ab", buffering=0)
        popen = subprocess.Popen(
            self._argv(c), cwd=pod_dir, env=self._env(pod, c),
            stdout=log_file, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True)
        return _Proc(popen, log_path, log_file)

    @staticmethod
    def _terminate(proc: _Proc, grace: float) -> None:
        p = proc.popen
        if p.poll() is None:
            try:
                # the whole session: sh -c children must die with the shell
                os.killpg(p.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            deadline = time.monotonic() + grace
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait(timeout=5)
        try:
            proc.log_file.close()
        except OSError:
            pass

    # --- PodRuntime -----------------------------------------------------------

    def sync_pod(self, pod: api.Pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            if key in self._pods:
                return
        # volumes materialize BEFORE any container starts
        # (volume_manager.go: WaitForAttachAndMount precedes SyncPod) —
        # and OUTSIDE the runtime lock: PVC resolution does HTTP, and a
        # slow apiserver must not stall PLEG/heartbeat/exec behind it
        self.volumes.setup_pod(pod)
        with self._lock:
            if key in self._pods:
                return  # a concurrent sync won; its volumes == ours
            procs: Dict[str, _Proc] = {}
            try:
                for c in pod.spec.containers or []:
                    procs[c.name] = self._spawn(key, pod, c)
            except OSError:
                # a later container's argv failed to spawn: reap the
                # already-started siblings — nothing may outlive an
                # unregistered pod (kill_pod couldn't find it) — and put
                # the materialized volumes back too
                for proc in procs.values():
                    self._terminate(proc, 0.5)
                self.volumes.teardown_pod(key)
                raise
            self._procs[key] = procs
            self._pods[key] = RunningPod(
                pod=pod,
                container_ids=[f"proc://{procs[c.name].popen.pid}"
                               for c in (pod.spec.containers or [])])

    def kill_pod(self, pod_key: str) -> None:
        with self._lock:
            procs = self._procs.pop(pod_key, {})
            self._pods.pop(pod_key, None)
        for proc in procs.values():
            self._terminate(proc, self.grace_seconds)
        self.volumes.teardown_pod(pod_key)

    def running(self) -> Dict[str, RunningPod]:
        with self._lock:
            return dict(self._pods)

    def container_states(self, pod_key: str) -> Dict[str, str]:
        """Real observation: poll each child PID (the PLEG relist source)."""
        with self._lock:
            procs = self._procs.get(pod_key)
            if procs is None:
                return {}
            return {cname: ("running" if proc.popen.poll() is None
                            else "dead")
                    for cname, proc in procs.items()}

    def exit_code(self, pod_key: str, cname: str) -> Optional[int]:
        with self._lock:
            proc = self._procs.get(pod_key, {}).get(cname)
        if proc is None:
            return None
        rc = proc.popen.poll()
        # negative = killed by signal: report 128+N like a shell would
        return (128 - rc) if rc is not None and rc < 0 else rc

    def kill_container(self, pod_key: str, cname: str) -> None:
        with self._lock:
            proc = self._procs.get(pod_key, {}).get(cname)
        if proc is not None:
            self._terminate(proc, self.grace_seconds)

    def restart_container(self, pod_key: str, cname: str) -> None:
        with self._lock:
            rp = self._pods.get(pod_key)
            procs = self._procs.get(pod_key)
            if rp is None or procs is None:
                return
            old = procs.get(cname)
        if old is not None:
            self._terminate(old, self.grace_seconds)
        with self._lock:
            rp = self._pods.get(pod_key)
            procs = self._procs.get(pod_key)
            if rp is None or procs is None:  # pod killed meanwhile
                return
            spec = next((c for c in (rp.pod.spec.containers or [])
                         if c.name == cname), None)
            if spec is None:
                return
            procs[cname] = self._spawn(pod_key, rp.pod, spec)
            rp.restart_counts[cname] = rp.restart_counts.get(cname, 0) + 1
            for i, c in enumerate(rp.pod.spec.containers or []):
                if c.name == cname:
                    rp.container_ids[i] = \
                        f"proc://{procs[cname].popen.pid}"

    # --- logs / exec (what the kubelet server serves) -------------------------

    def logs(self, pod_key: str, cname: str, tail_lines: Optional[int] = None,
             previous: bool = False) -> str:
        with self._lock:
            proc = self._procs.get(pod_key, {}).get(cname)
        if proc is None:
            raise KeyError(f"no container {cname!r} in pod {pod_key!r}")
        path = proc.log_path + (".prev" if previous else "")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return ""
        text = data.decode("utf-8", "replace")
        if tail_lines is not None and tail_lines >= 0:
            lines = text.splitlines(keepends=True)
            text = "".join(lines[-tail_lines:]) if tail_lines else ""
        return text

    def exec(self, pod_key: str, cname: str, command: List[str],
             timeout: float = 30.0):
        """(rc, combined output) of an argv run in the container's context
        (cwd + env) — the docker-exec analog."""
        with self._lock:
            rp = self._pods.get(pod_key)
            proc = self._procs.get(pod_key, {}).get(cname)
        if rp is None or proc is None:
            raise KeyError(f"no container {cname!r} in pod {pod_key!r}")
        spec = next((c for c in (rp.pod.spec.containers or [])
                     if c.name == cname), None)
        try:
            res = subprocess.run(
                list(command), cwd=self._pod_dir(pod_key),
                env=self._env(rp.pod, spec) if spec else None,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, timeout=timeout)
            return res.returncode, res.stdout.decode("utf-8", "replace")
        except subprocess.TimeoutExpired:
            return 124, f"command timed out after {timeout}s\n"
        except FileNotFoundError as e:
            return 127, f"{e}\n"

    def exec_probe(self, pod_key: str, cname: str, command) -> int:
        try:
            rc, _ = self.exec(pod_key, cname, list(command or ["true"]),
                              timeout=5.0)
            return rc
        except KeyError:
            return 1

    def cleanup(self) -> None:
        """Kill everything and remove the runtime root (tests/teardown)."""
        with self._lock:
            keys = list(self._procs)
        for k in keys:
            self.kill_pod(k)
        shutil.rmtree(self.root, ignore_errors=True)
