"""PLEG: Pod Lifecycle Event Generator (reference pkg/kubelet/pleg/generic.go).

Periodically relists the runtime's container states and diffs them against
the previous relist: a container observed running->dead yields a
ContainerDied event (generic.go:180's computeEvent). The kubelet consumes
the events to drive restart policy instead of rescanning every pod every
tick — the reference's reason for PLEG's existence at 100+ pods/node."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

CONTAINER_DIED = "ContainerDied"
CONTAINER_STARTED = "ContainerStarted"
POD_GONE = "PodGone"


@dataclass(frozen=True)
class PodLifecycleEvent:
    pod_key: str
    type: str
    container: str = ""


class PLEG:
    def __init__(self, runtime):
        self.runtime = runtime
        self._last: Dict[str, Dict[str, str]] = {}

    def relist(self) -> List[PodLifecycleEvent]:
        events: List[PodLifecycleEvent] = []
        current: Dict[str, Dict[str, str]] = {}
        for key in self.runtime.running():
            current[key] = self.runtime.container_states(key)
        for key, states in current.items():
            old = self._last.get(key, {})
            for cname, state in states.items():
                was = old.get(cname, "")
                if state == "dead" and was != "dead":
                    events.append(PodLifecycleEvent(key, CONTAINER_DIED,
                                                    cname))
                elif state == "running" and was == "dead":
                    events.append(PodLifecycleEvent(key, CONTAINER_STARTED,
                                                    cname))
        for key in self._last:
            if key not in current:
                events.append(PodLifecycleEvent(key, POD_GONE))
        self._last = current
        return events
