"""Liveness/readiness probing (reference pkg/kubelet/prober + pkg/probe).

Handlers:
- exec: delegated to the runtime (FakeRuntime consults its per-container
  exec-result table — the hollow analogue of running the command);
- httpGet: a real HTTP GET (2xx/3xx = healthy), like pkg/probe/http;
- tcpSocket: a real connect attempt, like pkg/probe/tcp.

The ProbeManager steps every worker from the kubelet's sync tick (one
thread for all probes — thread-per-worker doesn't scale to hollow fleets),
honoring each probe's initialDelay/period/thresholds. Readiness results
feed the POD_READY condition; a liveness failure past failureThreshold
kills the container, and the PLEG relist then restarts it per
restartPolicy with the restart count incremented
(pkg/kubelet/prober/worker.go semantics).
"""

from __future__ import annotations

import http.client
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api import types as api


def _timeout(probe: api.Probe) -> float:
    # 0 would put the socket in non-blocking mode (instant BlockingIOError,
    # permanent probe failure); the reference validates timeoutSeconds >= 1,
    # so floor at 1 rather than honor a meaningless 0
    t = probe.timeout_seconds
    return 1 if t is None or t <= 0 else t


def run_probe(probe: api.Probe, pod: api.Pod, container: api.Container,
              runtime) -> bool:
    """One probe attempt -> healthy?"""
    key = f"{pod.metadata.namespace}/{pod.metadata.name}"
    if probe.exec and probe.exec.command is not None:
        return runtime.exec_probe(key, container.name,
                                  probe.exec.command) == 0
    # Network probes: a hollow runtime fabricates pod IPs, so real connects
    # would block their full timeout against unroutable addresses and stall
    # the shared sync tick. Such runtimes advertise fakes_network and answer
    # from the same health table as exec probes; real I/O only happens when
    # the probe names an explicit host (httpGet.host).
    if probe.http_get is not None:
        g = probe.http_get
        if not g.host and getattr(runtime, "fakes_network", False):
            return runtime.network_probe(key, container.name)
        host = g.host or (pod.status.pod_ip if pod.status else "") \
            or "127.0.0.1"
        try:
            conn = http.client.HTTPConnection(
                host, int(g.port or 80), timeout=_timeout(probe))
            conn.request("GET", g.path or "/")
            code = conn.getresponse().status
            conn.close()
            return 200 <= code < 400
        except (OSError, http.client.HTTPException, ValueError):
            # HTTPException: non-HTTP bytes on the port (BadStatusLine);
            # ValueError: unresolvable named port — all mean "unhealthy",
            # never "abort the kubelet's whole sync tick"
            return False
    if probe.tcp_socket is not None:
        if getattr(runtime, "fakes_network", False):
            return runtime.network_probe(key, container.name)
        host = (pod.status.pod_ip if pod.status else "") or "127.0.0.1"
        try:
            with socket.create_connection(
                    (host, int(probe.tcp_socket.port or 0)),
                    timeout=_timeout(probe)):
                return True
        except (OSError, ValueError):
            return False
    return True  # no handler = always healthy (reference: nil probe)


@dataclass
class _Worker:
    probe: api.Probe
    kind: str                   # "liveness" | "readiness"
    started: float = field(default_factory=time.monotonic)
    next_due: float = 0.0
    successes: int = 0
    failures: int = 0
    # readiness starts False until the first success; liveness starts ok
    result: Optional[bool] = None

    def healthy(self, default: bool) -> bool:
        if self.result is None:
            return default
        return self.result


class ProbeManager:
    """Per-(pod, container, kind) probe workers, stepped from one loop."""

    def __init__(self, runtime, clock=time.monotonic):
        self.runtime = runtime
        self._clock = clock
        self._workers: Dict[Tuple[str, str, str], _Worker] = {}

    def _worker(self, key, cname, kind, probe) -> _Worker:
        wk = self._workers.get((key, cname, kind))
        if wk is None:
            wk = _Worker(probe=probe, kind=kind, started=self._clock())
            delay = (0 if probe.initial_delay_seconds is None
                     else probe.initial_delay_seconds)
            wk.next_due = wk.started + delay
            self._workers[(key, cname, kind)] = wk
        return wk

    def forget_pod(self, key: str):
        for wkey in [w for w in self._workers if w[0] == key]:
            del self._workers[wkey]

    def forget_container(self, key: str, cname: str):
        """Container restarted: probe state starts over (initialDelay)."""
        for wkey in [w for w in self._workers
                     if w[0] == key and w[1] == cname]:
            del self._workers[wkey]

    def step(self, pod: api.Pod) -> Tuple[bool, list]:
        """Run due probes for one running pod.

        Returns (all_containers_ready, [containers to kill for liveness])."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        now = self._clock()
        ready = True
        kill = []
        for c in (pod.spec.containers or []) if pod.spec else []:
            for kind, probe in (("liveness", c.liveness_probe),
                                ("readiness", c.readiness_probe)):
                if probe is None:
                    continue
                wk = self._worker(key, c.name, kind, probe)
                if now >= wk.next_due:
                    ok = run_probe(probe, pod, c, self.runtime)
                    # explicit 0s are honored (period 0 = probe every step);
                    # the api.Probe dataclass already supplies the reference
                    # defaults for absent fields
                    wk.next_due = now + (10 if probe.period_seconds is None
                                         else probe.period_seconds)
                    if ok:
                        wk.successes += 1
                        wk.failures = 0
                        if wk.successes >= (1 if probe.success_threshold is None
                                            else probe.success_threshold):
                            wk.result = True
                    else:
                        wk.failures += 1
                        wk.successes = 0
                        if wk.failures >= (3 if probe.failure_threshold is None
                                           else probe.failure_threshold):
                            wk.result = False
                if kind == "readiness":
                    # unready until the first success (prober/worker.go)
                    ready = ready and wk.healthy(default=False)
                elif not wk.healthy(default=True):
                    kill.append(c.name)
        return ready, kill
