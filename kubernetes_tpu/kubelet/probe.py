"""Liveness/readiness probing (reference pkg/kubelet/prober + pkg/probe).

Handlers:
- exec: delegated to the runtime (FakeRuntime consults its per-container
  exec-result table — the hollow analogue of running the command);
- httpGet: a real HTTP GET (2xx/3xx = healthy), like pkg/probe/http;
- tcpSocket: a real connect attempt, like pkg/probe/tcp.

The ProbeManager steps every worker from the kubelet's sync tick (one
thread for all probes — thread-per-worker doesn't scale to hollow fleets),
honoring each probe's initialDelay/period/thresholds. Readiness results
feed the POD_READY condition; a liveness failure past failureThreshold
kills the container, and the PLEG relist then restarts it per
restartPolicy with the restart count incremented
(pkg/kubelet/prober/worker.go semantics).
"""

from __future__ import annotations

import http.client
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api import types as api


def run_probe(probe: api.Probe, pod: api.Pod, container: api.Container,
              runtime) -> bool:
    """One probe attempt -> healthy?"""
    key = f"{pod.metadata.namespace}/{pod.metadata.name}"
    if probe.exec and probe.exec.command is not None:
        return runtime.exec_probe(key, container.name,
                                  probe.exec.command) == 0
    if probe.http_get is not None:
        g = probe.http_get
        host = g.host or (pod.status.pod_ip if pod.status else "") \
            or "127.0.0.1"
        try:
            conn = http.client.HTTPConnection(
                host, int(g.port or 80), timeout=probe.timeout_seconds or 1)
            conn.request("GET", g.path or "/")
            code = conn.getresponse().status
            conn.close()
            return 200 <= code < 400
        except OSError:
            return False
    if probe.tcp_socket is not None:
        host = (pod.status.pod_ip if pod.status else "") or "127.0.0.1"
        try:
            with socket.create_connection(
                    (host, int(probe.tcp_socket.port or 0)),
                    timeout=probe.timeout_seconds or 1):
                return True
        except OSError:
            return False
    return True  # no handler = always healthy (reference: nil probe)


@dataclass
class _Worker:
    probe: api.Probe
    kind: str                   # "liveness" | "readiness"
    started: float = field(default_factory=time.monotonic)
    next_due: float = 0.0
    successes: int = 0
    failures: int = 0
    # readiness starts False until the first success; liveness starts ok
    result: Optional[bool] = None

    def healthy(self, default: bool) -> bool:
        if self.result is None:
            return default
        return self.result


class ProbeManager:
    """Per-(pod, container, kind) probe workers, stepped from one loop."""

    def __init__(self, runtime, clock=time.monotonic):
        self.runtime = runtime
        self._clock = clock
        self._workers: Dict[Tuple[str, str, str], _Worker] = {}

    def _worker(self, key, cname, kind, probe) -> _Worker:
        wk = self._workers.get((key, cname, kind))
        if wk is None:
            wk = _Worker(probe=probe, kind=kind, started=self._clock())
            wk.next_due = wk.started + (probe.initial_delay_seconds or 0)
            self._workers[(key, cname, kind)] = wk
        return wk

    def forget_pod(self, key: str):
        for wkey in [w for w in self._workers if w[0] == key]:
            del self._workers[wkey]

    def forget_container(self, key: str, cname: str):
        """Container restarted: probe state starts over (initialDelay)."""
        for wkey in [w for w in self._workers
                     if w[0] == key and w[1] == cname]:
            del self._workers[wkey]

    def step(self, pod: api.Pod) -> Tuple[bool, list]:
        """Run due probes for one running pod.

        Returns (all_containers_ready, [containers to kill for liveness])."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        now = self._clock()
        ready = True
        kill = []
        for c in (pod.spec.containers or []) if pod.spec else []:
            for kind, probe in (("liveness", c.liveness_probe),
                                ("readiness", c.readiness_probe)):
                if probe is None:
                    continue
                wk = self._worker(key, c.name, kind, probe)
                if now >= wk.next_due:
                    ok = run_probe(probe, pod, c, self.runtime)
                    wk.next_due = now + (probe.period_seconds or 10)
                    if ok:
                        wk.successes += 1
                        wk.failures = 0
                        if wk.successes >= (probe.success_threshold or 1):
                            wk.result = True
                    else:
                        wk.failures += 1
                        wk.successes = 0
                        if wk.failures >= (probe.failure_threshold or 3):
                            wk.result = False
                if kind == "readiness":
                    # unready until the first success (prober/worker.go)
                    ready = ready and wk.healthy(default=False)
                elif not wk.healthy(default=True):
                    kill.append(c.name)
        return ready, kill
