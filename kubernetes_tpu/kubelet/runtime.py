"""Container runtime interface + the hollow (fake) implementation.

Parity target: reference pkg/kubelet/container (Runtime iface) and
pkg/kubelet/dockertools/fake_docker_client.go — the fake used by kubemark
hollow nodes: containers "start" instantly (optionally with a simulated
latency) and report Running until the pod is removed."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils.timeutil import now_iso


@dataclass
class RunningPod:
    pod: api.Pod
    started_at: str = field(default_factory=now_iso)
    container_ids: List[str] = field(default_factory=list)
    dead: set = field(default_factory=set)            # container names down
    restart_counts: Dict[str, int] = field(default_factory=dict)


class PodRuntime:
    """What the kubelet needs from a runtime: run, kill, observe — plus
    the container-level hooks PLEG and the probers drive. The container
    hooks have safe defaults so a minimal custom runtime keeps working
    (no PLEG events, probes observe healthy)."""

    def sync_pod(self, pod: api.Pod) -> None:
        raise NotImplementedError

    def kill_pod(self, pod_key: str) -> None:
        raise NotImplementedError

    def running(self) -> Dict[str, RunningPod]:
        raise NotImplementedError

    # --- container-level (PLEG + probes); override to participate ------------

    def container_states(self, pod_key: str) -> Dict[str, str]:
        return {}          # no per-container observability -> no PLEG events

    def exit_code(self, pod_key: str, cname: str) -> Optional[int]:
        """Exit code of a dead container; None = unknown (hollow runtimes
        kill containers without a code — treated as failure by the restart
        policy, which matches 'it crashed')."""
        return None

    def kill_container(self, pod_key: str, cname: str) -> None:
        pass

    def restart_container(self, pod_key: str, cname: str) -> None:
        pass

    def exec_probe(self, pod_key: str, cname: str, command) -> int:
        return 0           # exec probes observe healthy by default

    # A runtime whose pod IPs are fabricated (hollow nodes) sets this so
    # httpGet/tcpSocket probes are answered from network_probe instead of
    # blocking real connects against unroutable addresses.
    fakes_network = False

    def network_probe(self, pod_key: str, cname: str) -> bool:
        return True


class FakeRuntime(PodRuntime):
    """Instant-start runtime (EnableSleep mimics the fake docker client's
    latency knob, hollow-node.go:118)."""

    def __init__(self, start_latency: float = 0.0):
        self._lock = threading.Lock()
        self._pods: Dict[str, RunningPod] = {}
        self._exec_results: Dict[str, Dict[str, int]] = {}
        self.start_latency = start_latency
        self._counter = 0

    def sync_pod(self, pod: api.Pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            if key in self._pods:
                return
        if self.start_latency:
            time.sleep(self.start_latency)
        with self._lock:
            self._counter += 1
            self._pods[key] = RunningPod(
                pod=pod,
                container_ids=[f"fake://{self._counter:08x}-{c.name}"
                               for c in (pod.spec.containers or [])])

    def kill_pod(self, pod_key: str) -> None:
        with self._lock:
            self._pods.pop(pod_key, None)
            self._exec_results.pop(pod_key, None)

    def running(self) -> Dict[str, RunningPod]:
        with self._lock:
            return dict(self._pods)

    # --- container-level lifecycle (PLEG + probes drive these) ---------------

    def kill_container(self, pod_key: str, cname: str) -> None:
        """A container dies (crash / liveness kill); the pod object stays."""
        with self._lock:
            rp = self._pods.get(pod_key)
            if rp is not None:
                rp.dead.add(cname)

    def restart_container(self, pod_key: str, cname: str) -> None:
        with self._lock:
            rp = self._pods.get(pod_key)
            if rp is None:
                return
            rp.dead.discard(cname)
            rp.restart_counts[cname] = rp.restart_counts.get(cname, 0) + 1
            self._counter += 1
            for i, c in enumerate(rp.pod.spec.containers or []):
                if c.name == cname and i < len(rp.container_ids):
                    rp.container_ids[i] = f"fake://{self._counter:08x}-{cname}"

    def container_states(self, pod_key: str) -> Dict[str, str]:
        """name -> "running" | "dead" (the PLEG relist source)."""
        with self._lock:
            rp = self._pods.get(pod_key)
            if rp is None:
                return {}
            return {c.name: ("dead" if c.name in rp.dead else "running")
                    for c in (rp.pod.spec.containers or [])}

    # --- exec probes ----------------------------------------------------------

    def set_exec_result(self, pod_key: str, cname: str, rc: int) -> None:
        """Test/chaos hook: what `exec` probes observe for this container."""
        with self._lock:
            self._exec_results.setdefault(pod_key, {})[cname] = rc

    def exec_probe(self, pod_key: str, cname: str, command) -> int:
        with self._lock:
            rp = self._pods.get(pod_key)
            if rp is None or cname in rp.dead:
                return 1
            return self._exec_results.get(pod_key, {}).get(cname, 0)

    # hollow network: http/tcp probes observe the same health table
    fakes_network = True

    def network_probe(self, pod_key: str, cname: str) -> bool:
        return self.exec_probe(pod_key, cname, None) == 0


class FakeCadvisor:
    """Machine info provider (reference pkg/kubelet/cadvisor/testing fake).
    `memory_pressure` is the settable stats signal the eviction manager
    watches (the hollow analogue of memory.available crossing the hard
    eviction threshold)."""

    def __init__(self, cpu: str = "4", memory: str = "32Gi", pods: str = "110"):
        self.cpu = cpu
        self.memory = memory
        self.pods = pods
        self.memory_pressure = False

    def machine_resources(self) -> Dict[str, str]:
        return {"cpu": self.cpu, "memory": self.memory, "pods": self.pods}

    def under_memory_pressure(self) -> bool:
        return self.memory_pressure
