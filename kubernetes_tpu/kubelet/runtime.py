"""Container runtime interface + the hollow (fake) implementation.

Parity target: reference pkg/kubelet/container (Runtime iface) and
pkg/kubelet/dockertools/fake_docker_client.go — the fake used by kubemark
hollow nodes: containers "start" instantly (optionally with a simulated
latency) and report Running until the pod is removed."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.utils.timeutil import now_iso


@dataclass
class RunningPod:
    pod: api.Pod
    started_at: str = field(default_factory=now_iso)
    container_ids: List[str] = field(default_factory=list)


class PodRuntime:
    """What the kubelet needs from a runtime: run, kill, observe."""

    def sync_pod(self, pod: api.Pod) -> None:
        raise NotImplementedError

    def kill_pod(self, pod_key: str) -> None:
        raise NotImplementedError

    def running(self) -> Dict[str, RunningPod]:
        raise NotImplementedError


class FakeRuntime(PodRuntime):
    """Instant-start runtime (EnableSleep mimics the fake docker client's
    latency knob, hollow-node.go:118)."""

    def __init__(self, start_latency: float = 0.0):
        self._lock = threading.Lock()
        self._pods: Dict[str, RunningPod] = {}
        self.start_latency = start_latency
        self._counter = 0

    def sync_pod(self, pod: api.Pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            if key in self._pods:
                return
        if self.start_latency:
            time.sleep(self.start_latency)
        with self._lock:
            self._counter += 1
            self._pods[key] = RunningPod(
                pod=pod,
                container_ids=[f"fake://{self._counter:08x}-{c.name}"
                               for c in (pod.spec.containers or [])])

    def kill_pod(self, pod_key: str) -> None:
        with self._lock:
            self._pods.pop(pod_key, None)

    def running(self) -> Dict[str, RunningPod]:
        with self._lock:
            return dict(self._pods)


class FakeCadvisor:
    """Machine info provider (reference pkg/kubelet/cadvisor/testing fake)."""

    def __init__(self, cpu: str = "4", memory: str = "32Gi", pods: str = "110"):
        self.cpu = cpu
        self.memory = memory
        self.pods = pods

    def machine_resources(self) -> Dict[str, str]:
        return {"cpu": self.cpu, "memory": self.memory, "pods": self.pods}
