"""kubelet entrypoint: python -m kubernetes_tpu.kubelet

Flags bind to KubeletConfiguration, served at /configz next to /healthz and
/metrics (the reference kubelet's :10250 server, pkg/kubelet/server/
server.go:237-270). The runtime is the in-process FakeRuntime (hollow-node
semantics, cmd/kubemark/hollow-node.go:103-138) — there is no container
engine in this environment, so every node is a hollow node."""

from __future__ import annotations

import argparse
import signal
import socket
import sys
import threading

from kubernetes_tpu.apis.componentconfig import KubeletConfiguration
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.kubelet.runtime import FakeCadvisor, FakeRuntime
from kubernetes_tpu.kubelet.server import KubeletServer
from kubernetes_tpu.utils.debugserver import client_from_url


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubelet")
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--node-name", default=socket.gethostname())
    p.add_argument("--port", type=int, default=10250)
    p.add_argument("--max-pods", type=int, default=110)
    p.add_argument("--cpu", default="4")
    p.add_argument("--memory", default="32Gi")
    p.add_argument("--node-status-update-frequency", type=float, default=10.0)
    p.add_argument("--runtime", choices=("fake", "process"), default="fake",
                   help="fake = hollow node; process = real OS subprocesses "
                        "with logs/exec served on the node port")
    p.add_argument("--root-dir", default="",
                   help="pod sandbox/log root for --runtime process")
    a = p.parse_args(argv)
    cfg = KubeletConfiguration(
        port=a.port, max_pods=a.max_pods,
        node_status_update_frequency_seconds=a.node_status_update_frequency)

    client = client_from_url(a.master, qps=100, burst=200)
    runtime = (ProcessRuntime(root_dir=a.root_dir or None)
               if a.runtime == "process" else FakeRuntime())
    kl = Kubelet(client, a.node_name, runtime=runtime,
                 cadvisor=FakeCadvisor(cpu=a.cpu, memory=a.memory,
                                       pods=str(a.max_pods)),
                 heartbeat_period=a.node_status_update_frequency)
    # the node API server (server.go:237): logs/exec/pods + debug bundle,
    # started first so registration publishes the bound port
    server = KubeletServer(runtime, port=cfg.port,
                           configz={"componentconfig": cfg}).start()
    kl.server_port = server.port
    kl.start()
    print(f"kubelet {a.node_name} debug on http://127.0.0.1:{server.port}",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a_: stop.set())
    signal.signal(signal.SIGINT, lambda *a_: stop.set())
    stop.wait()
    kl.stop()
    server.stop()
    if isinstance(runtime, ProcessRuntime):
        runtime.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
