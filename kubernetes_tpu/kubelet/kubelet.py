"""The kubelet: register node, heartbeat, sync assigned pods.

Parity target: reference pkg/kubelet/kubelet.go — Run(:973) registers the
node and starts the loops; syncLoopIteration (:2619) merges pod-source
updates with periodic resyncs; syncPod (:1796) admits (GeneralPredicates,
the node-side re-check), starts containers via the runtime, and the status
manager (pkg/kubelet/status) pushes PodStatus. The PLEG relist
(pleg/generic.go:180) is the periodic runtime-vs-desired diff in _resync.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from kubernetes_tpu.api import fields as fieldsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy, scheme
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.kubelet.eviction import EVICTED_REASON, EvictionManager
from kubernetes_tpu.kubelet.pleg import CONTAINER_DIED, PLEG
from kubernetes_tpu.kubelet.probe import ProbeManager
from kubernetes_tpu.kubelet.runtime import FakeCadvisor, FakeRuntime, PodRuntime
from kubernetes_tpu.scheduler.cache import NodeInfo
from kubernetes_tpu.scheduler.predicates import PredicateFailure, general_predicates
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import now_iso, parse_iso
from kubernetes_tpu.utils.trace import Span, use_span

log = logging.getLogger("kubelet")


class Kubelet:
    def __init__(self, client: RESTClient, node_name: str,
                 runtime: Optional[PodRuntime] = None,
                 cadvisor: Optional[FakeCadvisor] = None,
                 heartbeat_period: float = 10.0,
                 sync_period: float = 1.0,
                 eviction_period: float = 2.0,
                 node_labels: Optional[Dict[str, str]] = None,
                 pod_ip_base: str = "10.0"):
        self.client = client
        self.node_name = node_name
        self.runtime = runtime or FakeRuntime()
        self.cadvisor = cadvisor or FakeCadvisor()
        self.heartbeat_period = heartbeat_period
        self.sync_period = sync_period
        self.eviction_period = eviction_period
        self.node_labels = dict(node_labels or {})
        self.node_labels.setdefault(api.LABEL_HOSTNAME, node_name)
        # the node API server's bound port (kubelet/server.py); published in
        # node.status.daemonEndpoints so kubectl logs/exec can find us
        # (reference --port + server.go:237)
        self.server_port: int = 0
        self.recorder = EventRecorder(client, "kubelet", source_host=node_name)
        # PVC->PV resolution for the runtime's volume manager (the kubelet
        # is the API-connected party; the runtime is not)
        vm = getattr(self.runtime, "volumes", None)
        if vm is not None and vm.resolver is None:
            vm.resolver = client
        self._pod_ip_base = pod_ip_base
        self._ip_counter = 0
        self._statuses: Dict[str, tuple] = {}  # key -> last written signature
        self._ready: Dict[str, bool] = {}      # key -> last probed readiness
        self._pulled: set = set()              # keys with Pulled already emitted
        # pods WE declared terminal (evicted / died with restartPolicy=Never /
        # failed admission): a stale watch event still carrying phase=Running
        # must never restart them (the reference's status manager owns the
        # same authority over locally-terminated pods)
        self._terminal: set = set()
        # terminal writes that failed transiently; retried each resync tick
        # (a stuck phase=Running in the API strands node capacity forever)
        self._pending_terminal: Dict[str, tuple] = {}
        self._heartbeat_lock = threading.Lock()
        # serializes pod deletion (informer thread) against the resync
        # tick's re-dispatch (resync thread): without it a stale desired
        # snapshot can restart a pod whose DELETE landed mid-loop
        self._lifecycle_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = []
        self.probes = ProbeManager(self.runtime)
        self.pleg = PLEG(self.runtime)
        self.eviction = EvictionManager(self.cadvisor, self.runtime)
        # pod source: apiserver watch filtered to me (config/apiserver.go:29)
        self.pod_informer = Informer(ListWatch(
            client, "pods",
            field_selector=fieldsel.parse_field_selector(
                f"spec.nodeName={node_name}")))
        self.pod_informer.add_event_handler(
            on_add=self._dispatch,
            on_update=lambda old, new: self._dispatch(new),
            on_delete=self._pod_deleted)

    # --- node lifecycle ------------------------------------------------------

    def register_node(self):
        """Create (or adopt) our Node object (reference kubelet
        registerWithApiserver)."""
        resources = self.cadvisor.machine_resources()
        node = api.Node(
            metadata=api.ObjectMeta(name=self.node_name, labels=self.node_labels),
            status=api.NodeStatus(
                capacity=dict(resources), allocatable=dict(resources),
                conditions=[_ready_condition()],
                addresses=[api.NodeAddress(type="InternalIP",
                                           address=self._node_ip())],
                daemon_endpoints=api.NodeDaemonEndpoints(
                    kubelet_endpoint=api.DaemonEndpoint(
                        port=self.server_port)) if self.server_port else None,
                node_info=api.NodeSystemInfo(
                    kubelet_version="kubernetes-tpu-0.1",
                    container_runtime_version=type(self.runtime).__name__)))
        try:
            self.client.create("nodes", node)
        except ApiError as e:
            if not e.code == 409:
                raise

    def heartbeat(self):
        """Refresh the Ready + MemoryPressure conditions (node status
        update loop; MemoryPressure fed by the eviction manager). Serialized:
        the eviction tick's prompt heartbeat must not lose its fresh
        MemoryPressure flip to the periodic thread's concurrent
        read-modify-write."""
        sp = Span("kubelet_heartbeat", node=self.node_name)
        try:
            with use_span(sp):
                with self._heartbeat_lock:
                    self._heartbeat_locked()
        finally:
            sp.finish()

    def _heartbeat_locked(self):
        try:
            node = self.client.get("nodes", self.node_name)
        except ApiError:
            return
        node.status = node.status or api.NodeStatus()
        conds = [c for c in (node.status.conditions or [])
                 if c.type not in (api.NODE_READY, api.NODE_MEMORY_PRESSURE)]
        conds.append(_ready_condition())
        conds.append(api.NodeCondition(
            type=api.NODE_MEMORY_PRESSURE,
            status=(api.CONDITION_TRUE if self.eviction.under_pressure
                    else api.CONDITION_FALSE),
            reason=("KubeletHasInsufficientMemory"
                    if self.eviction.under_pressure
                    else "KubeletHasSufficientMemory"),
            last_heartbeat_time=now_iso()))
        node.status.conditions = conds
        if self.server_port:
            node.status.daemon_endpoints = api.NodeDaemonEndpoints(
                kubelet_endpoint=api.DaemonEndpoint(port=self.server_port))
        try:
            # status PATCH, not PUT: concurrent spec writers (cordon, taints)
            # can no longer be clobbered by a stale heartbeat read
            # (reference resthandler.go:503 PATCH; merge type replaces the
            # conditions list wholesale, which the heartbeat owns)
            enc = scheme.encode(node)
            status = {k: enc["status"].get(k)
                      for k in ("conditions", "allocatable", "capacity",
                                "daemonEndpoints", "addresses")
                      if enc["status"].get(k) is not None}
            self.client.patch(
                "nodes", node.metadata.name, {"status": status},
                subresource="status",
                patch_type=self.client.MERGE_PATCH)
        except ApiError:
            pass

    # --- pod sync ------------------------------------------------------------

    def _dispatch(self, pod: api.Pod):
        # runs inline on the informer dispatch thread: events for a pod are
        # applied in order (the reference serializes via per-pod podWorkers;
        # a thread-per-event here let a stale update resurrect a killed pod)
        sp = Span("kubelet_sync_pod", node=self.node_name,
                  pod=f"{pod.metadata.namespace}/{pod.metadata.name}")
        try:
            # sync under the span: the status PATCHes and Event posts this
            # sync issues share its trace id through the apiserver audit log
            with use_span(sp):
                self._sync_pod(pod)
        finally:
            sp.finish()

    def _sync_pod(self, pod: api.Pod):
        """syncPod: admit -> run -> report (kubelet.go:1796)."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        if pod.metadata.deletion_timestamp is not None:
            if key in self.runtime.running():
                self.recorder.event(pod, "Normal", "Killing",
                                    f"Killing pod {pod.metadata.name}")
            self.runtime.kill_pod(key)
            return
        if key in self._terminal:
            # we already declared this pod Failed (eviction / Never-policy
            # death / admission): ignore stale Running snapshots
            return
        phase = pod.status.phase if pod.status else ""
        if phase in (api.POD_SUCCEEDED, api.POD_FAILED):
            return
        if key not in self.runtime.running():
            err = self._admit(pod)
            if err is not None:
                self._set_status(pod, api.POD_FAILED, reason="OutOfResources",
                                 message=err)
                self.recorder.event(pod, "Warning", "FailedAdmission", err)
                return
            if key not in self._pulled:
                # once per pod lifetime, not per start attempt: a FailedSync
                # retry loop re-entering here every resync tick would drain
                # the recorder's per-pod spam budget and silence later REAL
                # events (Killing/Evicted)
                self._pulled.add(key)
                for c in (pod.spec.containers or []) if pod.spec else []:
                    if c.image:
                        # no image puller in this runtime: images are always
                        # "present"; the event keeps the reference's
                        # lifecycle trail (Pulled -> Started) readable in
                        # kubectl describe
                        self.recorder.event(
                            pod, "Normal", "Pulled",
                            f'Container image "{c.image}" already present '
                            "on machine")
            try:
                self.runtime.sync_pod(pod)
            except Exception as e:
                # mount/spawn failure: surface it and stay Pending; the
                # resync tick re-dispatches desired-but-not-running pods so
                # a fixed hostPath / late-bound PVC heals without an event
                # (reference: FailedMount events + WaitForAttachAndMount
                # retry, volume_manager.go)
                self.recorder.event(pod, "Warning", "FailedSync",
                                    f"{type(e).__name__}: {e}")
                log.warning("sync of %s failed: %s", key, e)
                return
            self.recorder.event(pod, "Normal", "Started",
                                f"Started pod {pod.metadata.name}")
            created = parse_iso(pod.metadata.creation_timestamp)
            prior_start = bool(pod.status and pod.status.start_time)
            if created is not None and not prior_start:
                # the density-suite SLI: pod creation -> containers started
                # (coarse: the API stamps are second-resolution). Gated on
                # no prior status.start_time: a kubelet restart re-syncing
                # long-running pods must not record pod AGE as startup
                # latency and poison the histogram's tail
                # wall vs the serialized creationTimestamp — monotonic has
                # no epoch to compare against it
                # kube-verify: disable-next-line=monotonic-duration
                startup = max(time.time() - created, 0.0)
                METRICS.observe("kubelet_pod_startup_latency_seconds",
                                startup)
            # pods with readiness probes start unready until the first
            # success; afterwards the probe loop owns this bit
            has_readiness = any(c.readiness_probe
                                for c in (pod.spec.containers or []) if c)
            self._ready.setdefault(key, not has_readiness)
        self._set_status(pod, api.POD_RUNNING,
                         ready=self._ready.get(key, True))

    def _admit(self, pod: api.Pod) -> Optional[str]:
        """Node-side re-check of GeneralPredicates (canAdmitPod; the kubelet
        is the second enforcer, predicates.go:145-147)."""
        try:
            node = self.client.get("nodes", self.node_name)
        except ApiError:
            return None  # can't validate; accept (apiserver is authoritative)
        ni = NodeInfo(node)
        for rp in self.runtime.running().values():
            ni.add_pod(rp.pod)
        try:
            general_predicates(pod, ni)
        except PredicateFailure as e:
            return str(e)
        return None

    def _set_status(self, pod: api.Pod, phase: str, reason: str = "",
                    message: str = "", ready: bool = True):
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        running = self.runtime.running().get(key)
        restarts = tuple(sorted(running.restart_counts.items())) \
            if running else ()
        sig = (phase, reason, ready, restarts)
        if phase in (api.POD_FAILED, api.POD_SUCCEEDED):
            # local decision is authoritative even if the write below fails;
            # _sync_pod consults this before ever (re)starting the pod
            self._terminal.add(key)
        if self._statuses.get(key) == sig:
            return
        fresh = deep_copy(pod)
        fresh.metadata.resource_version = ""  # unconditional status write
        fresh.status = fresh.status or api.PodStatus()
        fresh.status.phase = phase
        fresh.status.reason = reason
        fresh.status.message = message
        fresh.status.host_ip = self._node_ip()
        if phase == api.POD_RUNNING:
            if self.runtime.fakes_network:
                self._ip_counter += 1
                fresh.status.pod_ip = fresh.status.pod_ip or (
                    f"{self._pod_ip_base}.{self._ip_counter // 255}."
                    f"{self._ip_counter % 255 + 1}")
            else:
                # real-process pods share the host network: their IP is the
                # node's, so endpoints built from it are actually dialable
                # (the proxy relay moves real bytes to them)
                fresh.status.pod_ip = self._node_ip()
            fresh.status.start_time = fresh.status.start_time or now_iso()
            conds = [c for c in (fresh.status.conditions or [])
                     if c.type != api.POD_READY]
            conds.append(api.PodCondition(
                type=api.POD_READY,
                status=api.CONDITION_TRUE if ready else api.CONDITION_FALSE,
                reason="" if ready else "ContainersNotReady",
                last_transition_time=now_iso()))
            fresh.status.conditions = conds
            if running:
                states = self.runtime.container_states(key)
                fresh.status.container_statuses = [
                    api.ContainerStatus(
                        name=c.name,
                        ready=ready and states.get(c.name) == "running",
                        image=c.image, container_id=cid,
                        restart_count=running.restart_counts.get(c.name, 0),
                        state=api.ContainerState(
                            running=api.ContainerStateRunning(started_at=now_iso())))
                    for c, cid in zip(fresh.spec.containers or [],
                                      running.container_ids)]
        try:
            # status PATCH (merge type): only the fields this kubelet
            # composes travel; fields owned by other writers survive
            self.client.patch(
                "pods", fresh.metadata.name,
                {"status": scheme.encode(fresh).get("status", {})},
                namespace=fresh.metadata.namespace, subresource="status",
                patch_type=self.client.MERGE_PATCH)
            self._statuses[key] = sig
            self._pending_terminal.pop(key, None)
        except ApiError as e:
            if e.is_not_found:
                self._pending_terminal.pop(key, None)
                return
            log.warning("status update for %s failed: %s", key, e)
            if phase in (api.POD_FAILED, api.POD_SUCCEEDED):
                # _sync_pod short-circuits terminal pods, so nothing else
                # would ever retry this write — queue it for the resync tick
                self._pending_terminal[key] = (pod, phase, reason, message)

    def _pod_deleted(self, pod: api.Pod):
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lifecycle_lock:
            self.runtime.kill_pod(key)
            self.probes.forget_pod(key)
            self._statuses.pop(key, None)
            self._ready.pop(key, None)
            self._pulled.discard(key)
            self._terminal.discard(key)  # a recreated name starts fresh

    def _resync(self):
        """Desired-state reconcile (kill runtime pods no longer desired)
        plus the PLEG relist + probe step — the syncLoopIteration sources
        (kubelet.go:2619) collapsed onto one periodic tick."""
        desired = {}
        for p in self.pod_informer.store.list():
            desired[f"{p.metadata.namespace}/{p.metadata.name}"] = p
        for key in list(self.runtime.running()):
            if key not in desired:
                self.runtime.kill_pod(key)
                self.probes.forget_pod(key)

        # retry terminal status writes that failed transiently
        for key, args in list(self._pending_terminal.items()):
            self._set_status(*args)

        # re-dispatch desired pods that never started (mount failures,
        # transient spawn errors): the retry loop behind FailedSync above.
        # Per-pod under the lifecycle lock, against the CURRENT store
        # object — a DELETE landing mid-loop must not be resurrected from
        # the stale `desired` snapshot
        running_now = self.runtime.running()
        for key in list(desired):
            if key in running_now or key in self._terminal:
                continue
            with self._lifecycle_lock:
                pod = self.pod_informer.store.get(key)
                if pod is None or pod.metadata.deletion_timestamp is not None:
                    continue
                phase = pod.status.phase if pod.status else ""
                if phase in (api.POD_SUCCEEDED, api.POD_FAILED):
                    continue
                if pod.spec and pod.spec.node_name == self.node_name:
                    self._sync_pod(pod)

        # PLEG: container deaths -> restart policy (pleg/generic.go:180)
        for ev in self.pleg.relist():
            if ev.type != CONTAINER_DIED:
                continue
            pod = desired.get(ev.pod_key)
            if pod is None:
                continue
            policy = (pod.spec.restart_policy or "Always") if pod.spec else "Always"
            # real runtimes report exit codes; None (hollow kill) counts as
            # failure. OnFailure restarts only failures; a clean exit under
            # OnFailure/Never leaves the container terminated, and the POD
            # completes only when EVERY container has terminated — a clean
            # sidecar exit must not kill a still-working sibling
            # (kubelet.go GetPhase over all container statuses)
            rc = self.runtime.exit_code(ev.pod_key, ev.container)
            succeeded = rc == 0
            if policy == "Always" or (policy == "OnFailure" and not succeeded):
                self.runtime.restart_container(ev.pod_key, ev.container)
                self.probes.forget_container(ev.pod_key, ev.container)
                self.recorder.event(
                    pod, "Normal", "Started",
                    f"Restarted container {ev.container}")
                # the probe loop below writes the status (restart_counts
                # changed its signature) with probe-derived readiness
                continue
            states = self.runtime.container_states(ev.pod_key)
            if any(s == "running" for s in states.values()):
                continue  # siblings still at work; pod stays Running
            all_ok = all(self.runtime.exit_code(ev.pod_key, c) == 0
                         for c in states)
            # terminal BEFORE kill: the informer dispatch thread must
            # never observe killed-but-not-yet-terminal and resurrect
            self._terminal.add(ev.pod_key)
            self.runtime.kill_pod(ev.pod_key)
            self.probes.forget_pod(ev.pod_key)
            if all_ok:
                self._set_status(pod, api.POD_SUCCEEDED, reason="Completed",
                                 message="all containers exited 0")
            else:
                self._set_status(pod, api.POD_FAILED,
                                 reason="ContainersDied",
                                 message=f"container {ev.container} died "
                                         f"(restartPolicy={policy})")

        # probes: readiness feeds POD_READY; liveness failures kill (the
        # next relist restarts per policy)
        for key, rp in self.runtime.running().items():
            pod = desired.get(key)
            if pod is None:
                continue
            ready, kill = self.probes.step(pod)
            for cname in kill:
                self.recorder.event(
                    pod, "Warning", "Unhealthy",
                    f"Liveness probe failed for {cname}; restarting")
                self.runtime.kill_container(key, cname)
            self._ready[key] = ready
            self._set_status(pod, api.POD_RUNNING, ready=ready)

    def _eviction_tick(self):
        """Memory-pressure observation + at most one eviction per interval
        (pkg/kubelet/eviction manager loop)."""
        was = self.eviction.under_pressure
        victim = self.eviction.observe()
        if self.eviction.under_pressure != was:
            self.heartbeat()  # flip MemoryPressure promptly
        if victim is None:
            return
        rp = self.runtime.running().get(victim)
        if rp is None:
            return
        pod = rp.pod
        self._terminal.add(victim)  # before the kill — see _resync Never path
        self.recorder.event(pod, "Warning", EVICTED_REASON,
                            "The node was low on resource: memory.")
        self.runtime.kill_pod(victim)
        self.probes.forget_pod(victim)
        self._set_status(pod, api.POD_FAILED, reason=EVICTED_REASON,
                         message="Pod evicted due to memory pressure")

    # --- lifecycle -----------------------------------------------------------

    def start(self, register: bool = True):
        if register:
            self.register_node()
        self.pod_informer.run()
        self.pod_informer.wait_for_sync()
        for name, target, period in (
                ("kubelet-heartbeat", self.heartbeat, self.heartbeat_period),
                ("kubelet-resync", self._resync, self.sync_period),
                ("kubelet-eviction", self._eviction_tick,
                 self.eviction_period)):
            t = threading.Thread(target=self._periodic, args=(target, period),
                                 name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _periodic(self, fn, period: float):
        while not self._stop.wait(period):
            try:
                fn()
            except Exception:
                log.exception("periodic %s failed", fn.__name__)

    def stop(self):
        self._stop.set()
        self.pod_informer.stop()

    def _node_ip(self) -> str:
        # hollow nodes fabricate an address (nothing routes to them anyway);
        # a real-process runtime is reachable on loopback, and kubectl
        # logs/exec dial node.status.addresses — they must get a real one
        return "192.168.0.1" if self.runtime.fakes_network else "127.0.0.1"


def _ready_condition() -> api.NodeCondition:
    return api.NodeCondition(
        type=api.NODE_READY, status=api.CONDITION_TRUE,
        reason="KubeletReady", message="kubelet is posting ready status",
        last_heartbeat_time=now_iso())
