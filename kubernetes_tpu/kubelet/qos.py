"""QoS classification (reference pkg/kubelet/qos/policy.go + util.go).

Guaranteed: every container sets limits and requests == limits for cpu+mem.
Burstable: some resource is requested/limited but not Guaranteed-shaped.
BestEffort: no requests or limits anywhere — first against the wall under
memory pressure (eviction ordering, pkg/kubelet/eviction/helpers.go).

BestEffort is the scheduler's predicates.is_best_effort — ONE definition
shared by the eviction ranking here and CheckNodeMemoryPressure there, so an
extended-resource-only pod (e.g. TPU, no cpu/mem) can never be evicted as
BestEffort yet rescheduled onto the pressured node as non-BestEffort."""

from __future__ import annotations

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.predicates import is_best_effort

GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"

_QOS_RESOURCES = (api.RESOURCE_CPU, api.RESOURCE_MEMORY)


def qos_class(pod: api.Pod) -> str:
    if is_best_effort(pod):
        return BEST_EFFORT
    guaranteed = True
    for c in (pod.spec.containers or []) if pod.spec else []:
        req = (c.resources.requests if c.resources and c.resources.requests
               else {})
        lim = (c.resources.limits if c.resources and c.resources.limits
               else {})
        for r in _QOS_RESOURCES:
            if req.get(r) != lim.get(r) or r not in lim:
                guaranteed = False
    if guaranteed:
        return GUARANTEED
    return BURSTABLE


# eviction order under resource pressure: BestEffort evicts first
EVICTION_ORDER = {BEST_EFFORT: 0, BURSTABLE: 1, GUARANTEED: 2}
