"""QoS classification (reference pkg/kubelet/qos/policy.go + util.go).

Guaranteed: every container sets limits and requests == limits for cpu+mem.
Burstable: at least one container sets a cpu/mem request.
BestEffort: no requests or limits anywhere — first against the wall under
memory pressure (eviction ordering, pkg/kubelet/eviction/helpers.go)."""

from __future__ import annotations

from kubernetes_tpu.api import types as api

GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"

_QOS_RESOURCES = (api.RESOURCE_CPU, api.RESOURCE_MEMORY)


def qos_class(pod: api.Pod) -> str:
    requests = limits = False
    guaranteed = True
    for c in (pod.spec.containers or []) if pod.spec else []:
        req = (c.resources.requests if c.resources and c.resources.requests
               else {})
        lim = (c.resources.limits if c.resources and c.resources.limits
               else {})
        for r in _QOS_RESOURCES:
            if r in req:
                requests = True
            if r in lim:
                limits = True
            if req.get(r) != lim.get(r) or r not in lim:
                guaranteed = False
    if not requests and not limits:
        return BEST_EFFORT
    if guaranteed:
        return GUARANTEED
    return BURSTABLE


# eviction order under resource pressure: BestEffort evicts first
EVICTION_ORDER = {BEST_EFFORT: 0, BURSTABLE: 1, GUARANTEED: 2}
