"""L7 node agent.

Parity target: reference pkg/kubelet (52.4k LoC) — the load-bearing shape:
syncLoop consuming pod-source updates (kubelet.go:2567), per-pod sync through
a runtime interface (dockertools/rkt behind container.Runtime), local
admission re-running GeneralPredicates (canAdmitPod), a status manager
pushing PodStatus to the apiserver, node-status heartbeats, and PLEG-style
runtime relisting. The hollow configuration (fake runtime + fake cadvisor) is
the kubemark building block (cmd/kubemark/hollow-node.go:85-139).
"""

from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.runtime import FakeRuntime, PodRuntime
