"""The kubelet's node API server (:10250 analog).

Parity target: reference pkg/kubelet/server/server.go:237-298 — the routes
a node agent serves beyond health/metrics:

  GET  /pods                                      running pod list
  GET  /containerLogs/{ns}/{pod}/{container}      ?tailLines=N&previous=true
  POST /exec/{ns}/{pod}/{container}?command=a&command=b    run argv
  GET  /healthz, /metrics, /configz               debug bundle

The reference streams exec/attach/portforward over SPDY
(pkg/util/httpstream); this framework's clients are its own, so exec
answers a plain JSON {rc, output} over HTTP and logs stream as text/plain —
same capability, native wire. kubectl logs/exec resolve the pod's node,
read the kubelet endpoint from node.status.daemonEndpoints, and call
these routes directly (the reference's apiserver->node proxy path
collapses to a direct hop in a flat test network).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.utils.debugserver import debug_route

_LOGS = re.compile(r"^/containerLogs/([^/]+)/([^/]+)/([^/]+)$")
_EXEC = re.compile(r"^/(?:exec|run)/([^/]+)/([^/]+)/([^/]+)$")


class KubeletServer:
    """HTTP server over a PodRuntime (+ the debug endpoint bundle)."""

    def __init__(self, runtime, port: int = 0, host: str = "127.0.0.1",
                 healthz: Optional[Callable[[], bool]] = None,
                 configz: Optional[Dict[str, object]] = None):
        self.runtime = runtime
        self._host = host
        self._port = port
        self.healthz = healthz or (lambda: True)
        self.configz: Dict[str, object] = dict(configz or {})
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    def start(self) -> "KubeletServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True  # see utils/nethost.py

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body: bytes, ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, payload):
                self._send(code, json.dumps(payload).encode(),
                           "application/json")

            def do_GET(self):
                url = urlparse(self.path)
                q = parse_qs(url.query, keep_blank_values=True)
                # full path incl. query: /profilez/start?dir=... needs it
                hit = debug_route(self.path, outer.healthz, outer.configz)
                if hit is not None:
                    return self._send(*hit[:2], hit[2])
                if url.path == "/pods":
                    from kubernetes_tpu.api.serialization import scheme
                    items = [scheme.encode(rp.pod)
                             for rp in outer.runtime.running().values()]
                    return self._send_json(200, {"kind": "PodList",
                                                 "items": items})
                m = _LOGS.match(url.path)
                if m:
                    return self._serve_logs(m, q)
                self._send(404, b"not found")

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                url = urlparse(self.path)
                # keep_blank_values: an empty argv element ('grep "" f') is
                # a real argument, not absence of one
                q = parse_qs(url.query, keep_blank_values=True)
                m = _EXEC.match(url.path)
                if m:
                    return self._serve_exec(m, q)
                self._send(404, b"not found")

            def _serve_logs(self, m, q):
                ns, pod, container = m.groups()
                logs = getattr(outer.runtime, "logs", None)
                if logs is None:
                    return self._send(501, b"runtime has no log access")
                tail = q.get("tailLines", [None])[0]
                prev = q.get("previous", ["false"])[0] in ("true", "1")
                try:
                    tail_n = int(tail) if tail else None
                except ValueError:
                    return self._send(400, f"bad tailLines {tail!r}".encode())
                try:
                    text = logs(f"{ns}/{pod}", container,
                                tail_lines=tail_n, previous=prev)
                except KeyError as e:
                    return self._send(404, str(e).encode())
                self._send(200, text.encode("utf-8", "replace"))

            def _serve_exec(self, m, q):
                ns, pod, container = m.groups()
                execfn = getattr(outer.runtime, "exec", None)
                if execfn is None:
                    return self._send(501, b"runtime has no exec")
                command = q.get("command", [])
                if not command:
                    return self._send(400, b"command required")
                try:
                    rc, output = execfn(f"{ns}/{pod}", container, command)
                except KeyError as e:
                    return self._send(404, str(e).encode())
                self._send_json(200, {"rc": rc, "output": output})

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="kubelet-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
