"""Memory-pressure eviction (reference pkg/kubelet/eviction).

When the stats provider reports memory pressure, the manager:
- flips the node's MemoryPressure condition True (the scheduler's
  CheckNodeMemoryPressure predicate then keeps new BestEffort pods away);
- evicts ONE victim per observation interval, ranked by QoS class —
  BestEffort before Burstable before Guaranteed, oldest first within a
  class (eviction/helpers.go qos ordering): pod phase Failed with reason
  "Evicted", containers killed.

Pressure clearing flips the condition back. One-victim-per-interval is the
reference's pressure-relief pacing (the manager re-observes between kills).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.kubelet.qos import EVICTION_ORDER, qos_class

log = logging.getLogger("kubelet.eviction")

EVICTED_REASON = "Evicted"


class EvictionManager:
    def __init__(self, cadvisor, runtime):
        self.cadvisor = cadvisor
        self.runtime = runtime
        self.under_pressure = False

    def observe(self) -> Optional[str]:
        """One interval: update pressure state; return the pod key to evict
        (or None). The kubelet owns the status/event writes."""
        self.under_pressure = bool(self.cadvisor.under_memory_pressure())
        if not self.under_pressure:
            return None
        victims = self._ranked()
        return victims[0] if victims else None

    def _ranked(self) -> List[str]:
        entries = []
        for key, rp in self.runtime.running().items():
            entries.append((EVICTION_ORDER.get(qos_class(rp.pod), 2),
                            rp.started_at, key))
        entries.sort()
        return [key for _, _, key in entries]
