"""kubernetes-discovery: the API discovery/aggregation proxy.

Parity target: reference cmd/kubernetes-discovery — one endpoint fronting
several API servers (e.g. the core plane and the federation plane):
/apis merges every upstream's group list, and resource requests route to
the upstream that serves their group. Clients configure one server and
see the union.
"""

from kubernetes_tpu.discovery.proxy import DiscoveryProxy

__all__ = ["DiscoveryProxy"]
