"""kubernetes-discovery entrypoint: python -m kubernetes_tpu.discovery

One endpoint fronting several API servers; --server may repeat (first is
the primary/core plane).
"""

from __future__ import annotations

import argparse
import signal
import threading

from kubernetes_tpu.discovery import DiscoveryProxy


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubernetes-discovery")
    p.add_argument("--server", action="append", required=True,
                   help="upstream apiserver host:port (repeatable; first "
                        "is primary)")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    a = p.parse_args(argv)

    proxy = DiscoveryProxy(a.server, host=a.bind_address, port=a.port).start()
    print(f"discovery proxy listening on "
          f"http://{a.bind_address}:{proxy.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a_: stop.set())
    signal.signal(signal.SIGINT, lambda *a_: stop.set())
    stop.wait()
    proxy.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
