"""The discovery proxy server.

Routing (reference cmd/kubernetes-discovery discoverysummarizer +
the aggregation pattern it grew into):

  GET /apis          union of every upstream's APIGroupList
  GET /api           the primary upstream's core versions
  /api/...           forwarded to the primary upstream
  /apis/<group>/...  forwarded to the upstream that announced <group>
                     (learned from its /apis at startup and refreshed
                     when an unknown group arrives)
  /healthz           503 until every upstream answers, then 200

Forwarding is transparent at the HTTP layer: method, query string, body,
and content-type travel as-is, so watches stream through chunk by chunk.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

import http.client

from kubernetes_tpu.utils.nethost import parse_host_port


class _Upstream:
    def __init__(self, address: str):
        self.host, self.port = parse_host_port(address)
        self.address = address

    def conn(self, timeout: float = 30.0) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)

    def get_json(self, path: str):
        conn = self.conn(timeout=5)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            return json.loads(data)
        finally:
            conn.close()


class DiscoveryProxy:
    """One socket fronting N API servers; the first is primary (core)."""

    def __init__(self, upstream_addresses: List[str], host: str = "127.0.0.1",
                 port: int = 0):
        if not upstream_addresses:
            raise ValueError("at least one upstream required")
        self.upstreams = [_Upstream(a) for a in upstream_addresses]
        self._host = host
        self._port = port
        self._httpd = None
        self._thread = None
        self._lock = threading.Lock()
        self._group_map: Dict[str, _Upstream] = {}

    # -- group learning --------------------------------------------------------

    def _refresh_groups(self) -> None:
        mapping: Dict[str, _Upstream] = {}
        for up in self.upstreams:
            doc = up.get_json("/apis")
            for g in (doc or {}).get("groups", []):
                # first upstream serving a group wins (primary precedence)
                mapping.setdefault(g.get("name", ""), up)
        with self._lock:
            self._group_map = mapping

    def _upstream_for_group(self, group: str) -> Optional[_Upstream]:
        with self._lock:
            up = self._group_map.get(group)
        if up is None:
            self._refresh_groups()
            with self._lock:
                up = self._group_map.get(group)
        return up

    def merged_groups(self) -> dict:
        groups, seen = [], set()
        for up in self.upstreams:
            doc = up.get_json("/apis")
            for g in (doc or {}).get("groups", []):
                name = g.get("name", "")
                if name not in seen:
                    seen.add(name)
                    groups.append(g)
        return {"kind": "APIGroupList", "groups": groups}

    # -- lifecycle -------------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    def start(self) -> "DiscoveryProxy":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    for up in outer.upstreams:
                        try:
                            ok = up.get_json("/api") is not None
                        except Exception:
                            ok = False
                        if not ok:
                            return self._send_json(
                                503, {"status": "unhealthy",
                                      "upstream": up.address})
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/apis" and self.command == "GET":
                    return self._send_json(200, outer.merged_groups())
                if path.startswith("/apis/"):
                    group = path.split("/", 3)[2]
                    up = outer._upstream_for_group(group)
                    if up is None:
                        return self._send_json(
                            404, {"kind": "Status", "code": 404,
                                  "reason": "NotFound",
                                  "message": f"no upstream serves group "
                                             f"{group!r}"})
                    return self._forward(up)
                # core API + everything else: the primary upstream
                return self._forward(outer.upstreams[0])

            def _forward(self, up: _Upstream):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                # watches idle between events; the upstream heartbeats
                # every ~30s, so 120s only trips on a truly dead upstream
                conn = up.conn(timeout=120)
                started = False
                try:
                    headers = {}
                    for h in ("Content-Type", "Accept", "Authorization"):
                        if self.headers.get(h):
                            headers[h] = self.headers[h]
                    conn.request(self.command, self.path, body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                    started = True
                    self.send_response(resp.status)
                    chunked = (resp.getheader("Transfer-Encoding", "")
                               .lower() == "chunked")
                    ctype = resp.getheader("Content-Type")
                    if ctype:
                        self.send_header("Content-Type", ctype)
                    if chunked:
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        # stream watch frames through as they arrive
                        while True:
                            chunk = resp.read1(65536)
                            if not chunk:
                                self.wfile.write(b"0\r\n\r\n")
                                break
                            self.wfile.write(
                                f"{len(chunk):x}\r\n".encode() + chunk
                                + b"\r\n")
                            self.wfile.flush()
                    else:
                        data = resp.read()
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except OSError as e:
                    if started:
                        # mid-stream failure: a second status line would
                        # corrupt the chunked body — close; the client's
                        # short read triggers its re-list/retry path
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                        except OSError:
                            pass
                        self.close_connection = True
                        return
                    try:
                        self._send_json(502, {
                            "kind": "Status", "code": 502,
                            "reason": "BadGateway",
                            "message": f"upstream {up.address}: {e}"})
                    except OSError:
                        pass
                finally:
                    conn.close()

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _route

        class Server(ThreadingHTTPServer):
            request_queue_size = 128

        self._httpd = Server((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="discovery-proxy", daemon=True)
        self._thread.start()
        self._refresh_groups()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
