"""The discovery proxy server.

Routing (reference cmd/kubernetes-discovery discoverysummarizer +
the aggregation pattern it grew into):

  GET /apis          union of every upstream's APIGroupList
  GET /api           the primary upstream's core versions
  /api/...           forwarded to the primary upstream
  /apis/<group>/...  forwarded to the upstream that announced <group>
                     (learned from its /apis at startup and refreshed
                     when an unknown group arrives)
  /healthz           503 until every upstream answers, then 200

Forwarding is transparent at the HTTP layer: method, query string, body,
and content-type travel as-is, so watches stream through chunk by chunk.

Member rotation is health-gated (the multi-apiserver half of ROADMAP item
4): an upstream whose connection fails before any response byte enters a
short cooldown and the request is retried against the next healthy
upstream — a killed apiserver costs its in-flight streams (clients
re-list, the Reflector contract) but never takes the proxy's route with
it. /healthz degrades instead of failing: 200 while ANY upstream lives.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

import http.client

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.nethost import parse_host_port

# an upstream that refused a connection is skipped for this long before
# being re-tried — long enough to stop hammering a corpse, short enough
# that a restarted apiserver rejoins the rotation promptly
DOWN_COOLDOWN_SECONDS = 2.0


class _Upstream:
    def __init__(self, address: str):
        self.host, self.port = parse_host_port(address)
        self.address = address
        # monotonic timestamp until which this upstream sits out rotation
        self.down_until = 0.0

    def mark_down(self) -> None:
        self.down_until = time.monotonic() + DOWN_COOLDOWN_SECONDS

    def mark_up(self) -> None:
        self.down_until = 0.0

    @property
    def in_cooldown(self) -> bool:
        return time.monotonic() < self.down_until

    def conn(self, timeout: float = 30.0) -> http.client.HTTPConnection:
        from kubernetes_tpu.utils.nethost import NoDelayHTTPConnection
        return NoDelayHTTPConnection(self.host, self.port, timeout=timeout)

    def get_json(self, path: str):
        conn = self.conn(timeout=5)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            return json.loads(data)
        finally:
            conn.close()


class _UpstreamDown(Exception):
    """The upstream failed before any response byte. `request_unsent` is
    True when the failure happened while still SENDING (connect/request):
    the upstream provably never received it, so any verb may rotate; False
    means the request was delivered but never answered — the upstream may
    have executed it, and only idempotent verbs may be replayed."""

    def __init__(self, cause: BaseException, request_unsent: bool):
        super().__init__(str(cause))
        self.cause = cause
        self.request_unsent = request_unsent


class DiscoveryProxy:
    """One socket fronting N API servers; the first is primary (core)."""

    def __init__(self, upstream_addresses: List[str], host: str = "127.0.0.1",
                 port: int = 0):
        if not upstream_addresses:
            raise ValueError("at least one upstream required")
        self.upstreams = [_Upstream(a) for a in upstream_addresses]
        self._host = host
        self._port = port
        self._httpd = None
        self._thread = None
        self._lock = threading.Lock()
        self._group_map: Dict[str, _Upstream] = {}

    # -- group learning --------------------------------------------------------

    def _refresh_groups(self) -> None:
        mapping: Dict[str, _Upstream] = {}
        for up in self.upstreams:
            doc = up.get_json("/apis")
            for g in (doc or {}).get("groups", []):
                # first upstream serving a group wins (primary precedence)
                mapping.setdefault(g.get("name", ""), up)
        with self._lock:
            self._group_map = mapping

    def _upstream_for_group(self, group: str) -> Optional[_Upstream]:
        with self._lock:
            up = self._group_map.get(group)
        if up is None:
            self._refresh_groups()
            with self._lock:
                up = self._group_map.get(group)
        return up

    def candidates(self, preferred: Optional[_Upstream] = None
                   ) -> List[_Upstream]:
        """Forwarding order: the preferred upstream (group owner / primary)
        first, then the rest — each tier healthy-before-cooldown, so a dead
        primary rotates out for DOWN_COOLDOWN_SECONDS but a fully-down
        fleet is still attempted (last-resort: cooldowns may be stale)."""
        ordered: List[_Upstream] = []
        if preferred is not None:
            ordered.append(preferred)
        ordered.extend(u for u in self.upstreams if u is not preferred)
        healthy = [u for u in ordered if not u.in_cooldown]
        return healthy + [u for u in ordered if u.in_cooldown]

    def merged_groups(self) -> dict:
        groups, seen = [], set()
        for up in self.upstreams:
            doc = up.get_json("/apis")
            for g in (doc or {}).get("groups", []):
                name = g.get("name", "")
                if name not in seen:
                    seen.add(name)
                    groups.append(g)
        return {"kind": "APIGroupList", "groups": groups}

    # -- lifecycle -------------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    def start(self) -> "DiscoveryProxy":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True  # see utils/nethost.py

            def log_message(self, fmt, *args):
                pass

            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                path = urlparse(self.path).path
                if path == "/healthz":
                    up_addrs, down_addrs = [], []
                    for up in outer.upstreams:
                        try:
                            ok = up.get_json("/api") is not None
                        except Exception:
                            ok = False
                        (up_addrs if ok else down_addrs).append(up.address)
                        (up.mark_up if ok else up.mark_down)()
                    if not up_addrs:
                        return self._send_json(
                            503, {"status": "unhealthy",
                                  "down": down_addrs})
                    if down_addrs:
                        # degraded, not dead: rotation still has members
                        return self._send_json(
                            200, {"status": "degraded", "up": up_addrs,
                                  "down": down_addrs})
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/apis" and self.command == "GET":
                    return self._send_json(200, outer.merged_groups())
                if path.startswith("/apis/"):
                    group = path.split("/", 3)[2]
                    up = outer._upstream_for_group(group)
                    if up is None:
                        return self._send_json(
                            404, {"kind": "Status", "code": 404,
                                  "reason": "NotFound",
                                  "message": f"no upstream serves group "
                                             f"{group!r}"})
                    return self._forward(outer.candidates(up))
                # core API + everything else: the primary upstream first,
                # health-gated rotation behind it
                return self._forward(outer.candidates(outer.upstreams[0]))

            def _forward(self, ups: List[_Upstream]):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                headers = {}
                # hop-safe headers travel as-is — including the tracing
                # pair: without traceparent/x-ktpu-retries the apiserver
                # would mint a fresh root trace for every proxied request
                # and audit records would lose component + retry-ordinal
                # attribution (the failover bundle correlates on these)
                for h in ("Content-Type", "Accept", "Authorization",
                          "User-Agent", "traceparent", "x-ktpu-retries"):
                    if self.headers.get(h):
                        headers[h] = self.headers[h]
                # Rotation policy: a failure while SENDING the request
                # means the upstream never received it — always safe to
                # re-send to the next member. A failure after the send
                # (getresponse) means the upstream may already have
                # EXECUTED it; replaying a non-idempotent verb there could
                # double-apply, so only idempotent reads rotate (the same
                # rule rest.py applies to its own keep-alive retries) —
                # everything else surfaces as 502 and the client's own
                # retry semantics (CAS re-read, re-list) take over.
                last_err: Optional[BaseException] = None
                for up in ups:
                    try:
                        self._forward_one(up, body, headers)
                        return
                    except _UpstreamDown as e:
                        up.mark_down()
                        last_err = e.cause
                        METRICS.inc("discovery_proxy_rotations",
                                    upstream=up.address)
                        if not e.request_unsent and \
                                self.command not in ("GET", "HEAD"):
                            break
                        continue
                down = ups[-1] if ups else None
                try:
                    self._send_json(502, {
                        "kind": "Status", "code": 502,
                        "reason": "BadGateway",
                        "message": f"no upstream reachable "
                                   f"(last: {down.address if down else '?'}"
                                   f": {last_err})"})
                except OSError:
                    pass

            def _forward_one(self, up: _Upstream, body, headers):
                # watches idle between events; the upstream heartbeats
                # every ~30s, so 120s only trips on a truly dead upstream
                conn = up.conn(timeout=120)
                started = False
                sent = False
                try:
                    conn.request(self.command, self.path, body=body,
                                 headers=headers)
                    sent = True
                    resp = conn.getresponse()
                    started = True
                    up.mark_up()
                    self.send_response(resp.status)
                    chunked = (resp.getheader("Transfer-Encoding", "")
                               .lower() == "chunked")
                    ctype = resp.getheader("Content-Type")
                    if ctype:
                        self.send_header("Content-Type", ctype)
                    if chunked:
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        # stream watch frames through as they arrive
                        while True:
                            chunk = resp.read1(65536)
                            if not chunk:
                                self.wfile.write(b"0\r\n\r\n")
                                break
                            self.wfile.write(
                                f"{len(chunk):x}\r\n".encode() + chunk
                                + b"\r\n")
                            self.wfile.flush()
                    else:
                        data = resp.read()
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                except (http.client.HTTPException, OSError) as e:
                    if not started:
                        # the upstream never answered; whether it may have
                        # EXECUTED the request (sent=True) decides if the
                        # caller is allowed to replay it
                        raise _UpstreamDown(e, request_unsent=not sent) \
                            from e
                    if isinstance(e, (BrokenPipeError,
                                      ConnectionResetError)):
                        return  # the CLIENT went away mid-stream
                    # mid-stream upstream failure: a second status line
                    # would corrupt the chunked body — close; the client's
                    # short read triggers its re-list/retry path
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                    self.close_connection = True
                finally:
                    conn.close()

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _route

        class Server(ThreadingHTTPServer):
            request_queue_size = 128

        self._httpd = Server((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="discovery-proxy", daemon=True)
        self._thread.start()
        self._refresh_groups()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
