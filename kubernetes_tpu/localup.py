"""Local multi-process cluster: python -m kubernetes_tpu.localup

The hack/local-up-cluster.sh analogue: boots the apiserver, scheduler,
controller-manager, N hollow kubelets, and a proxy — each as its OWN
process via its `python -m` entrypoint — then waits. kubectl talks to the
printed master URL. Ctrl-C tears everything down."""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import time
from typing import List, Optional


class LocalCluster:
    """Spawns the component processes; test-friendly start()/stop()."""

    def __init__(self, nodes: int = 2, port: int = 0, data_dir: str = "",
                 tpu_backend: bool = True):
        self.nodes = nodes
        self.port = port
        self.data_dir = data_dir
        self.tpu_backend = tpu_backend
        self.master_url: Optional[str] = None
        self.dns_addr: Optional[str] = None
        self.procs: List[subprocess.Popen] = []

    def _spawn(self, *args, pipe_stdout: bool = False) -> subprocess.Popen:
        # only the apiserver's stdout is ever read (its one banner line);
        # piping the others would deadlock them once the pipe buffer fills
        proc = subprocess.Popen(
            [sys.executable, "-m", *args],
            stdout=subprocess.PIPE if pipe_stdout else subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, text=True)
        self.procs.append(proc)
        return proc

    def start(self, timeout: float = 60.0) -> "LocalCluster":
        try:
            return self._start(timeout)
        except BaseException:
            self.stop()  # never leak half-started components
            raise

    def _start(self, timeout: float) -> "LocalCluster":
        apiserver = self._spawn(
            "kubernetes_tpu.apiserver", "--port", str(self.port),
            *(["--data-dir", self.data_dir] if self.data_dir else []),
            pipe_stdout=True)
        # the apiserver prints its bound address (works with --port 0)
        line = apiserver.stdout.readline()
        if "listening on " not in line:
            raise RuntimeError(f"apiserver failed to start: {line!r}")
        self.master_url = line.strip().split("listening on ")[1]

        self._spawn("kubernetes_tpu.scheduler", "--master", self.master_url,
                    "--port", "0",
                    "--tpu-backend", "true" if self.tpu_backend else "false")
        self._spawn("kubernetes_tpu.controllers", "--master", self.master_url,
                    "--port", "0")
        for i in range(self.nodes):
            self._spawn("kubernetes_tpu.kubelet", "--master", self.master_url,
                        "--node-name", f"node-{i:02d}", "--port", "0")
        # userspace mode: the relay that actually moves bytes — a local
        # cluster should have a working dataplane, not a rendered ruleset
        self._spawn("kubernetes_tpu.proxy", "--master", self.master_url,
                    "--port", "0", "--proxy-mode", "userspace")
        dns = self._spawn("kubernetes_tpu.dns", "--kube-master",
                          self.master_url, "--dns-port", "0",
                          pipe_stdout=True)
        line = dns.stdout.readline()
        if "listening on " in line:
            self.dns_addr = line.strip().split("listening on ")[1]
        self._wait_ready(timeout)
        return self

    def _wait_ready(self, timeout: float):
        """All nodes registered and Ready through the real API."""
        from kubernetes_tpu.utils.debugserver import client_from_url
        client = client_from_url(self.master_url)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for proc in self.procs:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"component exited early: {proc.args} rc={proc.returncode}")
            try:
                nodes, _ = client.list("nodes")
            except Exception:
                time.sleep(0.2)
                continue
            ready = [n for n in nodes if any(
                c.type == "Ready" and c.status == "True"
                for c in ((n.status.conditions or []) if n.status else []))]
            if len(ready) >= self.nodes:
                return
            time.sleep(0.2)
        raise TimeoutError(f"cluster not ready within {timeout}s")

    def stop(self):
        for proc in reversed(self.procs):
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.procs.clear()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="localup")
    p.add_argument("--nodes", type=int, default=2)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--data-dir", default="")
    p.add_argument("--tpu-backend", default="true", choices=("true", "false"))
    a = p.parse_args(argv)
    cluster = LocalCluster(nodes=a.nodes, port=a.port, data_dir=a.data_dir,
                           tpu_backend=a.tpu_backend == "true")
    cluster.start()
    print(f"cluster up: {cluster.master_url} ({a.nodes} nodes)\n"
          f"try: python -m kubernetes_tpu.kubectl -s {cluster.master_url} "
          f"get nodes", flush=True)
    stop = [False]
    signal.signal(signal.SIGTERM, lambda *x: stop.__setitem__(0, True))
    signal.signal(signal.SIGINT, lambda *x: stop.__setitem__(0, True))
    try:
        while not stop[0]:
            time.sleep(0.5)
    finally:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
