"""Fault-injecting client transport for chaos testing.

Parity target: reference pkg/client/chaosclient/chaosclient.go — a transport
wrapper that probabilistically intervenes in requests before they reach the
wire, so any component can be run against a misbehaving control plane without
touching the server. Interventions are seeded and deterministic, scoped by
path, and reported to a notifier so tests can assert on what was injected.

Idiomatic difference from the reference: Go wraps http.RoundTripper; here the
seam is RESTClient._request_once / RESTClient.watch, installed per-client by
`install_chaos` and removable with `ChaosController.uninstall()` so a test
can "heal" the network mid-run.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, List, Optional

from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS


class ChaosConnectionReset(ConnectionResetError):
    """Simulated transport failure (chaosclient's simulated connection
    reset). Distinct type so tests can tell injected faults from real ones."""

    def __init__(self):
        super().__init__("connection reset by peer (chaos)")


class Intervention:
    """What a chaos link decided to do instead of the real request: raise
    `error`, or short-circuit with HTTP `status` (a Status-shaped dict)."""

    __slots__ = ("source", "error", "status")

    def __init__(self, source: str, error: Optional[Exception] = None,
                 status: Optional[dict] = None):
        self.source = source
        self.error = error
        self.status = status

    def apply(self):
        if self.error is not None:
            raise self.error
        return self.status


class NetworkError:
    """Fail the request with a simulated connection reset."""

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        return Intervention("NetworkError", error=ChaosConnectionReset())


class HTTPError:
    """Short-circuit with an HTTP error status (e.g. a flaky 500/503).

    Carried as a Status dict so the controller applies the real seam's
    contract: >=400 raises ApiError EXCEPT 429, which is returned for
    RESTClient.request()'s retry loop — an injected flow-control shed must
    recover exactly like a server-sent one."""

    def __init__(self, code: int = 500, reason: str = "InternalError",
                 message: str = "chaos"):
        self.code = code
        self.reason = reason
        self.message = message

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        return Intervention(
            f"HTTPError({self.code})",
            status={"kind": "Status", "code": self.code,
                    "reason": self.reason, "message": self.message})


class Latency:
    """Delay the request, then let it through."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        time.sleep(self.seconds)
        return None


class Times:
    """Fire an inner chaos for the first n consultations, then pass through
    (a bounded outage). Thread-safe: links run outside the controller lock,
    so the check-and-decrement must be atomic or a shared client's concurrent
    threads could stretch the outage past n."""

    def __init__(self, n: int, inner):
        self.remaining = n
        self.inner = inner
        self._lock = threading.Lock()

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        with self._lock:
            if self.remaining <= 0:
                return None
            self.remaining -= 1
        return self.inner.intervene(rng, method, path)


class Probability:
    """Gate an inner chaos on a seeded coin flip (chaosclient's P)."""

    def __init__(self, p: float, inner):
        self.p = p
        self.inner = inner

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        if rng.random() < self.p:
            return self.inner.intervene(rng, method, path)
        return None


class PathChaos:
    """Scope an inner chaos to request paths matching a regex — e.g. fail
    only the scheduler's POST /bindings while everything else works."""

    def __init__(self, pattern: str, inner, methods: Optional[set] = None):
        self.pattern = re.compile(pattern)
        self.inner = inner
        self.methods = methods

    def intervene(self, rng, method: str, path: str) -> Optional[Intervention]:
        if self.methods is not None and method not in self.methods:
            return None
        if not self.pattern.search(path):
            return None
        return self.inner.intervene(rng, method, path)


class _LockedRandom:
    """Serialized rng draws so concurrent requests can't corrupt the seeded
    Mersenne state (instance methods of random.Random are not thread-safe)."""

    def __init__(self, rng, lock):
        self._rng = rng
        self._lock = lock

    def random(self) -> float:
        with self._lock:
            return self._rng.random()


class ChaosController:
    """The installed chain. Tracks interventions; uninstall() heals the
    client (restores the original transport methods)."""

    def __init__(self, client: RESTClient, links, seed: int,
                 notifier: Optional[Callable] = None):
        import random
        self.client = client
        self.links = list(links)
        self._lock = threading.Lock()
        self._rng = _LockedRandom(random.Random(seed), self._lock)
        self.notifier = notifier
        self.interventions: List[tuple] = []  # (source, method, path)
        self._orig_request_once = client._request_once
        self._orig_watch = client.watch
        self._installed = True

    # --- the seam ------------------------------------------------------------

    def _consult(self, method: str, path: str) -> Optional[Intervention]:
        # links run OUTSIDE the lock: a Latency link's sleep must only delay
        # the request it intervened on, never other threads' requests; only
        # the rng draw and the interventions log are serialized
        for link in self.links:
            iv = link.intervene(self._rng, method, path)
            if iv is not None:
                with self._lock:
                    self.interventions.append((iv.source, method, path))
                # the observatory's view of injected faults: a counter the
                # scraper/SLO layer can read, and a stamp on the active
                # span so a burning SLO window is attributable to injected
                # vs. real faults from the trace alone
                METRICS.inc("rest_client_chaos_interventions_total",
                            kind=iv.source)
                sp = trace.current_span()
                if sp is not None:
                    sp.attrs["chaos_intervention"] = iv.source
                    sp.attrs["chaos_interventions"] = \
                        sp.attrs.get("chaos_interventions", 0) + 1
                return iv
        return None

    def _request_once(self, method: str, path: str, body=None,
                      content_type=None) -> dict:
        iv = self._consult(method, path)
        if iv is not None:
            if self.notifier:
                self.notifier(iv, method, path)
            out = iv.apply()
            if out is not None:
                # honor the real seam's contract (rest.py): >=400 raises
                # ApiError, except 429 which is returned for request()'s
                # retry loop — a raw error Status must never decode into a
                # phantom resource object
                code = out.get("code", 0)
                if code >= 400 and code != 429:
                    raise ApiError(code, out.get("reason", "Unknown"),
                                   out.get("message", ""))
                return out
        return self._orig_request_once(method, path, body,
                                       content_type=content_type)

    def _watch(self, resource: str, namespace: str = "", **kw):
        # watches open a dedicated connection; chaos at open time models a
        # watch that can't (re)connect, driving the Reflector's re-list path
        path = f"watch:{resource}"
        iv = self._consult("WATCH", path)
        if iv is not None:
            if self.notifier:
                self.notifier(iv, "WATCH", path)
            out = iv.apply()
            if out is not None:
                # watch opens have no 429-retry contract: any injected
                # status is a failed open (the Reflector backs off/re-lists)
                raise ApiError(out.get("code", 500),
                               out.get("reason", "Unknown"),
                               out.get("message", ""))
        return self._orig_watch(resource, namespace, **kw)

    # --- lifecycle -----------------------------------------------------------

    def uninstall(self):
        """Heal: restore the unwrapped transport."""
        if self._installed:
            self.client._request_once = self._orig_request_once
            self.client.watch = self._orig_watch
            self._installed = False

    def count(self, source_prefix: str = "") -> int:
        with self._lock:
            return sum(1 for s, _, _ in self.interventions
                       if s.startswith(source_prefix))


def install_chaos(client: RESTClient, *links, seed: int = 0,
                  notifier: Optional[Callable] = None) -> ChaosController:
    """Wrap `client`'s transport with a chaos chain. Links are consulted in
    order per request; the first intervention wins. Returns the controller
    (use .uninstall() to heal)."""
    ctl = ChaosController(client, links, seed, notifier)
    client._request_once = ctl._request_once
    client.watch = ctl._watch
    return ctl
