"""Reflector: the checkpoint/resume protocol of the whole system.

Parity target: reference pkg/client/cache/reflector.go:56,252 — LIST at a
resourceVersion, hand the full state to the sink, then WATCH from that
version; on watch failure or 410 Gone, re-LIST. Components are crash-only:
all local state is a rebuildable cache of this protocol (SURVEY §5
checkpoint/resume).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.utils.trace import Span, use_span

log = logging.getLogger("reflector")


class ListWatch:
    """list() -> (items, rv); watch(rv) -> WatchStream.
    (reference cache.ListWatch with selector support, factory.go:458-501)."""

    def __init__(self, client: RESTClient, resource: str, namespace: str = "",
                 label_selector=None, field_selector=None):
        self.client = client
        self.resource = resource
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector

    def list(self):
        return self.client.list(self.resource, self.namespace,
                                self.label_selector, self.field_selector)

    def watch(self, resource_version):
        return self.client.watch(self.resource, self.namespace,
                                 resource_version=resource_version,
                                 label_selector=self.label_selector,
                                 field_selector=self.field_selector)


class Reflector:
    """Pumps a ListWatch into a sink.

    sink contract (duck-typed; FIFO, DeltaFIFO, ThreadSafeStore via adapter,
    and Informer all satisfy it):
      replace(items)           full state after each LIST
      add/update/delete(obj)   incremental watch events
    """

    def __init__(self, lw: ListWatch, sink, relist_backoff: float = 1.0,
                 name: str = ""):
        self.lw = lw
        self.sink = sink
        self.relist_backoff = relist_backoff
        self.name = name or f"reflector-{lw.resource}"
        self.last_sync_rv: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._synced = threading.Event()
        self._active_watch = None

    # --- lifecycle -----------------------------------------------------------

    def run(self):
        """Start the pump in a daemon thread."""
        self._thread = threading.Thread(target=self._loop, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        w = self._active_watch
        if w is not None:
            w.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    # --- the pump (ListAndWatch, reflector.go:252) ---------------------------

    def _loop(self):
        # one "sync chain" span per list-and-watch attempt CHAIN: retries of
        # a failing LIST reuse the same span (one trace id across the whole
        # retry storm, retry ordinal in attrs — rest.py forwards both, so
        # the apiserver audit log shows "attempt N of trace T"), and a chain
        # that syncs cleanly finishes its span and the next relist starts a
        # fresh trace.
        chain: Optional[Span] = None
        failures = 0
        try:
            while not self._stop.is_set():
                if chain is None:
                    chain = Span("reflector_sync", resource=self.lw.resource,
                                 reflector=self.name)
                    failures = 0
                try:
                    with use_span(chain):
                        self._list_and_watch()
                    chain.finish()
                    chain = None
                except Exception as e:
                    failures += 1
                    chain.attrs["retries"] = failures
                    log.warning("%s: list/watch failed: %s; backing off",
                                self.name, e)
                    self._stop.wait(self.relist_backoff)
        finally:
            if chain is not None:
                chain.finish()

    def _list_and_watch(self):
        items, rv = self.lw.list()
        self.sink.replace(items)
        self.last_sync_rv = rv
        self._synced.set()
        while not self._stop.is_set():
            try:
                stream = self.lw.watch(rv)
            except ApiError as e:
                if e.is_gone:  # 410: window expired -> re-list
                    log.info("%s: watch expired at rv %s; relisting", self.name, rv)
                    return
                raise
            self._active_watch = stream
            if self._stop.is_set():
                # stop() raced the watch open: it read _active_watch as None
                # while we were inside lw.watch(), so nobody will stop this
                # stream for us — without this check the pump parks in
                # readline until the server's next heartbeat (30s)
                stream.stop()
                return
            try:
                for etype, obj in stream:
                    if self._stop.is_set():
                        return
                    if etype == "ERROR":
                        # server dropped us (slow watcher / expired window):
                        # obj is a Status dict — answer with a full re-list,
                        # AFTER a backoff (we were dropped because we're too
                        # slow; an immediate O(N) list would amplify that)
                        log.warning("%s: error event: %s", self.name, obj)
                        self._stop.wait(self.relist_backoff)
                        return
                    rv = int(obj.metadata.resource_version or rv)
                    self.last_sync_rv = rv
                    if etype == "ADDED":
                        self.sink.add(obj)
                    elif etype == "MODIFIED":
                        self.sink.update(obj)
                    elif etype == "DELETED":
                        self.sink.delete(obj)
            finally:
                self._active_watch = None
                stream.stop()
            # stream closed server-side: reconnect from last rv without
            # relisting (the common watch-timeout path)


class StoreSink:
    """Adapts a ThreadSafeStore (plus optional event callback) to the
    Reflector sink contract."""

    def __init__(self, store, key_func, on_event: Optional[Callable] = None):
        self.store = store
        self.key = key_func
        self.on_event = on_event

    def replace(self, items):
        keyed = {self.key(o): o for o in items}
        # objects deleted during a watch gap must surface as DELETED to the
        # callback, or consumers' secondary structures go permanently stale
        vanished = [self.store.get(k) for k in self.store.list_keys()
                    if k not in keyed]
        self.store.replace(keyed)
        if self.on_event:
            for o in items:
                self.on_event("SYNC", o)
            for o in vanished:
                if o is not None:
                    self.on_event("DELETED", o)

    def add(self, obj):
        self.store.add(self.key(obj), obj)
        if self.on_event:
            self.on_event("ADDED", obj)

    def update(self, obj):
        self.store.update(self.key(obj), obj)
        if self.on_event:
            self.on_event("MODIFIED", obj)

    def delete(self, obj):
        self.store.delete(self.key(obj))
        if self.on_event:
            self.on_event("DELETED", obj)
