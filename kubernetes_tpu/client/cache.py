"""Keyed stores and queues for the state-replication runtime.

Parity target: reference pkg/client/cache — ThreadSafeStore
(thread_safe_store.go), the blocking FIFO the scheduler pops pending pods
from (fifo.go:54,191), and DeltaFIFO (delta_fifo.go) which preserves event
sequences per key for informer consumers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.analysis import runtime as _race
from kubernetes_tpu.api import types as api


def meta_namespace_key(obj) -> str:
    """namespace/name key (reference MetaNamespaceKeyFunc)."""
    meta = obj.metadata
    if meta.namespace:
        return f"{meta.namespace}/{meta.name}"
    return meta.name


class ThreadSafeStore:
    """Keyed object store with optional named indexes
    (reference thread_safe_store.go + Indexer)."""

    def __init__(self, indexers: Optional[Dict[str, Callable]] = None,
                 name: str = ""):
        self._lock = threading.RLock()
        self._items: Dict[str, object] = {}
        self._indexers = indexers or {}
        self._indices: Dict[str, Dict[str, set]] = {n: {} for n in self._indexers}
        # race-detector mode (analysis/runtime.py, enabled by conftest):
        # fingerprint on write, verify on read — catches readers mutating
        # shared cache objects in place. None in production: one branch.
        self._checker = _race.new_store_checker(name)

    def add(self, key: str, obj):
        with self._lock:
            old = self._items.get(key)
            self._items[key] = obj
            self._update_indices(key, old, obj)
            if self._checker:
                self._checker.on_write(key, obj)

    update = add

    def delete(self, key: str):
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._update_indices(key, old, None)
            if self._checker:
                self._checker.on_delete(key)

    def get(self, key: str):
        with self._lock:
            obj = self._items.get(key)
            if self._checker and obj is not None:
                self._checker.verify(key, obj)
            return obj

    def list(self) -> list:
        with self._lock:
            if self._checker:
                self._checker.verify_many(list(self._items.items()))
            return list(self._items.values())

    def list_keys(self) -> list:
        with self._lock:
            return list(self._items.keys())

    def replace(self, items: Dict[str, object]):
        with self._lock:
            self._items = dict(items)
            self._indices = {n: {} for n in self._indexers}
            for key, obj in self._items.items():
                self._update_indices(key, None, obj)
            if self._checker:
                self._checker.on_replace(self._items)

    def by_index(self, index_name: str, value: str) -> list:
        with self._lock:
            keys = self._indices.get(index_name, {}).get(value, ())
            pairs = [(k, self._items[k]) for k in keys if k in self._items]
            if self._checker:
                self._checker.verify_many(pairs)
            return [v for _, v in pairs]

    def __len__(self):
        with self._lock:
            return len(self._items)

    def _update_indices(self, key: str, old, new):
        for name, fn in self._indexers.items():
            idx = self._indices[name]
            if old is not None:
                for v in fn(old):
                    s = idx.get(v)
                    if s:
                        s.discard(key)
            if new is not None:
                for v in fn(new):
                    idx.setdefault(v, set()).add(key)


def node_name_indexer(pod) -> List[str]:
    """Index assigned pods by node (the scheduler's assigned-pod indexer)."""
    if pod.spec and pod.spec.node_name:
        return [pod.spec.node_name]
    return []


class FIFO:
    """Blocking producer/consumer queue keyed by object; re-adds replace the
    queued value in place (reference fifo.go — the scheduler's pending-pod
    queue, factory.go:104)."""

    def __init__(self, key_func: Callable = meta_namespace_key):
        self._lock = threading.Condition()
        self._items: "OrderedDict[str, object]" = OrderedDict()
        self._key = key_func
        self._closed = False

    def add(self, obj):
        key = self._key(obj)
        with self._lock:
            replaced = key in self._items
            self._items[key] = obj
            if not replaced:
                self._lock.notify()

    def add_if_not_present(self, obj):
        key = self._key(obj)
        with self._lock:
            if key not in self._items:
                self._items[key] = obj
                self._lock.notify()

    def delete(self, obj):
        with self._lock:
            self._items.pop(self._key(obj), None)

    def pop(self, timeout: Optional[float] = None):
        """Block until an item is available; None on timeout/close."""
        with self._lock:
            while not self._items:
                if self._closed or not self._lock.wait(timeout=timeout):
                    return None
            _, obj = self._items.popitem(last=False)
            return obj

    def drain(self, max_n: int) -> list:
        """Pop up to max_n queued items without blocking (batch-scheduler
        intake: first pod blocks via pop(), the rest of the batch drains)."""
        out = []
        with self._lock:
            while self._items and len(out) < max_n:
                _, obj = self._items.popitem(last=False)
                out.append(obj)
        return out

    def drain_where(self, pred: Callable) -> list:
        """Pop every queued item matching pred without blocking (gang-aware
        intake: a count-based drain must not strand the tail of a gang in
        the queue)."""
        with self._lock:
            keys = [k for k, v in self._items.items() if pred(v)]
            return [self._items.pop(k) for k in keys]

    def requeue_front(self, obj):
        """Put a drained item back at the HEAD of the queue (give-back
        intake: returned work must not go to the tail behind younger
        arrivals, or it starves under sustained load). If a newer copy was
        queued meanwhile it wins — only its position moves."""
        key = self._key(obj)
        with self._lock:
            fresh = key not in self._items
            if fresh:
                self._items[key] = obj
            self._items.move_to_end(key, last=False)
            if fresh:
                self._lock.notify()

    def close(self):
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def __len__(self):
        with self._lock:
            return len(self._items)


class DeltaFIFO:
    """Queue of per-key delta sequences [(type, obj), ...] — consumers see
    every intermediate state (reference delta_fifo.go). Types: Added,
    Updated, Deleted, Sync."""

    ADDED = "Added"
    UPDATED = "Updated"
    DELETED = "Deleted"
    SYNC = "Sync"

    def __init__(self, key_func: Callable = meta_namespace_key):
        self._lock = threading.Condition()
        self._deltas: "OrderedDict[str, List[Tuple[str, object]]]" = OrderedDict()
        self._key = key_func
        self._known: Dict[str, object] = {}  # last state per key
        self._closed = False

    def _queue(self, dtype: str, obj, key: Optional[str] = None):
        key = key or self._key(obj)
        with self._lock:
            fresh = key not in self._deltas
            self._deltas.setdefault(key, []).append((dtype, obj))
            if dtype == DeltaFIFO.DELETED:
                self._known.pop(key, None)
            else:
                self._known[key] = obj
            if fresh:
                self._lock.notify()

    def add(self, obj):
        self._queue(DeltaFIFO.ADDED, obj)

    def update(self, obj):
        self._queue(DeltaFIFO.UPDATED, obj)

    def delete(self, obj):
        self._queue(DeltaFIFO.DELETED, obj)

    def replace(self, objs: list):
        """Full-state resync: emits Sync for live keys and Deleted for
        known keys that vanished (the reflector re-list path)."""
        new_keys = {self._key(o) for o in objs}
        with self._lock:
            vanished = [k for k in self._known if k not in new_keys]
        for o in objs:
            self._queue(DeltaFIFO.SYNC, o)
        for k in vanished:
            obj = self._known.get(k)
            if obj is not None:
                self._queue(DeltaFIFO.DELETED, obj, key=k)

    def pop(self, timeout: Optional[float] = None):
        """Block for the next (key, deltas) batch; None on timeout/close."""
        with self._lock:
            while not self._deltas:
                if self._closed or not self._lock.wait(timeout=timeout):
                    return None
            key, deltas = self._deltas.popitem(last=False)
            return key, deltas

    def close(self):
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def __len__(self):
        with self._lock:
            return len(self._deltas)
