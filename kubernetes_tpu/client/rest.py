"""Typed REST client with client-side flow control.

Parity target: reference pkg/client/restclient — QPS/burst token bucket on
every request (config.go:96-103), typed encode/decode through the scheme,
structured Status errors, and a streaming watch that yields (event_type,
object) tuples from the NDJSON frames (pkg/client/restclient/request.go Watch).
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
from typing import Iterator, Optional, Tuple
from urllib.parse import quote

from kubernetes_tpu.api import binary_codec
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict
from kubernetes_tpu.registry.generic import RESOURCES
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.flowcontrol import TokenBucket
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("restclient")


class ApiError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        self.code = code
        self.reason = reason
        self.message = message
        super().__init__(f"{code} {reason}: {message}")

    @property
    def is_not_found(self):
        return self.code == 404

    @property
    def is_conflict(self):
        return self.code == 409

    @property
    def is_gone(self):
        return self.code == 410


class WatchStream:
    """Iterator over watch frames; `stop()` closes the connection."""

    def __init__(self, conn: http.client.HTTPConnection, resp, cls,
                 binary: bool = False):
        self._conn = conn
        self._resp = resp
        self._cls = cls
        self._binary = binary
        self._stopped = False

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._resp.read(n - len(buf))
            if not chunk:
                return b""
            buf += chunk
        return buf

    def _frames(self):
        if not self._binary:
            while True:
                line = self._resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue  # heartbeat
                yield json.loads(line)
        else:
            while True:
                hdr = self._read_exact(4)
                if len(hdr) < 4:
                    return
                length = int.from_bytes(hdr, "big")
                if length == 0:
                    continue  # heartbeat frame
                payload = self._read_exact(length)
                if len(payload) < length:
                    return
                yield binary_codec.decode_dict(payload)

    def __iter__(self) -> Iterator[Tuple[str, object]]:
        try:
            for frame in self._frames():
                if self._stopped:
                    return
                if frame.get("type") == "ERROR":
                    # terminal server-side error (slow-watcher drop / 410):
                    # the object is a Status dict, not a resource
                    yield "ERROR", frame.get("object")
                    return
                obj = from_dict(self._cls, frame["object"])
                yield frame["type"], obj
        except (http.client.HTTPException, OSError, ValueError, AttributeError):
            # AttributeError: http.client raises it when the response's
            # buffered reader is torn down mid-readline by stop()
            if not self._stopped:
                raise
        finally:
            self.stop()

    def stop(self):
        self._stopped = True
        # shut down the socket first: close() would block on the reader
        # buffer's lock while another thread is parked in readline(); a
        # SHUT_RDWR makes that readline return immediately instead
        import socket as _socket
        sock = getattr(self._conn, "sock", None)
        if sock is not None:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            self._conn.close()
        except OSError:
            pass


class RESTClient:
    """One logical client per component, identified by user_agent; qps/burst
    mirror the reference's --kube-api-qps/--kube-api-burst flags."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 qps: float = 50.0, burst: int = 100,
                 user_agent: str = "kubernetes-tpu-client", timeout: float = 30.0,
                 bearer_token: str = "", basic_auth: Optional[tuple] = None,
                 content_type: str = "application/json",
                 tls: bool = False, ca_file: str = "",
                 cert_file: str = "", key_file: str = "",
                 insecure_skip_verify: bool = False):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.user_agent = user_agent
        self.bearer_token = bearer_token
        self.basic_auth = basic_auth  # (user, password)
        # application/vnd.kubernetes.protobuf selects the binary wire codec
        # (reference --kube-api-content-type; kubemark defaults to it)
        self.content_type = content_type
        # TLS client config (reference restclient.TLSClientConfig): server
        # CA for verification plus an optional client-cert identity the
        # apiserver's x509 authenticator maps to user/groups
        self.tls = tls or bool(ca_file) or bool(cert_file)
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file
        # skipping verification is an EXPLICIT opt-in, never a default: a
        # client that silently talks TLS-without-verification is
        # indistinguishable from a MITM'd one. Loud when chosen, and every
        # unverified connection ticks the tls_insecure_connections counter.
        self.insecure_skip_verify = insecure_skip_verify
        if self.tls and insecure_skip_verify:
            log.warning(
                "TLS certificate verification DISABLED for %s:%s "
                "(insecure_skip_verify=True)", host, port)
        self._limiter = TokenBucket(qps, burst)
        self._local = threading.local()

    @classmethod
    def for_server(cls, server, **kw) -> "RESTClient":
        """Client for an in-process server. A secure server implies tls=True,
        but NOT skip-verify: pass ca_file for verification or opt in to
        insecure_skip_verify=True explicitly (it is counted + warned)."""
        if getattr(server, "secure", False):
            kw.setdefault("tls", True)
        return cls(host="127.0.0.1", port=server.port, **kw)

    # --- low-level -----------------------------------------------------------

    def _ssl_context(self):
        # built once and shared: every watch reconnect would otherwise
        # re-read the CA/cert files and lose TLS session reuse
        ctx = getattr(self, "_ssl_ctx", None)
        if ctx is not None:
            return ctx
        import ssl
        ctx = ssl.create_default_context()
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.cert_file:
            ctx.load_cert_chain(self.cert_file, self.key_file or None)
        self._ssl_ctx = ctx
        return ctx

    def _new_conn(self, timeout: float) -> http.client.HTTPConnection:
        # NODELAY variants: Nagle + delayed ACK costs ~40ms on every small
        # request — see utils/nethost.py
        from kubernetes_tpu.utils.nethost import (
            NoDelayHTTPConnection, NoDelayHTTPSConnection,
        )
        if self.tls:
            if self.insecure_skip_verify:
                METRICS.inc("tls_insecure_connections")
            return NoDelayHTTPSConnection(
                self.host, self.port, timeout=timeout,
                context=self._ssl_context())
        return NoDelayHTTPConnection(self.host, self.port,
                                     timeout=timeout)

    def _conn(self) -> http.client.HTTPConnection:
        # one keep-alive connection per thread
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._new_conn(self.timeout)
            self._local.conn = conn
        return conn

    def _drop_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def request(self, method: str, path: str, body: Optional[dict] = None,
                content_type: Optional[str] = None) -> dict:
        # cross-process tracing: inside a traced context (a scheduler bind
        # span, a reflector relist chain) every request gets its own child
        # span, and _request_once stamps that span's traceparent into the
        # headers — the apiserver's request span and audit record then share
        # the caller's trace id. Untraced requests skip the span (the server
        # still mints a root trace for its audit record).
        parent = trace.current_span()
        if parent is None:
            return self._request_with_retries(method, path, body,
                                              content_type=content_type)
        sp = trace.Span(f"rest:{method}", parent=parent, path=path,
                        component=self.user_agent)
        # carry the caller's accumulated retry count (a reflector relist
        # chain counts its failed attempts on the chain span) so the server
        # can audit "this was attempt N of a retry storm"
        base = parent.attrs.get("retries", 0)
        if base:
            sp.attrs["retries"] = base
        try:
            # _request_once stamps the real HTTP status onto the span; the
            # ApiError arm covers chaos interventions that short-circuit
            # before any wire response exists
            with trace.use_span(sp):
                return self._request_with_retries(
                    method, path, body, content_type=content_type)
        except ApiError as e:
            sp.attrs["status"] = e.code
            raise
        finally:
            sp.finish()

    def _request_with_retries(self, method: str, path: str,
                              body: Optional[dict] = None,
                              content_type: Optional[str] = None) -> dict:
        # 429 = server-side max-in-flight shed the request before executing
        # it: always safe to retry after a short backoff (the reference
        # client honors Retry-After the same way)
        sp = trace.current_span()
        for attempt, backoff in enumerate((0.1, 0.4, 1.0, 2.0, None)):
            if sp is not None and attempt:
                sp.attrs["retries"] = sp.attrs.get("retries", 0) + 1
            parsed = self._request_once(method, path, body,
                                        content_type=content_type)
            if parsed.get("code") == 429 and backoff is not None:
                import time as _time
                _time.sleep(backoff)
                continue
            if parsed.get("code") == 429:
                raise ApiError(429, parsed.get("reason", "TooManyRequests"),
                               parsed.get("message", ""))
            return parsed
        raise AssertionError("unreachable")

    def _request_once(self, method: str, path: str,
                      body: Optional[dict] = None,
                      content_type: Optional[str] = None) -> dict:
        self._limiter.accept()
        binary = (self.content_type == binary_codec.CONTENT_TYPE
                  and content_type is None)
        if body is None:
            payload = None
        elif binary:
            payload = binary_codec.encode_dict(body)
        else:
            # explicit content types (patches) always travel as JSON
            payload = json.dumps(body).encode()
        headers = {"User-Agent": self.user_agent}
        if self.content_type == binary_codec.CONTENT_TYPE:
            headers["Accept"] = binary_codec.CONTENT_TYPE
        if payload is not None:
            headers["Content-Type"] = content_type or self.content_type
        self._auth_headers(headers)
        self._trace_headers(headers)
        for attempt in (1, 2):
            conn = self._conn()
            try:
                conn.request(method, path, body=payload, headers=headers)
            except (http.client.HTTPException, OSError):
                # send failed before the server saw the request (stale
                # keep-alive socket) — always safe to retry once
                self._drop_conn()
                if attempt == 2:
                    raise
                continue
            try:
                resp = conn.getresponse()
                data = resp.read()
                break
            except (http.client.HTTPException, OSError):
                # the server may have executed the request; retrying a
                # non-idempotent verb could double-apply it
                self._drop_conn()
                if method == "GET" and attempt == 1:
                    continue
                raise
        sp = trace.current_span()
        if sp is not None:
            sp.attrs["status"] = resp.status  # the wire truth, 201 included
        if not data:
            parsed = {}
        elif binary_codec.is_binary(data):
            parsed = binary_codec.decode_dict(data)
        else:
            parsed = json.loads(data)
        if resp.status == 429:
            # flow-control shed: surfaced as a dict so request() can retry
            return {"kind": "Status", "code": 429,
                    "reason": parsed.get("reason", "TooManyRequests"),
                    "message": parsed.get("message", "")}
        if resp.status >= 400:
            raise ApiError(resp.status, parsed.get("reason", "Unknown"),
                           parsed.get("message", ""))
        return parsed

    @staticmethod
    def _trace_headers(headers: dict) -> None:
        """Stamp the current span's traceparent (and retry ordinal) into the
        outgoing headers — the cross-process half of utils/trace.py."""
        sp = trace.current_span()
        if sp is None:
            return
        headers[trace.TRACEPARENT_HEADER] = trace.format_traceparent(sp)
        retries = sp.attrs.get("retries", 0)
        if retries:
            headers[trace.RETRY_HEADER] = str(int(retries))

    def _auth_headers(self, headers: dict) -> None:
        if self.bearer_token:
            headers["Authorization"] = f"Bearer {self.bearer_token}"
        elif self.basic_auth:
            import base64
            cred = base64.b64encode(
                f"{self.basic_auth[0]}:{self.basic_auth[1]}".encode()).decode()
            headers["Authorization"] = f"Basic {cred}"

    # --- paths ---------------------------------------------------------------

    @staticmethod
    def _collection_path(resource: str, namespace: str = "") -> str:
        rd = RESOURCES.get(resource)
        # group resources live under /apis/<group>/<version> (reference
        # generated clientsets carry their group in the path the same way)
        base = "/api/v1"
        if rd is not None and rd.api_version != "v1":
            base = f"/apis/{rd.api_version}"
        if rd is not None and not rd.namespaced:
            return f"{base}/{resource}"
        if namespace:
            return f"{base}/namespaces/{namespace}/{resource}"
        return f"{base}/{resource}"

    def _item_path(self, resource: str, name: str, namespace: str = "") -> str:
        return f"{self._collection_path(resource, namespace)}/{quote(name)}"

    @staticmethod
    def _query(label_selector=None, field_selector=None, **extra) -> str:
        parts = []
        if label_selector:
            parts.append("labelSelector=" + quote(str(label_selector)))
        if field_selector:
            parts.append("fieldSelector=" + quote(str(field_selector)))
        parts += [f"{k}={quote(str(v))}" for k, v in extra.items() if v is not None]
        return ("?" + "&".join(parts)) if parts else ""

    # --- typed verbs ---------------------------------------------------------

    def create(self, resource: str, obj, namespace: str = ""):
        ns = namespace or (obj.metadata.namespace if obj.metadata else "")
        d = self.request("POST", self._collection_path(resource, ns), scheme.encode(obj))
        return from_dict(RESOURCES[resource].cls, d)

    def get(self, resource: str, name: str, namespace: str = ""):
        d = self.request("GET", self._item_path(resource, name, namespace))
        return from_dict(RESOURCES[resource].cls, d)

    def list(self, resource: str, namespace: str = "",
             label_selector=None, field_selector=None):
        """Returns (items, list_resource_version)."""
        path = self._collection_path(resource, namespace) + self._query(
            label_selector, field_selector)
        d = self.request("GET", path)
        cls = RESOURCES[resource].cls
        items = [from_dict(cls, i) for i in d.get("items", [])]
        return items, int(d.get("metadata", {}).get("resourceVersion", "0"))

    def update(self, resource: str, obj, namespace: str = ""):
        ns = namespace or (obj.metadata.namespace if obj.metadata else "")
        d = self.request("PUT", self._item_path(resource, obj.metadata.name, ns),
                         scheme.encode(obj))
        return from_dict(RESOURCES[resource].cls, d)

    def update_status(self, resource: str, obj, namespace: str = ""):
        ns = namespace or (obj.metadata.namespace if obj.metadata else "")
        d = self.request("PUT",
                         self._item_path(resource, obj.metadata.name, ns) + "/status",
                         scheme.encode(obj))
        return from_dict(RESOURCES[resource].cls, d)

    # patch content types (reference pkg/api/types.go PatchType)
    from kubernetes_tpu.utils.strategicpatch import (
        MERGE_PATCH_TYPE as MERGE_PATCH,
        STRATEGIC_PATCH_TYPE as STRATEGIC_PATCH,
    )

    def patch(self, resource: str, name: str, patch: dict, namespace: str = "",
              subresource: str = "", patch_type: str = STRATEGIC_PATCH):
        """Server-side PATCH (resthandler.go:503-615): the server merges and
        retries conflicts, so concurrent writers of disjoint fields — label
        PATCH vs status PATCH — both land without a read-modify-write race
        on the client."""
        path = self._item_path(resource, name, namespace)
        if subresource:
            path += f"/{subresource}"
        d = self.request("PATCH", path, patch, content_type=patch_type)
        return from_dict(RESOURCES[resource].cls, d)

    def patch_status(self, resource: str, name: str, patch: dict,
                     namespace: str = ""):
        return self.patch(resource, name, patch, namespace,
                          subresource="status")

    def delete(self, resource: str, name: str, namespace: str = ""):
        d = self.request("DELETE", self._item_path(resource, name, namespace))
        return from_dict(RESOURCES[resource].cls, d)

    def bind(self, binding: api.Binding, namespace: str):
        """The scheduler's single write (reference factory.go:563-570)."""
        self.request("POST", f"/api/v1/namespaces/{namespace}/bindings",
                     scheme.encode(binding))

    def get_scale(self, resource: str, name: str, namespace: str = ""):
        from kubernetes_tpu.apis import extensions as ext
        d = self.request("GET", self._item_path(resource, name, namespace) + "/scale")
        return from_dict(ext.Scale, d)

    def update_scale(self, resource: str, name: str, namespace: str, scale):
        from kubernetes_tpu.apis import extensions as ext
        d = self.request("PUT", self._item_path(resource, name, namespace) + "/scale",
                         scheme.encode(scale))
        return from_dict(ext.Scale, d)

    def rollback_deployment(self, name: str, namespace: str, rollback):
        self.request("POST",
                     self._item_path("deployments", name, namespace) + "/rollback",
                     scheme.encode(rollback))

    def watch(self, resource: str, namespace: str = "", resource_version=None,
              label_selector=None, field_selector=None) -> WatchStream:
        """Open a streaming watch. Not rate-limited (watches are long-lived;
        the reference also exempts them)."""
        path = self._collection_path(resource, namespace) + self._query(
            label_selector, field_selector, watch="true",
            resourceVersion=resource_version)
        binary = self.content_type == binary_codec.CONTENT_TYPE
        conn = self._new_conn(self.timeout + 35)
        headers = {"User-Agent": self.user_agent}
        if binary:
            headers["Accept"] = binary_codec.CONTENT_TYPE
        self._auth_headers(headers)
        self._trace_headers(headers)
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        if resp.status >= 400:
            data = resp.read()
            if not data:
                parsed = {}
            elif binary_codec.is_binary(data):
                parsed = binary_codec.decode_dict(data)
            else:
                parsed = json.loads(data)
            conn.close()
            raise ApiError(resp.status, parsed.get("reason", "Unknown"),
                           parsed.get("message", ""))
        return WatchStream(conn, resp, RESOURCES[resource].cls, binary=binary)
