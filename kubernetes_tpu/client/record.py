"""Event recording — compatibility shim.

The recorder moved to utils/events.py when it grew the reference's full
correlation stack (aggregation + spam filter, events_cache.go); every
existing `from kubernetes_tpu.client.record import EventRecorder` keeps
working through this re-export.
"""

from kubernetes_tpu.utils.events import (  # noqa: F401
    AGGREGATED_PREFIX, MAX_AGGREGATION_ENTRIES, EventCorrelator, EventRecorder,
)
