"""Event recording with client-side aggregation.

Parity target: reference pkg/client/record — EventRecorder/EventBroadcaster
(event.go:96,112) and the dedup/aggregation cache (events_cache.go:69-75):
repeats of the same (object, reason, message) become a count bump via PUT
instead of a new Event object, which is the spam control that keeps 5k-node
clusters from melting the API server with "FailedScheduling" storms.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso

log = logging.getLogger("events")

# aggregation cache cap (the reference's events_cache LRU analogue)
MAX_AGGREGATION_ENTRIES = 4096


class EventRecorder:
    """`event(obj, type, reason, message)` — async fire-and-forget like the
    reference broadcaster (a blocked event sink must never stall the
    scheduler loop)."""

    def __init__(self, client: RESTClient, source_component: str,
                 source_host: str = ""):
        self.client = client
        self.source = api.EventSource(component=source_component, host=source_host)
        # agg key -> (event name, count); LRU-capped so long-running
        # components don't grow without bound
        self._seen: "OrderedDict[Tuple, Tuple[str, int]]" = OrderedDict()
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._pump, name="event-recorder",
                                        daemon=True)
        self._started = False
        self._lock = threading.Lock()

    def event(self, obj, etype: str, reason: str, message: str):
        with self._lock:
            if not self._started:
                self._thread.start()
                self._started = True
        self._q.put((obj, etype, reason, message))

    def flush(self, timeout: float = 5.0):
        """Best-effort wait for queued events to be posted (tests)."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def _pump(self):
        while True:
            obj, etype, reason, message = self._q.get()
            try:
                self._record(obj, etype, reason, message)
            except Exception as e:
                log.warning("event post failed: %s", e)

    def _record(self, obj, etype: str, reason: str, message: str):
        meta = obj.metadata
        ref = api.ObjectReference(
            kind=type(obj).__name__, namespace=meta.namespace, name=meta.name,
            uid=meta.uid, resource_version=meta.resource_version)
        agg_key = (ref.kind, ref.namespace, ref.name, etype, reason, message)
        ns = meta.namespace or "default"
        existing = self._seen.get(agg_key)
        if existing is not None:
            name, count = existing
            try:
                ev = self.client.get("events", name, ns)
                ev.count = count + 1
                ev.last_timestamp = _now_iso()
                self.client.update("events", ev, ns)
                self._seen[agg_key] = (name, count + 1)
                self._seen.move_to_end(agg_key)
                return
            except ApiError:
                pass  # fall through to create
        now = _now_iso()
        name = f"{meta.name}.{int(time.time() * 1e6):x}"
        ev = api.Event(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            involved_object=ref, reason=reason, message=message,
            source=self.source, type=etype,
            first_timestamp=now, last_timestamp=now, count=1)
        self.client.create("events", ev, ns)
        self._seen[agg_key] = (name, 1)
        self._seen.move_to_end(agg_key)
        while len(self._seen) > MAX_AGGREGATION_ENTRIES:
            self._seen.popitem(last=False)
